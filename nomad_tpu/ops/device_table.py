"""Device-resident node table: the dense columns pinned on device,
maintained by incremental scatter deltas.

BENCH_r05 showed the system host-bound AROUND the kernel (163.8k
placements/s in-kernel vs 12.3k e2e): every eval re-shipped the full
(N, D) capacity/used columns to the device — at 50k nodes that is two
~800 KB H2D transfers per dispatch, each a tunnel op on a remote TPU.
This module keeps ONE device copy per NodeTableCache and advances it
with batched row scatters:

  - `capacity` is immutable per node-set epoch: uploaded once, reused
    by every dispatch until a node registration/status flip rebuilds
    the host table (epoch bump -> fresh upload).
  - `used` / `free_ports` advance by `.at[rows].set(new_rows)` — the
    rows a plan apply touched, shipped as (idx, values) pairs instead
    of the whole column. `.set` (not `.add`) with the host-computed
    values makes the mirror bit-identical to the host shadow by
    construction: no float-order concerns, and parity is checkable row
    for row.
  - per-eval plan overlays (`ProposedIndex.plan_delta`) apply on
    device as a sparse `.at[rows].add(deltas)` over the resident
    `used`, so the kernel's `used0` never crosses the bus densely.

MVCC: the mirror tracks ONE version — the cache's latest. Every
NodeTable version carries a (mirror, version) token; a kernel dispatch
uses the device arrays only when the token still matches, otherwise it
falls back to shipping dense columns (stale snapshots pay, the steady
state doesn't). Scatter dispatches are ASYNC (jax's deferred
execution): the cache never blocks on them, so the device applies
table deltas while the host builds the next eval's masks — the
double-buffered delta application of the pipelined worker loop.

Delta debt + fold-to-rebuild: every scatter pads its row block to a
power-of-two bucket (bounds XLA recompiles) and appends device work;
the cumulative scattered-row count since the last full upload is the
mirror's *delta debt*. When debt crosses the governor watermark, one
contiguous re-upload (`fold`) is cheaper than the scatter history it
replaces — the reclaim policy registered in nomad_tpu/governor/.

`NOMAD_TPU_TABLE_DELTA=0` disables both the host delta path and this
mirror (every refresh becomes a cold rebuild) — the bisection escape
hatch.
"""

from __future__ import annotations

import os
import time as _time
from typing import Dict, List, Optional, Tuple

import numpy as np
from ..utils.locks import make_lock

TABLE_DELTA_ENV = "NOMAD_TPU_TABLE_DELTA"

# overlay/scatter row blocks above this fraction of the table fall back
# to dense shipping — scattering most of the table costs more than one
# contiguous transfer
SPARSE_MAX_FRAC = 0.5
DELTA_LOG_MAX = 256
# widest delta worth journaling row indices for: a companion mirror
# re-uploads contiguously past this anyway (SPARSE_MAX_FRAC), so wider
# entries journal a None sentinel instead of pinning huge index arrays
JOURNAL_ROWS_MAX = 16384

# row-index journaling engages only once a companion mirror exists
# (the mesh-sharded resident table registers itself on construction);
# a single-chip deployment never pays the index-array memory — its
# journal entries carry None sentinels, which any late-arriving
# companion reads as a gap (one re-upload, then arrays flow)
_ROW_JOURNAL = False


def enable_row_journal() -> None:
    global _ROW_JOURNAL
    _ROW_JOURNAL = True


def delta_enabled() -> bool:
    """The bisection escape hatch: NOMAD_TPU_TABLE_DELTA=0 forces the
    old rebuild-per-refresh path (host and device alike)."""
    return os.environ.get(TABLE_DELTA_ENV, "1") not in ("0", "off", "no")


def _pad_n(n: int) -> int:
    # kept in lockstep with ops/select._pad_n (the kernel's node-axis
    # padding rule); duplicated to keep this module import-light
    p = 8
    while p < n:
        p *= 2
    return p


def _bucket_rows(m: int) -> int:
    b = 8
    while b < m:
        b *= 2
    return b


class DeviceTableState:
    """Immutable snapshot of the mirror's device arrays. Readers grab
    one reference and use it without locking; scatter updates replace
    the whole state object, never mutate it (jax arrays are functional
    anyway — this just makes the version/array pairing atomic)."""

    __slots__ = ("version", "epoch", "n", "n_pad", "capacity", "used",
                 "free_ports")

    def __init__(self, version: int, epoch: int, n: int, n_pad: int,
                 capacity, used, free_ports):
        self.version = version
        self.epoch = epoch
        self.n = n
        self.n_pad = n_pad
        self.capacity = capacity
        self.used = used
        self.free_ports = free_ports


FEAS_ENTRIES_MAX = 64


class FeasMaskStore:
    """Device-resident combined feasibility masks (ISSUE 17).

    One per mirror, keyed by the stack's feasibility cache key. Entries
    are versioned by the node-attr index (ids_epoch, version) — the
    authority on WHICH nodes the mask covers and WHEN it was last
    correct — not by the mirror's own version, which advances on alloc
    deltas that don't touch feasibility. `put` uploads the full padded
    mask on first sight / epoch change and row-scatters on incremental
    attr updates; `resident` hands the array to the dispatch only when
    the request's token still names the entry exactly."""

    def __init__(self):
        self._l = make_lock()
        # feas_key -> {"arr", "n", "n_pad", "epoch", "version"}
        self._entries: Dict[object, dict] = {}
        # rows scattered atop parked masks by per-eval residue
        # (ISSUE 20) since the last fold/reset — the governor's
        # feas.residue_rows watermark; fold() zeroes it
        self.residue_debt = 0
        self.stats: Dict[str, int] = {
            "uploads": 0, "scatters": 0, "hits": 0, "stale": 0,
            "residue_scatters": 0, "residue_rows": 0, "folds": 0,
        }

    def peek(self, key) -> Optional[Tuple[int, int]]:
        """(ids_epoch, version) of the resident entry, or None. The
        compiler uses this to journal only the rows changed since."""
        with self._l:
            e = self._entries.get(key)
            return None if e is None else (e["epoch"], e["version"])

    def put(self, key, mask: np.ndarray, epoch: int, version: int,
            rows) -> Optional[Tuple]:
        """Park `mask` (table-space bool[n]) on device and return the
        token (key, epoch, version, n) a request attaches to dispatch
        against it, or None if the upload failed. `rows` — table rows
        changed since this entry's previous version within the same
        epoch — selects the jitted row-scatter patch over the full
        upload; None forces the upload."""
        n = len(mask)
        n_pad = _pad_n(n)
        tok = (key, epoch, version, n)
        # snapshot the decision inputs under the lock; the device work
        # (upload or jitted scatter) runs OUTSIDE it — parking a mask
        # must not serialize concurrent readers behind a dispatch
        with self._l:
            e = self._entries.get(key)
            if e is not None and e["epoch"] == epoch \
                    and e["version"] == version and e["n"] == n:
                return tok  # already current
            patchable = (
                e is not None and e["epoch"] == epoch
                and e["n"] == n and rows is not None
                and len(rows) <= n * SPARSE_MAX_FRAC)
            base = e["arr"] if patchable else None
            base_ver = e["version"] if patchable else None
        kind = "none"
        try:
            if patchable and len(rows) == 0:
                # version advanced but no row's verdict context
                # changed: stamp the entry, no device work
                arr = base
            elif patchable:
                idx = np.fromiter(rows, np.int32, len(rows))
                b = _bucket_rows(len(idx))
                if b > len(idx):
                    # pad with a repeat of the first row: duplicate
                    # `.set` indices land the same value, harmless
                    idx = np.concatenate(
                        [idx, np.full(b - len(idx), idx[0],
                                      np.int32)])
                arr = _feas_scatter(base, idx, mask[idx].astype(bool))
                kind = "scatters"
            else:
                padded = np.zeros(n_pad, bool)
                padded[:n] = mask
                import jax
                arr = jax.device_put(padded)
                kind = "uploads"
        except Exception:
            return None
        with self._l:
            if patchable:
                # a concurrent put moved the entry while we patched its
                # snapshot: our base is stale, drop this park (the next
                # eval re-parks from its own fresher mask)
                e2 = self._entries.get(key)
                if e2 is None or e2["version"] != base_ver \
                        or e2["epoch"] != epoch:
                    return None
            if kind != "none":
                self.stats[kind] += 1
            self._entries[key] = {"arr": arr, "n": n, "n_pad": n_pad,
                                  "epoch": epoch, "version": version}
            while len(self._entries) > FEAS_ENTRIES_MAX:
                self._entries.pop(next(iter(self._entries)))
            return tok

    def resident(self, token, n_pad: int):
        """The device array for `token`, or None when the entry moved
        on (or the kernel's padding disagrees) — caller falls back to
        packing the host mask."""
        if token is None:
            return None
        key, epoch, version, n = token
        with self._l:
            e = self._entries.get(key)
            if e is None or e["epoch"] != epoch \
                    or e["version"] != version or e["n_pad"] != n_pad:
                self.stats["stale"] += 1
                return None
            self.stats["hits"] += 1
            return e["arr"]

    def apply_residue(self, arr, rows: np.ndarray, vals: np.ndarray):
        """Reproduce the host mask's residue mutations (CSI claims,
        quota caps, preferred-node restriction) on the parked device
        mask with ONE jitted row-scatter — per-eval, never stored, so
        the resident entry itself stays the pre-residue combined mask
        and the token keeps surviving. Returns the scattered array or
        None (caller falls back to packing the host mask)."""
        m = len(rows)
        if m == 0:
            return arr
        try:
            idx = np.asarray(rows, dtype=np.int32)
            v = np.asarray(vals, dtype=bool)
            b = _bucket_rows(m)
            if b > m:
                # pad with a repeat of the first row: duplicate `.set`
                # indices land the same value, harmless
                idx = np.concatenate(
                    [idx, np.full(b - m, idx[0], np.int32)])
                v = np.concatenate([v, np.full(b - m, v[0], bool)])
            out = _feas_scatter(arr, idx, v)
        except Exception:
            return None
        with self._l:
            self.stats["residue_scatters"] += 1
            self.stats["residue_rows"] += m
            self.residue_debt += m
        return out

    def fold(self) -> dict:
        """Governor reclaim (governor_feas_residue_high): drop the
        parked entries and zero the residue debt — the next eval
        re-parks a fresh combined mask instead of compounding scatter
        work atop a long-lived base."""
        with self._l:
            dropped = len(self._entries)
            self._entries.clear()
            debt = self.residue_debt
            self.residue_debt = 0
            self.stats["folds"] += 1
        return {"feas_entries_dropped": dropped,
                "residue_debt_cleared": debt}

    def debt(self) -> int:
        with self._l:
            return self.residue_debt

    def snapshot(self) -> dict:
        with self._l:
            return {"entries": len(self._entries),
                    "residue_debt": self.residue_debt, **self.stats}


class DeviceNodeTable:
    """The device-resident mirror one NodeTableCache owns.

    Lazy: holds no device memory (and triggers no jax init) until a
    kernel first asks for arrays via `arrays_for`. Until then,
    `note_delta`/`note_rebuild` just advance the version counter so a
    later materialization starts from the right table."""

    def __init__(self):
        self._l = make_lock()
        self._state: Optional[DeviceTableState] = None
        self.version = 0            # latest host table version (token)
        self.epoch = 0              # node-set generation
        self.delta_debt = 0         # rows scattered since last upload
        # replay journal: (version, touched-row indices) per delta,
        # recorded whether or not THIS mirror is materialized — a
        # companion mirror on another device topology (the mesh-sharded
        # resident table, parallel/sharded_table.py) catches its copy
        # up by scatter-setting the union of journaled rows from the
        # latest host table (`.set` with host values makes replay
        # order-free and idempotent). Bounded ring: a companion that
        # fell further behind than DELTA_LOG_MAX entries re-uploads.
        self.delta_log: List[Tuple[int, np.ndarray]] = []
        self.stats: Dict[str, int] = {
            "uploads": 0, "scatters": 0, "folds": 0,
            "overlay_dispatches": 0, "stale_misses": 0,
        }
        # device-resident compiled feasibility masks (ISSUE 17): keyed
        # by the stack's feas cache key, versioned by the attr index —
        # deliberately NOT by this mirror's version/epoch, because node
        # attribute changes and alloc deltas advance independently
        self.feas = FeasMaskStore()

    # -- cache-side bookkeeping (called under the cache's lock) --------
    def note_rebuild(self) -> int:
        """A node-set rebuild invalidated the columns: bump the epoch,
        drop the device arrays (re-materialized lazily from the new
        table), return the new version token."""
        with self._l:
            self.epoch += 1
            self.version += 1
            self._state = None
            self.delta_debt = 0
            self.delta_log.clear()
            return self.version

    def note_delta(self, table, rows) -> int:
        """Advance the mirror past an alloc-delta refresh: `rows` are
        the host-table indices the refresh touched. When materialized,
        dispatch the row scatter asynchronously (no block — the device
        chews it while the host moves on); otherwise only the version
        advances. Returns the new version token."""
        with self._l:
            self.version += 1
            # journal the touched rows even while lazy: companion
            # mirrors (the mesh-sharded resident table) replay them.
            # Wide deltas journal a sentinel — replaying them would
            # cost more than the contiguous re-upload they force — and
            # without a registered companion no index arrays are built
            self.delta_log.append(
                (self.version,
                 np.fromiter(rows, np.int32, len(rows))
                 if _ROW_JOURNAL and len(rows) <= JOURNAL_ROWS_MAX
                 else None))
            if len(self.delta_log) > DELTA_LOG_MAX:
                del self.delta_log[:len(self.delta_log) - DELTA_LOG_MAX]
            st = self._state
            if st is None:
                return self.version
            if rows:
                try:
                    # nomad-lint: allow[lock-discipline] scatter stays under _l to pair arrays with the version token; jax dispatch is async (never blocks)
                    st = self._scatter(st, table, rows)
                except Exception:   # pragma: no cover — defensive:
                    # a failed device op must not poison scheduling;
                    # drop the mirror, dense fallback takes over
                    st = None
                    self.stats["stale_misses"] += 1
            if st is not None:
                st = DeviceTableState(self.version, self.epoch, st.n,
                                      st.n_pad, st.capacity, st.used,
                                      st.free_ports)
            self._state = st
            return self.version

    def deltas_since(self, version: int) -> Optional[List[Tuple[int,
                                                                np.ndarray]]]:
        """The journal entries bridging (version, self.version], or None
        when the journal can't (caller re-uploads): the gap predates the
        retained ring, a rebuild cleared the log, or a bridging entry
        was too wide to journal (sentinel)."""
        with self._l:
            if version > self.version:
                return None
            if version == self.version:
                return []
            need = self.version - version
            ent = [e for e in self.delta_log if e[0] > version]
            if len(ent) != need or any(r is None for _v, r in ent):
                return None
            return ent

    def _scatter(self, st: DeviceTableState, table,
                 rows) -> DeviceTableState:
        import jax

        m = len(rows)
        if m > st.n * SPARSE_MAX_FRAC:
            # wide delta: one contiguous upload beats a scatter of most
            # of the table (counts as a fold, resets the debt)
            return self._upload(table, epoch=st.epoch, fold=True)
        idx = np.fromiter(rows, np.int32, m)
        from ..analysis import sanitizer
        if sanitizer.enabled():
            # OOB guard BEFORE padding: on TPU `.at[rows]` silently
            # drops out-of-range rows — the corruption would be mute
            sanitizer.check_rows("device_table.scatter", idx, st.n)
        b = _bucket_rows(m)
        if b > m:
            # pad with repeats of the first row carrying its own value:
            # duplicate .set with an identical payload is deterministic
            idx = np.concatenate([idx, np.full(b - m, idx[0], np.int32)])
        from ..utils import stages

        t0 = _time.perf_counter() if stages.enabled else 0.0
        used_rows = table.base_used[idx].astype(np.float32)
        port_rows = table.free_ports[idx].astype(np.float32)
        if sanitizer.enabled():
            sanitizer.check_finite("device_table.scatter",
                                   used_rows=used_rows,
                                   port_rows=port_rows)
        used, ports = _scatter_set(st.used, st.free_ports, idx,
                                   used_rows, port_rows)
        if stages.enabled:
            # dispatch cost only — the scatter itself is async; the
            # interesting signal is rows shipped vs a dense column
            stages.add("h2d", _time.perf_counter() - t0)
        self.delta_debt += m
        self.stats["scatters"] += 1
        del jax  # imported for the side effect of a clear failure mode
        return DeviceTableState(st.version, st.epoch, st.n, st.n_pad,
                                st.capacity, used, ports)

    def _upload(self, table, epoch: int, fold: bool) -> DeviceTableState:
        import jax

        from ..utils import stages

        t0 = _time.perf_counter() if stages.enabled else 0.0
        n = table.n
        n_pad = _pad_n(n)
        d = table.base_used.shape[1]
        cap = np.zeros((n_pad, d), np.float32)
        cap[:n] = table.capacity
        used = np.zeros((n_pad, d), np.float32)
        used[:n] = table.base_used
        ports = np.zeros(n_pad, np.float32)
        ports[:n] = table.free_ports
        st = DeviceTableState(self.version, epoch, n, n_pad,
                              jax.device_put(cap), jax.device_put(used),
                              jax.device_put(ports))
        if stages.enabled:
            stages.add("h2d", _time.perf_counter() - t0)
        # the journal (delta_log) survives uploads on purpose: it is
        # the companion mirrors' replay record, not this mirror's
        # scatter history — only a node-set rebuild invalidates it
        self.delta_debt = 0
        self.stats["folds" if fold else "uploads"] += 1
        return st

    def fold(self, table, version: Optional[int] = None) -> dict:
        """Governor reclaim (fold-to-rebuild): replace the scatter
        history with one contiguous re-upload from the current host
        table. `table` must be the version the mirror tracks (the
        cache passes its latest). No-op when never materialized."""
        with self._l:
            if version is not None and version != self.version:
                return {"folded": False, "reason": "stale table"}
            debt = self.delta_debt
            if self._state is None:
                self.delta_debt = 0
                return {"folded": False, "reason": "not materialized"}
            # nomad-lint: allow[lock-discipline] upload must be atomic with the version token; jax dispatch is async (never blocks under _l)
            self._state = self._upload(table, epoch=self.epoch,
                                       fold=True)
            return {"folded": True, "debt_cleared": debt}

    # -- kernel-side access --------------------------------------------
    def arrays_for(self, table) -> Optional[DeviceTableState]:
        """The device arrays for `table`, or None when the mirror has
        moved past it (stale snapshot -> dense fallback). First valid
        call materializes the mirror from this table (full upload)."""
        token = getattr(table, "device_version", -1)
        with self._l:
            if token != self.version:
                self.stats["stale_misses"] += 1
                return None
            st = self._state
            if st is None:
                try:
                    # nomad-lint: allow[lock-discipline] lazy materialization must pair arrays with the version token; dispatch is async
                    st = self._upload(table, epoch=self.epoch,
                                      fold=False)
                except Exception:   # pragma: no cover — defensive
                    return None
                self._state = st
            return st

    def overlay_used(self, st: DeviceTableState, rows: np.ndarray,
                     deltas: np.ndarray):
        """used0 = resident used + sparse per-eval plan overlay,
        computed on device. Returns a device array (async), or None
        when the overlay is too dense to be worth scattering."""
        m = len(rows)
        if m == 0:
            return st.used
        if m > st.n * SPARSE_MAX_FRAC:
            return None
        idx = np.asarray(rows, np.int32)
        vals = np.asarray(deltas, np.float32)
        from ..analysis import sanitizer
        if sanitizer.enabled():
            sanitizer.check_rows("device_table.overlay", idx, st.n)
            sanitizer.check_finite("device_table.overlay", deltas=vals)
        b = _bucket_rows(m)
        if b > m:
            idx = np.concatenate([idx, np.zeros(b - m, np.int32)])
            vals = np.concatenate(
                [vals, np.zeros((b - m, vals.shape[1]), np.float32)])
        self.stats["overlay_dispatches"] += 1
        return _overlay_add(st.used, idx, vals)

    # -- governor accounting -------------------------------------------
    def debt(self) -> int:
        return self.delta_debt

    def log_len(self) -> int:
        return len(self.delta_log)

    def device_bytes(self) -> int:
        """Bytes the materialized mirror pins on device (capacity +
        used + free_ports buffer sizes; 0 while lazy). Shape metadata
        only — reading .nbytes never syncs the device."""
        with self._l:
            st = self._state
        if st is None:
            return 0
        total = 0
        for arr in (st.capacity, st.used, st.free_ports):
            total += int(getattr(arr, "nbytes", 0))
        return total

    def snapshot(self) -> dict:
        with self._l:
            return {"version": self.version, "epoch": self.epoch,
                    "materialized": self._state is not None,
                    "delta_debt": self.delta_debt,
                    "delta_log": len(self.delta_log), **self.stats}


def resident_request_args(mirror, req, n_pad: int,
                          metric_prefix: str) -> Optional[dict]:
    """Resident replacements for a request's table-shaped kernel inputs
    (capacity, used0, free_ports), shared by the single-device mirror
    (SelectKernel._resident_args) and the mesh-sharded one
    (ShardedSelect.resident_args) — ONE place owns the MVCC gate, the
    overlay fallback, and the free_ports identity rule. `mirror` is
    anything exposing arrays_for/overlay_used. Returns None for stale
    tables, shape mismatches, or overlays too wide to scatter, counting
    `<metric_prefix>_fallback` / `<metric_prefix>_dispatch`."""
    t = req.table
    if t is None or req.used_base_rows is None:
        return None
    from ..utils import metrics
    state = mirror.arrays_for(t)
    if state is None or state.n_pad != n_pad:
        metrics.incr_counter(metric_prefix + "_fallback")
        return None
    used0 = mirror.overlay_used(state, req.used_base_rows,
                                req.used_base_deltas)
    if used0 is None:
        metrics.incr_counter(metric_prefix + "_fallback")
        return None
    out = {"capacity": state.capacity, "used0": used0}
    if req.free_ports is not None and \
            req.free_ports is getattr(t, "free_ports", None):
        out["free_ports"] = state.free_ports
    feas = getattr(mirror, "feas", None)
    tok = getattr(req, "feas_token", None)
    if feas is not None and tok is not None:
        arr = feas.resident(tok, n_pad)
        if arr is not None:
            res = getattr(req, "feas_residue", None)
            if res is not None and len(res[0]):
                # ISSUE 20: the token survived residue mutations —
                # re-apply them on device as one sparse scatter
                # instead of re-uploading the combined mask
                arr = feas.apply_residue(arr, res[0], res[1])
                if arr is not None:
                    metrics.incr_counter(metric_prefix + "_feas_residue")
            if arr is not None:
                out["feasible"] = arr
                metrics.incr_counter(metric_prefix + "_feas_resident")
    metrics.incr_counter(metric_prefix + "_dispatch")
    return out


# jitted scatter kernels: compiled per (n_pad, row-bucket) shape — both
# axes are power-of-two bucketed, so the compile count stays bounded
_JIT_CACHE: Dict[str, object] = {}


def _jit(name: str, fn):
    import jax

    hit = _JIT_CACHE.get(name)
    if hit is None:
        hit = jax.jit(fn)
        _JIT_CACHE[name] = hit
    return hit


def _scatter_set(used, ports, idx, used_rows, port_rows):
    from ..analysis.sanitizer import traces
    traces.note("scatter_set", (tuple(used.shape), len(idx)))
    def fn(u, p, i, ur, pr):
        return u.at[i].set(ur), p.at[i].set(pr)
    return _jit("scatter_set", fn)(used, ports, idx, used_rows,
                                   port_rows)


def _overlay_add(used, idx, vals):
    from ..analysis.sanitizer import traces
    traces.note("overlay_add", (tuple(used.shape), len(idx)))
    def fn(u, i, v):
        return u.at[i].add(v)
    return _jit("overlay_add", fn)(used, idx, vals)


def _feas_scatter(mask, idx, vals):
    from ..analysis.sanitizer import traces
    traces.note("feas_scatter", (tuple(mask.shape), len(idx)))
    def fn(m, i, v):
        return m.at[i].set(v)
    return _jit("feas_scatter", fn)(mask, idx, vals)
