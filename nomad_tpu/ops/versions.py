"""Version parsing and constraint matching.

Reference behavior: scheduler/feasible.go checkVersionMatch uses
hashicorp/go-version (lenient) for the "version" operand and a strict
semver mode for "semver" (feasible.go newVersionConstraintParser /
newSemverConstraintParser). We implement the subset of both actually
used by constraints: comparison operators =, !=, >, >=, <, <=, ~>
(pessimistic), comma-separated conjunctions.
"""

from __future__ import annotations

import re
from typing import List, Optional, Tuple

_VERSION_RE = re.compile(
    r"^v?(\d+(?:\.\d+)*)(?:-([0-9A-Za-z.-]+))?(?:\+([0-9A-Za-z.-]+))?$")


class Version:
    __slots__ = ("segments", "prerelease", "orig_len")

    def __init__(self, segments: Tuple[int, ...], prerelease: str,
                 orig_len: int = 0):
        self.segments = segments
        self.prerelease = prerelease
        # segment count as written: "~> 1.0" and "~> 1.0.0" differ in
        # which segment the pessimistic operator bumps (go-version)
        self.orig_len = orig_len or len(segments)

    @classmethod
    def parse(cls, s: str) -> Optional["Version"]:
        m = _VERSION_RE.match(s.strip())
        if not m:
            return None
        raw = tuple(int(p) for p in m.group(1).split("."))
        # normalize to 3 segments for comparison (go-version pads)
        segs = raw + (0,) * (3 - len(raw)) if len(raw) < 3 else raw
        return cls(segs, m.group(2) or "", orig_len=len(raw))

    def _cmp_key(self):
        # a prerelease sorts before the release itself
        return (self.segments, 0 if self.prerelease == "" else -1,
                self.prerelease)

    def compare(self, other: "Version") -> int:
        a, b = self.segments, other.segments
        if a != b:
            return -1 if a < b else 1
        if self.prerelease == other.prerelease:
            return 0
        if self.prerelease == "":
            return 1
        if other.prerelease == "":
            return -1
        return -1 if self.prerelease < other.prerelease else 1


_CONSTRAINT_RE = re.compile(r"^\s*(~>|>=|<=|!=|=|>|<)?\s*(.+?)\s*$")


def parse_constraints(spec: str) -> Optional[List[Tuple[str, Version]]]:
    out = []
    for part in spec.split(","):
        m = _CONSTRAINT_RE.match(part)
        if not m or not m.group(2):
            return None
        op = m.group(1) or "="
        v = Version.parse(m.group(2))
        if v is None:
            return None
        out.append((op, v))
    return out


def _check_one(op: str, have: Version, want: Version) -> bool:
    c = have.compare(want)
    if op == "=":
        return c == 0
    if op == "!=":
        return c != 0
    if op == ">":
        return c > 0
    if op == ">=":
        return c >= 0
    if op == "<":
        return c < 0
    if op == "<=":
        return c <= 0
    if op == "~>":
        # pessimistic, keyed on the constraint's WRITTEN precision
        # (go-version): "~> 1.0" = >= 1.0, < 2.0; "~> 1.0.0" =
        # >= 1.0.0, < 1.1.0
        if c < 0:
            return False
        bump = max(want.orig_len - 2, 0)
        upper = want.segments[:bump] + (want.segments[bump] + 1,)
        return (have.segments[:bump] == upper[:bump]
                and have.segments[bump] < upper[bump])
    return False


def version_matches(version_str, constraint_str: str,
                    strict_semver: bool = False) -> bool:
    # attribute values may be ints/floats (feasible.go converts
    # non-string types before parsing)
    version_str = str(version_str)
    v = Version.parse(version_str)
    if v is None:
        return False
    if strict_semver and not re.match(r"^\d+\.\d+\.\d+(-|\+|$)", version_str.strip()):
        return False
    constraints = parse_constraints(constraint_str)
    if constraints is None:
        return False
    if strict_semver and any(op == "~>" for op, _ in constraints):
        # the strict semver parser has no pessimistic operator
        # (feasible.go newSemverConstraintParser)
        return False
    if not strict_semver and v.prerelease:
        # go-version: a prerelease version only matches constraint
        # parts whose own version carries a prerelease AND shares the
        # same Major.Minor.Patch core ("Prerelease X.Y.Z must match",
        # feasible_test.go:917 table)
        def core(x):
            return (tuple(x.segments[:3]) + (0, 0, 0))[:3]
        for _op, want in constraints:
            if want.prerelease == "":
                return False
            if core(v) != core(want):
                return False
    return all(_check_one(op, v, want) for op, want in constraints)
