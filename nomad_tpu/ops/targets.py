"""Vectorized constraint/affinity evaluation over the node axis.

This is the columnar rewrite of scheduler/feasible.go's per-node
checkConstraint (:750-785) and resolveTarget (:713): a constraint
becomes one bool[N] mask over the whole node table. Non-tensorizable
operands (regexp, version, semver, set_contains) are evaluated once per
*distinct attribute value* and broadcast through an inverse index —
nodes overwhelmingly share attribute values (that's why the reference's
computed-class memoization works, feasible.go:1026-1118), so this does
O(distinct) expensive checks instead of O(N).
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..models.job import (
    CONSTRAINT_DISTINCT_HOSTS, CONSTRAINT_DISTINCT_PROPERTY,
    CONSTRAINT_IS_NOT_SET, CONSTRAINT_IS_SET, CONSTRAINT_REGEX,
    CONSTRAINT_SEMVER, CONSTRAINT_SET_CONTAINS, CONSTRAINT_SET_CONTAINS_ALL,
    CONSTRAINT_SET_CONTAINS_ANY, CONSTRAINT_VERSION,
)
from .versions import version_matches


class TargetColumns:
    """Resolves constraint targets to (values, found) columns over nodes,
    with caching. Values are numpy object arrays of str (or None)."""

    def __init__(self, nodes: List):
        self.nodes = nodes
        self.n = len(nodes)
        self._cache: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}

    def resolve(self, target: str) -> Tuple[np.ndarray, np.ndarray]:
        """(values: object[N], found: bool[N]) for one target expression."""
        hit = self._cache.get(target)
        if hit is not None:
            return hit
        n = self.n
        values = np.empty(n, dtype=object)
        found = np.zeros(n, dtype=bool)
        if not target.startswith("${"):
            values[:] = target
            found[:] = True
        elif target == "${node.unique.id}":
            for i, node in enumerate(self.nodes):
                values[i] = node.id
            found[:] = True
        elif target == "${node.datacenter}":
            for i, node in enumerate(self.nodes):
                values[i] = node.datacenter
            found[:] = True
        elif target == "${node.unique.name}":
            for i, node in enumerate(self.nodes):
                values[i] = node.name
            found[:] = True
        elif target == "${node.class}":
            for i, node in enumerate(self.nodes):
                values[i] = node.node_class
            found[:] = True
        elif target.startswith("${attr."):
            attr = target[len("${attr."):].removesuffix("}")
            for i, node in enumerate(self.nodes):
                v = node.attributes.get(attr)
                if v is not None:
                    values[i] = v
                    found[i] = True
        elif target.startswith("${meta."):
            meta = target[len("${meta."):].removesuffix("}")
            for i, node in enumerate(self.nodes):
                v = node.meta.get(meta)
                if v is not None:
                    values[i] = v
                    found[i] = True
        # unknown interpolation: nothing found (reference returns nil, false)
        self._cache[target] = (values, found)
        return values, found


def _per_distinct(values: np.ndarray, found: np.ndarray, fn) -> np.ndarray:
    """Apply fn(value_str)->bool once per distinct found value, broadcast."""
    out = np.zeros(len(values), dtype=bool)
    if not found.any():
        return out
    idx = np.nonzero(found)[0]
    strs = values[idx]
    distinct: Dict[str, bool] = {}
    res = np.zeros(len(idx), dtype=bool)
    for j, s in enumerate(strs):
        r = distinct.get(s)
        if r is None:
            r = fn(s)
            distinct[s] = r
        res[j] = r
    out[idx] = res
    return out


def _check_set_contains_all(lval: str, rval: str) -> bool:
    have = {p.strip() for p in lval.split(",")}
    return all(p.strip() in have for p in rval.split(","))


def _check_set_contains_any(lval: str, rval: str) -> bool:
    have = {p.strip() for p in lval.split(",")}
    return any(p.strip() in have for p in rval.split(","))


_REGEX_CACHE: Dict[str, Optional[re.Pattern]] = {}


def _regex(pattern: str) -> Optional[re.Pattern]:
    p = _REGEX_CACHE.get(pattern)
    if p is None and pattern not in _REGEX_CACHE:
        try:
            p = re.compile(pattern)
        except re.error:
            p = None
        _REGEX_CACHE[pattern] = p
    return p


def constraint_mask(cols: TargetColumns, ltarget: str, rtarget: str,
                    operand: str) -> np.ndarray:
    """bool[N]: does each node satisfy the constraint?
    Mirrors checkConstraint (feasible.go:750-785)."""
    n = cols.n
    # handled by dedicated stateful checkers, pass-through here
    if operand in (CONSTRAINT_DISTINCT_HOSTS, CONSTRAINT_DISTINCT_PROPERTY):
        return np.ones(n, dtype=bool)

    lvals, lfound = cols.resolve(ltarget)
    rvals, rfound = cols.resolve(rtarget) if rtarget else (
        np.empty(n, dtype=object), np.zeros(n, dtype=bool))

    if operand in ("=", "==", "is"):
        return lfound & rfound & np.asarray(lvals == rvals, dtype=bool)
    if operand in ("!=", "not"):
        # reference: !reflect.DeepEqual(lVal, rVal) — unfound sides are nil
        l = np.where(lfound, lvals, None)
        r = np.where(rfound, rvals, None)
        return np.asarray(l != r, dtype=bool)
    if operand in ("<", "<=", ">", ">="):
        ok = lfound & rfound
        out = np.zeros(n, dtype=bool)
        idx = np.nonzero(ok)[0]
        for i in idx:
            l, r = lvals[i], rvals[i]
            if not isinstance(l, str) or not isinstance(r, str):
                continue
            out[i] = ((operand == "<" and l < r) or
                      (operand == "<=" and l <= r) or
                      (operand == ">" and l > r) or
                      (operand == ">=" and l >= r))
        return out
    if operand == CONSTRAINT_IS_SET:
        return lfound.copy()
    if operand == CONSTRAINT_IS_NOT_SET:
        return ~lfound
    if operand == CONSTRAINT_VERSION:
        rv = rtarget
        return lfound & rfound & _per_distinct(
            lvals, lfound, lambda s: version_matches(s, rv))
    if operand == CONSTRAINT_SEMVER:
        rv = rtarget
        return lfound & rfound & _per_distinct(
            lvals, lfound, lambda s: version_matches(s, rv, strict_semver=True))
    if operand == CONSTRAINT_REGEX:
        pat = _regex(rtarget)
        if pat is None:
            return np.zeros(n, dtype=bool)
        return lfound & rfound & _per_distinct(
            lvals, lfound, lambda s: pat.search(s) is not None)
    if operand in (CONSTRAINT_SET_CONTAINS, CONSTRAINT_SET_CONTAINS_ALL):
        rv = rtarget
        return lfound & rfound & _per_distinct(
            lvals, lfound, lambda s: _check_set_contains_all(s, rv))
    if operand == CONSTRAINT_SET_CONTAINS_ANY:
        rv = rtarget
        return lfound & rfound & _per_distinct(
            lvals, lfound, lambda s: _check_set_contains_any(s, rv))
    return np.zeros(n, dtype=bool)


# -- scalar twins (state/node_attr_index.py + scheduler/feasible_compiler)
#
# The compiled feasibility engine evaluates each operand once per
# DISTINCT interned value and broadcasts through code columns, and
# patches single rows on node update. Both paths call these scalar
# twins, so compiled masks match constraint_mask bit for bit by
# construction — there is exactly one implementation of the operand
# semantics per row.

def node_target_value(node, target: str):
    """(value, found) for ONE node — the scalar twin of
    TargetColumns.resolve. Values are raw (not str-coerced), exactly
    like the column path."""
    if not target.startswith("${"):
        return target, True
    if target == "${node.unique.id}":
        return node.id, True
    if target == "${node.datacenter}":
        return node.datacenter, True
    if target == "${node.unique.name}":
        return node.name, True
    if target == "${node.class}":
        return node.node_class, True
    if target.startswith("${attr."):
        v = node.attributes.get(target[len("${attr."):].removesuffix("}"))
        return (v, True) if v is not None else (None, False)
    if target.startswith("${meta."):
        v = node.meta.get(target[len("${meta."):].removesuffix("}"))
        return (v, True) if v is not None else (None, False)
    # unknown interpolation: nothing found (reference returns nil, false)
    return None, False


def constraint_verdict(operand: str, rtarget: str, lval, lfound: bool,
                       rval, rfound: bool) -> bool:
    """One row of constraint_mask: does (lval, rval) satisfy the
    operand? `rtarget` is the RAW constraint rtarget string — the
    reference passes it verbatim (not the resolved value) to the
    version/semver/regexp/set_contains comparators, and this twin
    preserves that quirk."""
    if operand in (CONSTRAINT_DISTINCT_HOSTS, CONSTRAINT_DISTINCT_PROPERTY):
        return True
    if operand in ("=", "==", "is"):
        return bool(lfound and rfound and lval == rval)
    if operand in ("!=", "not"):
        return (lval if lfound else None) != (rval if rfound else None)
    if operand in ("<", "<=", ">", ">="):
        if not (lfound and rfound):
            return False
        if not isinstance(lval, str) or not isinstance(rval, str):
            return False
        return ((operand == "<" and lval < rval) or
                (operand == "<=" and lval <= rval) or
                (operand == ">" and lval > rval) or
                (operand == ">=" and lval >= rval))
    if operand == CONSTRAINT_IS_SET:
        return bool(lfound)
    if operand == CONSTRAINT_IS_NOT_SET:
        return not lfound
    if operand == CONSTRAINT_VERSION:
        return bool(lfound and rfound and version_matches(lval, rtarget))
    if operand == CONSTRAINT_SEMVER:
        return bool(lfound and rfound
                    and version_matches(lval, rtarget, strict_semver=True))
    if operand == CONSTRAINT_REGEX:
        pat = _regex(rtarget)
        if pat is None:
            return False
        return bool(lfound and rfound and pat.search(lval) is not None)
    if operand in (CONSTRAINT_SET_CONTAINS, CONSTRAINT_SET_CONTAINS_ALL):
        return bool(lfound and rfound
                    and _check_set_contains_all(lval, rtarget))
    if operand == CONSTRAINT_SET_CONTAINS_ANY:
        return bool(lfound and rfound
                    and _check_set_contains_any(lval, rtarget))
    return False


def driver_ok(node, driver: str) -> bool:
    """One row of NodeTable.driver_mask (DriverChecker,
    feasible.go:398)."""
    info = node.drivers.get(driver)
    if info is not None:
        return bool(info.detected and info.healthy)
    return node.attributes.get(f"driver.{driver}", "") not in ("", "0",
                                                               "false")


def host_volume_value(node, source: str):
    """Interned access-mode value of one host volume on one node:
    None (absent), "ro", or "rw" — the only facts
    NodeTable.host_volume_mask reads per row."""
    vol = node.host_volumes.get(source)
    if vol is None:
        return None
    return "ro" if vol.get("read_only", False) else "rw"


def host_volume_ok(value, ro_strict: bool) -> bool:
    """One (volume request, node) cell of host_volume_mask: `value` is
    host_volume_value's result, `ro_strict` is
    `req.read_only is False` (the reference's exact identity check)."""
    if value is None:
        return False
    return not (ro_strict and value == "ro")


def affinity_columns(cols: TargetColumns, affinities: List) -> Tuple[np.ndarray, float]:
    """(weighted_match_sum: f32[N], sum_abs_weights) for NodeAffinityIterator
    (rank.go:637-668): score = sum(weight * matches) / sum(|weight|)."""
    n = cols.n
    total = np.zeros(n, dtype=np.float32)
    sum_weight = 0.0
    for aff in affinities:
        sum_weight += abs(float(aff.weight))
        mask = constraint_mask(cols, aff.ltarget, aff.rtarget, aff.operand)
        total += mask.astype(np.float32) * float(aff.weight)
    return total, sum_weight
