"""Vectorized spread / distinct-property scoring inputs (ISSUE 20).

`_spread_inputs` and `_distinct_prop_inputs` were the last per-eval
O(N)-Python stages on the select path: every eval re-walked the
proposed-alloc lists per spread attribute (ProposedIndex.
property_counts) and every table rebuild re-ran the O(N) Python
dictionary encoding (NodeTable.attr_codes). This module replaces both
with array passes:

  - `attr_codes_fast` derives the table's dictionary encoding from the
    write-through interned columns (state/node_attr_index.py) — one
    np.take through the index->table permutation plus an np.unique to
    reproduce attr_codes' first-encounter-order numbering EXACTLY, so
    downstream kernel state is bit-identical. The interned column
    survives table rebuilds (it is maintained per changed row), so a
    node update no longer costs an O(N) re-encode per attribute;
  - `property_counts_vec` turns the per-alloc Python walk into one
    scatter-add over the proposed rows' attribute codes
    (np.add.at), with desired-percent deltas broadcast per unique
    value by the caller;
  - `distinct_uncontended` folds distinct_hosts/distinct_property into
    a plan-time verdict for single-placement evals: one vectorized
    check over the proposed node/property codes replaces the in-kernel
    per-step gating when no proposed alloc contends (the state ships
    only when it can actually fire).

Everything is gated by the ISSUE 20 residue kill switch
(`NOMAD_TPU_FEAS_RESIDUE=0` / ServerConfig.feas_residue=false restores
the scalar builds), and the scalar twins stay in
scheduler/stack.py + ops/tables.py as the fallback and parity
reference (tests/test_feas_residue.py pins 1k-seed bit-parity).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

STATS: Dict[str, int] = {
    "spread_score_evals": 0,   # vectorized count/present builds
    "vector_builds": 0,        # spread/distinct input sets built vectorized
    "scalar_builds": 0,        # ... built on the scalar fallback
    "codes_vec_builds": 0,     # attr_codes derived from interned columns
    "codes_fallbacks": 0,      # attr_codes fell back to the O(N) encode
    "distinct_folds": 0,       # distinct state folded to plan-time verdict
}

# accumulated input-build seconds per arm; the bench_feas_residue cell
# delta-reads these to compute spread_score_speedup (scalar_s/vector_s)
TIMINGS: Dict[str, float] = {"vector_s": 0.0, "scalar_s": 0.0}


def enabled() -> bool:
    from ..scheduler import feasible_compiler
    return feasible_compiler.residue_enabled()


def note_build(dt: float) -> None:
    """Attribute one eval's spread/distinct input-build wall time to
    the active arm (called by the stack around both paths)."""
    if enabled():
        STATS["vector_builds"] += 1
        TIMINGS["vector_s"] += dt
    else:
        STATS["scalar_builds"] += 1
        TIMINGS["scalar_s"] += dt


def stats() -> Dict[str, float]:
    out: Dict[str, float] = dict(STATS)
    out.update(TIMINGS)
    return out


def reset_stats() -> None:
    for k in STATS:
        STATS[k] = 0
    for k in TIMINGS:
        TIMINGS[k] = 0.0


# -- dictionary encoding off the interned columns ----------------------

# the targets the attr index interns (feasible_compiler._resolve's
# column gate); anything else stays on the table's own encoder
_COLUMN_TARGETS = ("${node.unique.id}", "${node.datacenter}",
                   "${node.unique.name}", "${node.class}")


def _interned_codes(table, attribute: str, snapshot):
    """(codes i32[N], values) in the table's first-encounter-order
    numbering, derived from the write-through interned column, or None
    (caller falls back to NodeTable.attr_codes)."""
    if not (attribute in _COLUMN_TARGETS
            or attribute.startswith("${attr.")
            or attribute.startswith("${meta.")):
        return None
    store = getattr(snapshot, "_store", None) if snapshot is not None \
        else None
    if store is None:
        return None
    cache = getattr(store, "attr_index", None)
    if cache is None or not cache.enabled:
        return None
    if cache.needs_build():
        cache.build_install(snapshot)
    with cache.lock:
        idx = cache.synced(snapshot)
        if idx is None:
            return None
        col = idx.column(attribute)
        if col.overflow:
            return None
        perm, _inv = idx.perm_for(table.ids)
        if perm is None:
            return None
        # snapshot the aligned codes under the lock; the numbering
        # pass below is pure array work on the copy
        col_t = col.codes[:idx.n][perm].copy()
        values_src = list(col.values)
    n = table.n
    pos = np.flatnonzero(col_t >= 0)
    if pos.size == 0:
        return np.zeros(n, dtype=np.int32), []
    cds = col_t[pos]
    # attr_codes numbers values by first encounter in table-row order;
    # np.unique(return_index) hands us each intern code's first
    # position, and ranking those positions reproduces the numbering
    uniq, first = np.unique(cds, return_index=True)
    order = np.argsort(first, kind="stable")
    lut = np.empty(len(values_src), dtype=np.int32)
    lut[uniq[order]] = np.arange(len(uniq), dtype=np.int32)
    values = [values_src[int(c)] for c in uniq[order]]
    codes = np.full(n, len(values), dtype=np.int32)
    codes[pos] = lut[cds]
    return codes, values


def attr_codes_fast(table, attribute: str, snapshot
                    ) -> Tuple[np.ndarray, List[str]]:
    """NodeTable.attr_codes semantics, preferring the interned-column
    derivation. The result lands in the table's own cache under the
    same key, so ProposedIndex.property_counts' identity check
    (`tvals is values`) keeps holding for every later consumer."""
    hit = table._attr_codes_cache.get(attribute)
    if hit is not None:
        return hit
    built = _interned_codes(table, attribute, snapshot)
    if built is None:
        STATS["codes_fallbacks"] += 1
        return table.attr_codes(attribute)
    STATS["codes_vec_builds"] += 1
    table._attr_codes_cache[attribute] = built
    return built


def attr_present_mask(table, attribute: str, snapshot
                      ) -> Optional[np.ndarray]:
    """bool[N]: the node carries a value for `attribute` — presence
    read straight off the interned column (code != -1), or None to
    fall back to the per-node walk. Backs the CSI plugin-attr residue
    mask so a table rebuild costs O(1) numpy, not O(N) Python."""
    built = _interned_codes(table, attribute, snapshot)
    if built is None:
        return None
    codes, values = built
    return codes != len(values)


# -- proposed-alloc counts as one scatter ------------------------------

def property_counts_vec(proposed, tcodes: np.ndarray, n_values: int,
                        tg_name: Optional[str]
                        ) -> Tuple[np.ndarray, np.ndarray]:
    """(counts f32[C+1], present bool[C+1]) — the vectorized twin of
    ProposedIndex.property_counts for the identity-mapped case: one
    gather of the proposed rows' codes and one np.add.at. Index C is
    the missing-attribute bucket (never counted, like the scalar
    walk's `continue`)."""
    rows, tgs = proposed.prop_arrays()
    counts = np.zeros(n_values + 1, dtype=np.float32)
    if rows.size:
        if tg_name is not None:
            rows = rows[tgs == tg_name]
        cds = tcodes[rows]
        cds = cds[cds != n_values]
        if cds.size:
            np.add.at(counts, cds, np.float32(1.0))
    present = counts > 0
    STATS["spread_score_evals"] += 1
    return counts, present


# -- plan-time distinct fold -------------------------------------------

def distinct_uncontended(mask: np.ndarray, job_count: np.ndarray,
                         distinct_props: List[Dict]) -> bool:
    """True when a SINGLE placement's distinct_hosts/distinct_property
    gates can never fire on any feasible node — the per-eval plan-time
    verdict (one scatter's worth of vectorized reads over the proposed
    node/property counts) that lets the request drop the per-step
    kernel state entirely. Only valid for count==1: multi-placement
    batches self-collide in-kernel and need the live counters."""
    if mask.any() and np.any(job_count[mask] != 0):
        return False
    for dp in distinct_props:
        counts, codes = dp["counts"], dp["codes"]
        if mask.any() and np.any(counts[codes[mask]] + 1.0 > dp["limit"]):
            return False
    return True
