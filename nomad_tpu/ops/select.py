"""The fused placement kernel.

One device dispatch replaces the reference's entire per-placement
iterator chain (stack.go Select -> feasible -> BinPack -> scorers ->
Limit -> MaxScore) AND the outer per-alloc loop: a `lax.scan` places all
`count` instances of a task group sequentially *on device*, with each
step seeing the previous steps' placements (usage, anti-affinity
collisions, spread histograms, distinct-hosts/-property counts carried
through the scan). Score semantics mirror:

  - bin-pack / spread fit    structs/funcs.go ScoreFitBinPack:174 (/18)
  - job anti-affinity        rank.go:502  (-(collisions+1)/desired_count)
  - reschedule penalty       rank.go:564  (-1 on penalty nodes)
  - node affinity            rank.go:637  (sum(w*match)/sum|w|)
  - spread                   spread.go:110 (targeted + even-spread boost)
  - normalization            rank.go:696  (mean over *fired* scorers)
  - selection                select.go MaxScoreIterator -> full argmax
                             (no log2(n) sampling: the whole node axis
                             is scored at once, SURVEY.md §2.6)

Shapes are padded to buckets to bound recompilation:
  N -> next power of two; steps K -> bucket; spreads S, distinct-property
  P, codes C -> fixed maxima. Padded lanes carry zero weight.
"""

from __future__ import annotations

import dataclasses
import os
from functools import lru_cache, partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from ..utils.locks import make_lock

# JIT shape-cache bound (governor accounting): every distinct
# (steps, spreads, distinct, lane) shape bucket compiles and caches an
# XLA executable; maxsize turns the open-ended dict into a true
# shape-LRU so a long-running server's kernel cache stays bounded and
# evictions free the executables with the dropped reference
KERNEL_CACHE_MAX = int(os.environ.get("NOMAD_TPU_KERNEL_CACHE_MAX",
                                      "128"))

S_MAX = 4       # max spread stanzas per task group
P_MAX = 4       # max distinct_property constraints
C_MAX = 64      # max distinct attribute values per spread/property axis
NEG_INF = -1e30
TOP_K = 5       # ScoreMetaData entries kept (reference kheap topK)
CHUNK_J = 256   # max instances placed on one node per chunked step
KWAY_W = 32     # winners per phase at small tables (floor for _kway_w)
KWAY_STEPS = 256  # phases per dispatch: ~56 cover a 10k batch, and the
                  # out buffers ride the tunnel — small beats roomy


def _kway_steps(w: int) -> int:
    """Phase budget per dispatch. Wide phases need fewer steps for the
    same count, and the out buffers ([steps, 2w+...] ints) ride the
    tunnel on every dispatch — half the rows at w>=128 halves the
    transfer; overflow continues from the device-resident carry."""
    return KWAY_STEPS if w <= KWAY_W else 128


def _kway_w(n_pad: int) -> int:
    """Winners per K-way phase, scaled with the table. On a
    near-homogeneous table the waterline rule yields chunk≈1 per
    winner, so a batch takes ~count/W sequential phases; at 65536 rows
    top_k(N, 257) costs barely more than top_k(N, 33) while cutting
    phases 8x (round-5 profile: 10k placements @50k nodes spent 0.6 s
    in ~320 phases at W=32)."""
    if n_pad <= 4096:
        return 32
    return 128      # sweep @65536 rows: W 64-256 all ~0.21 s for a 10k
                    # batch (steps scale down, per-phase cost up); 512+
                    # regress on the [W, CHUNK_J] stream block


def _pad_n(n: int) -> int:
    p = 8
    while p < n:
        p *= 2
    return p


def _bucket_k(k: int) -> int:
    """Scan length bucket. Dispatch overhead dominates scan-step cost
    (~26us/step vs ~0.7s/dispatch over the axon tunnel), so buckets are
    generous: powers of two up to 1024, then multiples of 1024."""
    if k <= 1024:
        b = 1
        while b < k:
            b *= 2
        return b
    return min(-(-k // 1024) * 1024, 65536)


@dataclasses.dataclass
class SelectRequest:
    """Host-side inputs for placing `count` instances of one task group."""
    ask: np.ndarray                  # f32[D] cpu/mem/disk[/mbits] per instance
    count: int
    feasible: np.ndarray             # bool[N] all static checks combined
    capacity: np.ndarray             # f32[N,D]
    used: np.ndarray                 # f32[N,D] live + plan overlay
    desired_count: float             # anti-affinity denominator (tg count)
    tg_collisions: np.ndarray        # i32[N] proposed allocs of job+tg
    job_count: np.ndarray            # i32[N] proposed allocs of job
    distinct_hosts: bool = False
    penalty: Optional[np.ndarray] = None        # bool[N]
    affinity: Optional[np.ndarray] = None       # f32[N] weighted sum
    affinity_sum_weights: float = 0.0
    algorithm: str = "binpack"       # "binpack" | "spread"
    scan_exclusive: bool = False     # reserved-port ask: one instance/node/scan
    port_need: float = 0.0
    free_ports: Optional[np.ndarray] = None     # f32[N]
    port_ok: Optional[np.ndarray] = None        # bool[N]
    # device dimension (scheduler/devices.py): placements-remaining
    # slots per node (consumed 1 per placement), the "devices" scorer
    # column, and whether that scorer fires (any ask has affinities)
    dev_slots: Optional[np.ndarray] = None      # f32[N]
    dev_score: Optional[np.ndarray] = None      # f32[N]
    dev_fires: bool = False
    # preemption competition (rank.go:415-448 + PreemptionScoringIterator
    # :714): nodes whose fit comes from evicting victims carry the
    # logistic preemption score as an extra fired scorer; `used` must
    # already reflect the hypothetical evictions for those nodes.
    # 0 = no preemption on this node (the logistic is never exactly 0).
    pre_score: Optional[np.ndarray] = None      # f32[N]
    # spreads: list of dicts with codes i32[N], counts f32[C+1],
    #          present bool[C+1], desired f32[C+1] (-1 == none),
    #          has_implicit, implicit_desired, weight, has_targets
    spreads: List[Dict] = dataclasses.field(default_factory=list)
    sum_spread_weights: float = 0.0
    # distinct_property: list of dicts with codes i32[N], counts f32[C+1],
    #          limit f32
    distinct_props: List[Dict] = dataclasses.field(default_factory=list)
    # nodes actually under consideration (ready + in the eval's DCs);
    # the resident table holds ALL nodes, so metrics must not count
    # down/foreign-DC rows as evaluated (AllocMetric semantics)
    n_considered: Optional[int] = None
    # device-resident dispatch (ops/device_table.py): the host
    # NodeTable whose mirror token may let this dispatch reuse the
    # device copies of capacity/used/free_ports, plus the per-eval
    # plan overlay in sparse (rows, deltas) form so `used0` is
    # computed ON DEVICE from the resident base instead of shipping
    # the dense column. Only set when `used` is exactly
    # base_used + scatter(deltas at rows) — preemption overlays and
    # private tables leave it None (dense fallback).
    table: Optional[object] = None
    used_base_rows: Optional[np.ndarray] = None   # i32[M]
    used_base_deltas: Optional[np.ndarray] = None  # f32[M,D]
    # device-resident combined feasibility mask (ISSUE 17): token into
    # the mirror's FeasMaskStore, set by the stack only when `feasible`
    # reaches the dispatch unmutated (no CSI/preferred residue). Any
    # path that swaps `feasible` must clear it.
    feas_token: Optional[Tuple] = None
    # sparse residue atop the parked mask (ISSUE 20): (rows i32[M],
    # vals bool[M]) reproducing the host mask's CSI-claim/quota/
    # preferred-node mutations on device via one jitted scatter, so
    # the token survives residue instead of forcing a dense re-upload.
    # Only meaningful beside feas_token; cleared with it.
    feas_residue: Optional[Tuple[np.ndarray, np.ndarray]] = None


@dataclasses.dataclass
class SelectResult:
    """Result of one multi-placement kernel dispatch."""
    node_idx: np.ndarray             # i32[K] chosen node per step (-1 none)
    final_score: np.ndarray          # f32[K]
    scores: Dict[str, np.ndarray]    # component -> f32[K]
    top_idx: np.ndarray              # i32[K, TOP_K]
    top_scores: np.ndarray           # f32[K, TOP_K]
    nodes_evaluated: int
    nodes_filtered: int
    exhausted_dim: np.ndarray        # i32[K, D] counts per DIM_NAMES dim
    placed: int


def _select_scan_fn(capacity, used0, feasible, ask, k_valid,
                 tg_coll0, job_count0, distinct_hosts_flag, scan_exclusive,
                 penalty, affinity_norm, desired_count,
                 port_need, free_ports, port_ok,
                 dev_slots0, dev_score, dev_fires, pre_score,
                 sp_codes, sp_counts0, sp_present0, sp_desired,
                 sp_weight, sp_has_targets, sp_valid, sum_spread_w,
                 dp_codes, dp_counts0, dp_limit, dp_valid,
                 *, k_steps: int, spread_alg: bool, s_live: int, p_live: int):
    """The fused kernel. Shapes:
    capacity/used0 f32[N,3]; feasible bool[N]; ask f32[3];
    sp_* [S, ...] with code axis C+1; dp_* [P, ...].
    Returns per-step choices, scores, metrics, and the final usage state.
    """
    n = capacity.shape[0]
    cap_cpu = jnp.maximum(capacity[:, 0], 1e-9)
    cap_mem = jnp.maximum(capacity[:, 1], 1e-9)

    def step(carry, step_i):
        (used, tg_coll, job_cnt, scan_placed, free_p, dev_slots,
         sp_counts, sp_present, dp_counts) = carry

        # ---- feasibility beyond the static mask -----------------------
        feas = feasible
        feas &= jnp.where(distinct_hosts_flag > 0, job_cnt == 0, True)
        # reserved-port asks make instances mutually exclusive per node
        # within this scan (the same host port would collide)
        feas &= jnp.where(scan_exclusive > 0, scan_placed == 0, True)
        feas &= free_p >= port_need
        feas &= port_ok
        feas &= dev_slots >= 1.0
        # distinct_property: count(value)+1 <= limit, missing attr fails
        for p in range(p_live):
            codes = dp_codes[p]
            cnt = dp_counts[p][codes]
            missing = codes == dp_counts.shape[-1] - 1
            ok = (cnt + 1.0 <= dp_limit[p]) & ~missing
            feas &= jnp.where(dp_valid[p], ok, True)

        # ---- fit (AllocsFit over the node axis) -----------------------
        after = used + ask[None, :]
        fit_dims = after <= capacity + 1e-6
        fit = jnp.all(fit_dims, axis=1)

        # ---- bin-pack / spread fit score ------------------------------
        free_cpu = 1.0 - after[:, 0] / cap_cpu
        free_mem = 1.0 - after[:, 1] / cap_mem
        total = jnp.power(10.0, free_cpu) + jnp.power(10.0, free_mem)
        if spread_alg:
            fit_score = jnp.clip(total - 2.0, 0.0, 18.0)
        else:
            fit_score = jnp.clip(20.0 - total, 0.0, 18.0)
        binpack = fit_score / 18.0

        # ---- job anti-affinity ---------------------------------------
        coll = tg_coll.astype(jnp.float32)
        anti_fires = coll > 0
        anti = jnp.where(anti_fires,
                         -(coll + 1.0) / jnp.maximum(desired_count, 1.0),
                         0.0)

        # ---- reschedule penalty --------------------------------------
        pen_fires = penalty
        pen = jnp.where(pen_fires, -1.0, 0.0)

        # ---- node affinity -------------------------------------------
        aff_fires = affinity_norm != 0.0
        aff = affinity_norm

        # ---- device affinity ("devices" scorer, rank.go:456) ---------
        dev = jnp.where(dev_fires > 0, dev_score, 0.0)

        # ---- preemption scorer (rank.go:714 logistic) ----------------
        pre_fires = pre_score != 0.0

        # ---- spread ---------------------------------------------------
        spread_total = jnp.zeros(n, dtype=jnp.float32)
        for s in range(s_live):
            codes = sp_codes[s]
            c_axis = sp_counts.shape[-1]
            missing = codes == c_axis - 1
            used_cnt = sp_counts[s][codes] + 1.0
            desired = sp_desired[s][codes]
            has_desired = desired >= 0.0
            w = sp_weight[s] / jnp.maximum(sum_spread_w, 1e-9)
            targeted = jnp.where(
                has_desired,
                (desired - used_cnt) / jnp.maximum(desired, 1e-9) * w,
                -1.0)
            # even-spread scoring (spread.go evenSpreadScoreBoost)
            pres = sp_present[s]
            cnts = sp_counts[s]
            big = 1e30
            min_cnt = jnp.min(jnp.where(pres, cnts, big))
            max_cnt = jnp.max(jnp.where(pres, cnts, -big))
            any_present = jnp.any(pres)
            cur = sp_counts[s][codes]
            even = jnp.where(
                min_cnt == 0.0,
                -1.0,
                (min_cnt - cur) / jnp.maximum(min_cnt, 1e-9))
            at_min = cur == min_cnt
            even = jnp.where(
                at_min,
                jnp.where(min_cnt == max_cnt, -1.0,
                          jnp.where(min_cnt == 0.0, 1.0,
                                    (max_cnt - min_cnt) /
                                    jnp.maximum(min_cnt, 1e-9))),
                even)
            even = jnp.where(any_present, even, 0.0)
            even = jnp.where(missing, -1.0, even)
            contrib = jnp.where(sp_has_targets[s],
                                jnp.where(missing, -1.0, targeted), even)
            spread_total += jnp.where(sp_valid[s], contrib, 0.0)
        spread_fires = spread_total != 0.0

        # ---- normalization (mean over fired scorers) ------------------
        fired = (1.0 + anti_fires.astype(jnp.float32)
                 + pen_fires.astype(jnp.float32)
                 + aff_fires.astype(jnp.float32)
                 + spread_fires.astype(jnp.float32)
                 + jnp.where(dev_fires > 0, 1.0, 0.0)
                 + pre_fires.astype(jnp.float32))
        final = (binpack + anti + pen + aff + spread_total + dev
                 + pre_score) / fired

        # ---- masked argmax -------------------------------------------
        ok = feas & fit
        masked = jnp.where(ok, final, NEG_INF)
        choice = jnp.argmax(masked)
        valid = (masked[choice] > NEG_INF / 2) & (step_i < k_valid)
        choice_out = jnp.where(valid, choice, -1)

        # diagnostics (top-k meta, per-dimension exhaustion) only on the
        # first and failing steps — a full top_k + [N,D] scan per step
        # dominates large tables; per-instance scores are exact always
        def _meta(_):
            top_scores, top_idx = jax.lax.top_k(masked, TOP_K)
            prefix_ok = jnp.cumprod(fit_dims.astype(jnp.int32), axis=1)
            earlier_ok = jnp.concatenate(
                [jnp.ones((n, 1), dtype=bool),
                 prefix_ok[:, :-1].astype(bool)], axis=1)
            first_fail = feas[:, None] & earlier_ok & ~fit_dims
            return (top_idx.astype(jnp.int32), top_scores,
                    first_fail.sum(axis=0).astype(jnp.int32),
                    ok.sum().astype(jnp.int32))

        def _no_meta(_):
            return (jnp.full((TOP_K,), -1, jnp.int32),
                    jnp.full((TOP_K,), NEG_INF, jnp.float32),
                    jnp.full((capacity.shape[1],), -1, jnp.int32),
                    jnp.int32(-1))

        top_idx, top_scores, exhausted, ok_count = jax.lax.cond(
            (step_i == 0) | ~valid, _meta, _no_meta, operand=None)

        # ---- carry updates (the placement happens here) ---------------
        inc = jnp.where(valid, 1, 0)
        incf = inc.astype(jnp.float32)
        used = used.at[choice].add(incf * ask)
        tg_coll = tg_coll.at[choice].add(inc)
        job_cnt = job_cnt.at[choice].add(inc)
        scan_placed = scan_placed.at[choice].add(inc)
        free_p = free_p.at[choice].add(-incf * port_need)
        dev_slots = dev_slots.at[choice].add(-incf)
        chosen_sp_codes = sp_codes[:, choice]           # [S]
        sp_counts = sp_counts.at[jnp.arange(sp_counts.shape[0]),
                                 chosen_sp_codes].add(incf)
        sp_present = sp_present.at[jnp.arange(sp_counts.shape[0]),
                                   chosen_sp_codes].set(
            sp_present[jnp.arange(sp_counts.shape[0]),
                       chosen_sp_codes] | valid)
        chosen_dp_codes = dp_codes[:, choice]
        dp_counts = dp_counts.at[jnp.arange(dp_counts.shape[0]),
                                 chosen_dp_codes].add(incf)

        out = (choice_out.astype(jnp.int32),
               jnp.where(valid, masked[jnp.maximum(choice, 0)], 0.0),
               jnp.where(valid, binpack[jnp.maximum(choice, 0)], 0.0),
               jnp.where(valid, anti[jnp.maximum(choice, 0)], 0.0),
               jnp.where(valid, pen[jnp.maximum(choice, 0)], 0.0),
               jnp.where(valid, aff[jnp.maximum(choice, 0)], 0.0),
               jnp.where(valid, spread_total[jnp.maximum(choice, 0)], 0.0),
               jnp.where(valid, dev[jnp.maximum(choice, 0)], 0.0),
               jnp.where(valid, pre_score[jnp.maximum(choice, 0)], 0.0),
               top_idx, top_scores,
               exhausted, ok_count)
        return (used, tg_coll, job_cnt, scan_placed, free_p, dev_slots,
                sp_counts, sp_present, dp_counts), out

    carry0 = (used0, tg_coll0, job_count0,
              jnp.zeros(n, dtype=jnp.int32), free_ports, dev_slots0,
              sp_counts0, sp_present0, dp_counts0)
    carry, outs = jax.lax.scan(step, carry0, jnp.arange(k_steps))
    return carry, outs


_select_scan = partial(
    jax.jit, static_argnames=("k_steps", "spread_alg", "s_live",
                              "p_live"))(_select_scan_fn)

# positional order of _select_scan_fn's array arguments (the batched
# dispatcher calls it positionally under vmap)
_SCAN_ARGS = (
    "capacity", "used0", "feasible", "ask", "k_valid",
    "tg_coll0", "job_count0", "distinct_hosts_flag", "scan_exclusive",
    "penalty", "affinity_norm", "desired_count",
    "port_need", "free_ports", "port_ok",
    "dev_slots0", "dev_score", "dev_fires", "pre_score",
    "sp_codes", "sp_counts0", "sp_present0", "sp_desired",
    "sp_weight", "sp_has_targets", "sp_valid", "sum_spread_w",
    "dp_codes", "dp_counts0", "dp_limit", "dp_valid")


@lru_cache(maxsize=KERNEL_CACHE_MAX)
def _scan_batched_jit(k_steps: int, spread_alg: bool, s_live: int,
                      p_live: int):
    """The vmapped scan: B independent lanes over ONE shared capacity
    table (in_axes=None keeps it unstacked/resident) — the small-count
    arm of multi-eval batching. Covers the FULL scoring surface
    (spreads, distinct-property, reserved ports) unlike the K-way arm,
    because it is literally the scan kernel with a lane axis."""
    def fn(*args):
        return _select_scan_fn(*args, k_steps=k_steps,
                               spread_alg=spread_alg,
                               s_live=s_live, p_live=p_live)
    in_axes = tuple(None if name == "capacity" else 0
                    for name in _SCAN_ARGS)
    return jax.jit(jax.vmap(fn, in_axes=in_axes))


def _local_final_score(after, cap_cpu, cap_mem, coll, penalty, affinity,
                       desired_count, spread_alg: bool,
                       dev_score=0.0, dev_fires=0.0, pre_score=0.0):
    """Node-local score (binpack/spread fit + anti-affinity + penalty +
    affinity + device affinity, normalized over fired scorers).
    Shape-polymorphic over the leading axes: after[..., D],
    cap/coll/penalty/affinity/dev_score[...]. This is the spread-free
    subset of the scan step's scoring, shared with the chunked kernel
    (semantics: rank.go BinPack/JobAntiAffinity/NodeReschedulingPenalty/
    NodeAffinity/device scoring:456/ScoreNormalization)."""
    free_cpu = 1.0 - after[..., 0] / cap_cpu
    free_mem = 1.0 - after[..., 1] / cap_mem
    total = jnp.power(10.0, free_cpu) + jnp.power(10.0, free_mem)
    if spread_alg:
        fit_score = jnp.clip(total - 2.0, 0.0, 18.0)
    else:
        fit_score = jnp.clip(20.0 - total, 0.0, 18.0)
    binpack = fit_score / 18.0
    collf = coll.astype(jnp.float32)
    anti_fires = collf > 0
    anti = jnp.where(anti_fires,
                     -(collf + 1.0) / jnp.maximum(desired_count, 1.0), 0.0)
    pen = jnp.where(penalty, -1.0, 0.0)
    aff_fires = affinity != 0.0
    dev = jnp.where(dev_fires > 0, dev_score, 0.0)
    pre_fires = pre_score != 0.0
    fired = (1.0 + anti_fires.astype(jnp.float32)
             + penalty.astype(jnp.float32)
             + aff_fires.astype(jnp.float32)
             + jnp.where(dev_fires > 0, 1.0, 0.0)
             + pre_fires.astype(jnp.float32))
    final = (binpack + anti + pen + affinity + dev + pre_score) / fired
    return final, binpack, anti, pen


def _select_chunked_fn(capacity, used0, feasible, ask, k_valid,
                    tg_coll0, penalty, affinity_norm, desired_count,
                    port_need, free_ports, port_ok,
                    dev_slots0, dev_score, dev_fires, pre_score,
                    *, max_steps: int, spread_alg: bool):
    """Chunked greedy placement for node-local scoring (no spread, no
    distinct-hosts/-property, no reserved-port exclusivity). Exactly
    equivalent to the one-instance-per-step scan: because every score
    term is a function of the candidate node's own state, placing an
    instance on the argmax node leaves every other node's score fixed —
    so the greedy sequence keeps choosing the same node until its own
    score is overtaken by the runner-up. Each while-loop step therefore
    places a whole chunk (up to CHUNK_J) on the argmax node: the chunk
    length is the number of consecutive sub-placements that still beat
    the runner-up under the scan's argmax tie rule (lowest index wins).

    This turns the O(count) sequential scan into O(nodes-touched +
    overtake-events) steps — the difference between 1.4 s and ~50 ms for
    a 10k-instance batch job (BASELINE ladder #2).

    Returns per-step (choice, chunk, top_idx/top_scores, exhausted,
    feasible-count) buffers plus the final carry for host-side
    continuation when max_steps is exhausted.
    """
    n = capacity.shape[0]
    cap_cpu = jnp.maximum(capacity[:, 0], 1e-9)
    cap_mem = jnp.maximum(capacity[:, 1], 1e-9)
    arange_j = jnp.arange(CHUNK_J, dtype=jnp.float32)

    def cond(state):
        (_used, _coll, _freep, _dev, remaining, step, alive, *_outs) = state
        return (remaining > 0) & alive & (step < max_steps)

    def body(state):
        (used, coll, free_p, dev_slots, remaining, step, _alive,
         out_choice, out_chunk, out_ti, out_ts, out_exh, out_feas) = state

        feas = feasible & (free_p >= port_need) & port_ok & \
            (dev_slots >= 1.0)
        after = used + ask[None, :]
        fit_dims = after <= capacity + 1e-6
        fit = jnp.all(fit_dims, axis=1)

        final, _b, _a, _p = _local_final_score(
            after, cap_cpu, cap_mem, coll, penalty, affinity_norm,
            desired_count, spread_alg, dev_score, dev_fires, pre_score)
        ok = feas & fit
        masked = jnp.where(ok, final, NEG_INF)
        # winner + runner-up as two argmax reductions — a full top_k
        # over the node axis per step dominates large tables
        choice = jnp.argmax(masked)
        valid = masked[choice] > NEG_INF / 2
        masked2 = masked.at[choice].set(NEG_INF)
        runner_idx = jnp.argmax(masked2)
        runner_val = masked2[runner_idx]

        # diagnostics (top-k score meta + per-dimension exhaustion) are
        # only materialized on the first step and on failing steps; the
        # host reuses the dispatch-level snapshot for later chunks
        def _meta(_):
            top_scores, top_idx = jax.lax.top_k(masked, TOP_K)
            prefix_ok = jnp.cumprod(fit_dims.astype(jnp.int32), axis=1)
            earlier_ok = jnp.concatenate(
                [jnp.ones((n, 1), dtype=bool),
                 prefix_ok[:, :-1].astype(bool)], axis=1)
            first_fail = feas[:, None] & earlier_ok & ~fit_dims
            return (top_idx.astype(jnp.int32), top_scores,
                    first_fail.sum(axis=0).astype(jnp.int32),
                    ok.sum().astype(jnp.int32))

        def _no_meta(_):
            return (jnp.full((TOP_K,), -1, jnp.int32),
                    jnp.full((TOP_K,), NEG_INF, jnp.float32),
                    jnp.full((capacity.shape[1],), -1, jnp.int32),
                    jnp.int32(-1))

        top_idx, top_scores, exhausted, feas_count = jax.lax.cond(
            (step == 0) | ~valid, _meta, _no_meta, operand=None)

        # max instances that physically fit on the chosen node
        free_dims = capacity[choice] - used[choice]
        per_dim = jnp.where(ask > 0, jnp.floor((free_dims + 1e-6) / ask), 1e9)
        m_fit = jnp.min(per_dim)
        m_port = jnp.where(port_need > 0,
                           jnp.floor(free_p[choice] / port_need), 1e9)
        a_max = jnp.minimum(
            jnp.minimum(jnp.minimum(m_fit, m_port), dev_slots[choice]),
            remaining.astype(jnp.float32))

        # score of the choice after each sub-placement a (state used_c +
        # a*ask, then + ask for the instance itself — the scan scores on
        # `after`); runner-up scores are frozen (node-locality)
        after_j = used[choice][None, :] + (arange_j[:, None] + 1.0) * ask
        coll_j = coll[choice].astype(jnp.float32) + arange_j
        final_j, _, _, _ = _local_final_score(
            after_j, cap_cpu[choice], cap_mem[choice], coll_j,
            penalty[choice], affinity_norm[choice],
            desired_count, spread_alg, dev_score[choice], dev_fires,
            pre_score[choice])
        # argmax tie rule: lowest index wins, so the choice survives a
        # tie with the runner-up only if its index is lower
        wins = (final_j > runner_val) | \
               ((final_j == runner_val) & (choice < runner_idx))
        prefix = jnp.cumprod(wins.astype(jnp.int32))
        chunk = jnp.minimum(jnp.maximum(prefix.sum().astype(jnp.float32),
                                        1.0), a_max)
        chunk = jnp.where(valid, chunk, 0.0)
        chunk_i = chunk.astype(jnp.int32)

        # indexed scatters: chunk is 0 on invalid steps, so the adds
        # are no-ops without O(N) select masks
        used = used.at[choice].add(chunk * ask)
        coll = coll.at[choice].add(chunk_i)
        free_p = free_p.at[choice].add(-chunk * port_need)
        dev_slots = dev_slots.at[choice].add(-chunk)

        out_choice = out_choice.at[step].set(
            jnp.where(valid, choice, -1).astype(jnp.int32))
        out_chunk = out_chunk.at[step].set(chunk_i)
        out_ti = out_ti.at[step].set(top_idx)
        out_ts = out_ts.at[step].set(top_scores)
        out_exh = out_exh.at[step].set(exhausted)
        out_feas = out_feas.at[step].set(feas_count)

        return (used, coll, free_p, dev_slots, remaining - chunk_i,
                step + 1, valid,
                out_choice, out_chunk, out_ti, out_ts, out_exh, out_feas)

    d = capacity.shape[1]
    state0 = (used0, tg_coll0, free_ports, dev_slots0, k_valid,
              jnp.int32(0), jnp.bool_(True),
              jnp.full(max_steps, -1, jnp.int32),
              jnp.zeros(max_steps, jnp.int32),
              jnp.full((max_steps, TOP_K), -1, jnp.int32),
              jnp.full((max_steps, TOP_K), NEG_INF, jnp.float32),
              jnp.zeros((max_steps, d), jnp.int32),
              jnp.zeros(max_steps, jnp.int32))
    out = jax.lax.while_loop(cond, body, state0)
    (used, coll, free_p, dev_slots, remaining, steps, _alive,
     out_choice, out_chunk, out_ti, out_ts, out_exh, out_feas) = out
    return ((used, coll, free_p, dev_slots),
            (out_choice, out_chunk, out_ti, out_ts, out_exh, out_feas,
             remaining, steps))


_select_chunked = partial(
    jax.jit, static_argnames=("max_steps", "spread_alg"))(
        _select_chunked_fn)


@lru_cache(maxsize=KERNEL_CACHE_MAX)
def _chunked_batched_jit(max_steps: int, spread_alg: bool):
    """The vmapped chunked kernel: B node-local lanes over ONE shared
    capacity table in a single dispatch. The while_loop batches to
    max-steps-over-lanes iterations, so a batch of small-count evals
    costs about as many node passes as its slowest lane — the chunk-ok
    arm of multi-eval batching (the scan arm covers spread/distinct
    lanes)."""
    def fn(*args):
        return _select_chunked_fn(*args, max_steps=max_steps,
                                  spread_alg=spread_alg)
    in_axes = tuple(None if name == "capacity" else 0
                    for name in _CHUNKED_ARGS)
    return jax.jit(jax.vmap(fn, in_axes=in_axes))


def _kway_core(capacity, used0, feasible, ask, k_valid,
               tg_coll0, penalty, affinity_norm, desired_count,
               port_need, free_ports, port_ok,
               dev_slots0, dev_score, dev_fires, pre_score,
               *, max_steps: int, spread_alg: bool, w: int):
    """K-way chunked greedy placement for node-local scoring: each phase
    takes the top-W nodes and gives EACH the number of sub-placements
    that keep its own score above the (W+1)-th node's score (the
    waterline), under the scan's argmax tie rule. Greedy only ever picks
    the current argmax, and scores are node-local, so until every winner
    falls below the waterline the argmax stays inside the winner set —
    the multiset of placements per phase is exactly the greedy one (the
    host reconstructs the exact order with a heap merge,
    _expand_kway). A phase whose winner chunks would overshoot the
    remaining count degenerates to placing only on the single best node,
    preserving exactness for the tail.

    Phases ~ count/(W * avg-chunk) instead of the 2-way kernel's
    count/avg-chunk steps — an order of magnitude fewer sequential
    device steps for big batches, and out buffers to match."""
    n = capacity.shape[0]
    cap_cpu = jnp.maximum(capacity[:, 0], 1e-9)
    cap_mem = jnp.maximum(capacity[:, 1], 1e-9)
    arange_j = jnp.arange(CHUNK_J, dtype=jnp.float32)

    def cond(state):
        (_used, _coll, _freep, _dev, remaining, step, alive, *_o) = state
        return (remaining > 0) & alive & (step < max_steps)

    def body(state):
        (used, coll, free_p, dev_slots, remaining, step, _alive,
         out_widx, out_chunk, out_ti, out_ts, out_exh, out_feas) = state

        feas = feasible & (free_p >= port_need) & port_ok & \
            (dev_slots >= 1.0)
        after = used + ask[None, :]
        fit_dims = after <= capacity + 1e-6
        fit = jnp.all(fit_dims, axis=1)
        final, _b, _a, _p = _local_final_score(
            after, cap_cpu, cap_mem, coll, penalty, affinity_norm,
            desired_count, spread_alg, dev_score, dev_fires, pre_score)
        ok = feas & fit
        masked = jnp.where(ok, final, NEG_INF)

        tv, ti = jax.lax.top_k(masked, w + 1)
        wl_val = tv[w]
        wl_idx = ti[w]
        widx = ti[:w]
        wvalid = tv[:w] > NEG_INF / 2
        valid = wvalid[0]

        # diagnostics on the first and failing phases only
        def _meta(_):
            top_scores, top_idx = jax.lax.top_k(masked, TOP_K)
            prefix_ok = jnp.cumprod(fit_dims.astype(jnp.int32), axis=1)
            earlier_ok = jnp.concatenate(
                [jnp.ones((n, 1), dtype=bool),
                 prefix_ok[:, :-1].astype(bool)], axis=1)
            first_fail = feas[:, None] & earlier_ok & ~fit_dims
            return (top_idx.astype(jnp.int32), top_scores,
                    first_fail.sum(axis=0).astype(jnp.int32),
                    ok.sum().astype(jnp.int32))

        def _no_meta(_):
            return (jnp.full((TOP_K,), -1, jnp.int32),
                    jnp.full((TOP_K,), NEG_INF, jnp.float32),
                    jnp.full((capacity.shape[1],), -1, jnp.int32),
                    jnp.int32(-1))

        top_idx, top_scores, exhausted, feas_count = jax.lax.cond(
            (step == 0) | ~valid, _meta, _no_meta, operand=None)

        # physical capacity per winner
        free_dims = capacity[widx] - used[widx]                 # [W, D]
        per_dim = jnp.where(ask[None, :] > 0,
                            jnp.floor((free_dims + 1e-6) / ask[None, :]),
                            1e9)
        m_fit = jnp.min(per_dim, axis=1)
        m_port = jnp.where(port_need > 0,
                           jnp.floor(free_p[widx] / port_need), 1e9)
        a_max = jnp.minimum(jnp.minimum(m_fit, m_port), dev_slots[widx])
        a_max = jnp.minimum(a_max, jnp.float32(CHUNK_J))

        # per-winner scores after each sub-placement  [W, CHUNK_J]
        after_j = used[widx][:, None, :] \
            + (arange_j[None, :, None] + 1.0) * ask[None, None, :]
        coll_j = coll[widx].astype(jnp.float32)[:, None] + arange_j[None, :]
        final_j, _, _, _ = _local_final_score(
            after_j, cap_cpu[widx][:, None], cap_mem[widx][:, None],
            coll_j, penalty[widx][:, None], affinity_norm[widx][:, None],
            desired_count, spread_alg, dev_score[widx][:, None], dev_fires,
            pre_score[widx][:, None])
        wins = (final_j > wl_val) | \
               ((final_j == wl_val) & (widx[:, None] < wl_idx))
        prefix = jnp.cumprod(wins.astype(jnp.int32), axis=1)
        chunk = jnp.minimum(
            jnp.maximum(prefix.sum(axis=1).astype(jnp.float32), 1.0),
            a_max)
        chunk = jnp.where(wvalid, chunk, 0.0)

        # overshoot fallback: the tail phase degenerates to the 2-way
        # rule — place on the best node only, chunked against the
        # RUNNER-UP's score (not the waterline: with W winners zeroed
        # out, the runner-up is the true greedy competitor)
        total = chunk.sum()
        runner_val = tv[1]
        runner_idx = ti[1]
        wins0 = (final_j[0] > runner_val) | \
                ((final_j[0] == runner_val) & (widx[0] < runner_idx))
        chunk0 = jnp.minimum(
            jnp.maximum(jnp.cumprod(wins0.astype(jnp.int32)).sum()
                        .astype(jnp.float32), 1.0), a_max[0])
        first_only = jnp.zeros_like(chunk).at[0].set(
            jnp.minimum(chunk0, remaining.astype(jnp.float32)))
        chunk = jnp.where(total > remaining.astype(jnp.float32),
                          first_only, chunk)
        chunk = jnp.where(valid, chunk, jnp.zeros_like(chunk))
        chunk_i = chunk.astype(jnp.int32)

        # winner indices are distinct, so scatter-add is well-defined;
        # invalid lanes carry chunk 0 (no-op adds on a real node row)
        safe_w = jnp.maximum(widx, 0)
        used = used.at[safe_w].add(chunk[:, None] * ask[None, :])
        coll = coll.at[safe_w].add(chunk_i)
        free_p = free_p.at[safe_w].add(-chunk * port_need)
        dev_slots = dev_slots.at[safe_w].add(-chunk)

        out_widx = out_widx.at[step].set(
            jnp.where(chunk_i > 0, widx, -1).astype(jnp.int32))
        out_chunk = out_chunk.at[step].set(chunk_i)
        out_ti = out_ti.at[step].set(top_idx)
        out_ts = out_ts.at[step].set(top_scores)
        out_exh = out_exh.at[step].set(exhausted)
        out_feas = out_feas.at[step].set(feas_count)

        return (used, coll, free_p, dev_slots,
                remaining - chunk_i.sum(), step + 1, valid,
                out_widx, out_chunk, out_ti, out_ts, out_exh, out_feas)

    d = capacity.shape[1]
    state0 = (used0, tg_coll0, free_ports, dev_slots0, k_valid,
              jnp.int32(0), jnp.bool_(True),
              jnp.full((max_steps, w), -1, jnp.int32),
              jnp.zeros((max_steps, w), jnp.int32),
              jnp.full((max_steps, TOP_K), -1, jnp.int32),
              jnp.full((max_steps, TOP_K), NEG_INF, jnp.float32),
              jnp.zeros((max_steps, d), jnp.int32),
              jnp.zeros(max_steps, jnp.int32))
    out = jax.lax.while_loop(cond, body, state0)
    (used, coll, free_p, dev_slots, remaining, steps, _alive,
     out_widx, out_chunk, out_ti, out_ts, out_exh, out_feas) = out
    # ONE int payload + one float payload crosses the tunnel: per-array
    # device->host copies each cost a tunnel op, which dwarfs the bytes
    packed_i = jnp.concatenate(
        [out_widx, out_chunk, out_ti, out_exh, out_feas[:, None],
         jnp.broadcast_to(remaining[None, None], (max_steps, 1)),
         jnp.broadcast_to(steps[None, None], (max_steps, 1))], axis=1)
    return ((used, coll, free_p, dev_slots), (packed_i, out_ts))


_select_kway = partial(jax.jit, static_argnames=("max_steps",
                                                 "spread_alg",
                                                 "w"))(_kway_core)

# Multi-eval batching (SURVEY §2.6 row 1: "batch multiple evals per
# device dispatch"): B independent placement problems over ONE shared
# node-capacity table run as a single dispatch — over a tunneled device
# this amortizes the per-op latency across the whole eval batch, and on
# a local chip it raises utilization the same way.
_KWAY_BATCH_AXES = (None,) + (0,) * 15


@partial(jax.jit, static_argnames=("max_steps", "spread_alg", "w"))
def _select_kway_batched(capacity, used0, feasible, ask, k_valid,
                         tg_coll0, penalty, affinity_norm, desired_count,
                         port_need, free_ports, port_ok,
                         dev_slots0, dev_score, dev_fires, pre_score,
                         *, max_steps: int, spread_alg: bool, w: int):
    fn = partial(_kway_core, max_steps=max_steps, spread_alg=spread_alg,
                 w=w)
    return jax.vmap(fn, in_axes=_KWAY_BATCH_AXES)(
        capacity, used0, feasible, ask, k_valid,
        tg_coll0, penalty, affinity_norm, desired_count,
        port_need, free_ports, port_ok,
        dev_slots0, dev_score, dev_fires, pre_score)


# Kinds for each packed argument: how its leading axis shards over a
# node-axis mesh (parallel/sharded.py). "node"=[N], "node2"=[N,d],
# "code"=[S,N] style, "rep"=replicated small state, "scalar"=0-d.
PACK_SHARD_KINDS = {
    "capacity": "node2", "used0": "node2", "feasible": "node",
    "ask": "rep", "k_valid": "scalar",
    "tg_coll0": "node", "job_count0": "node",
    "distinct_hosts_flag": "scalar", "scan_exclusive": "scalar",
    "penalty": "node", "affinity_norm": "node", "desired_count": "scalar",
    "port_need": "scalar", "free_ports": "node", "port_ok": "node",
    "dev_slots0": "node", "dev_score": "node", "dev_fires": "scalar",
    "pre_score": "node",
    "sp_codes": "code", "sp_counts0": "rep", "sp_present0": "rep",
    "sp_desired": "rep", "sp_weight": "rep", "sp_has_targets": "rep",
    "sp_valid": "rep", "sum_spread_w": "scalar",
    "dp_codes": "code", "dp_counts0": "rep", "dp_limit": "rep",
    "dp_valid": "rep",
}

MAX_SCAN_STEPS = 65536
# counts at or below this take the vmapped-scan arm of select_many
SCAN_BATCH_MAX = 256
# max lanes one micro-batch gateway fire ships in a single vmapped
# dispatch (server/worker.py MicroBatchGateway). Together with
# _pad_and_stack's power-of-two lane padding this bounds the distinct
# (arm, n_pad, lanes) trace signatures micro-batching can mint to
# {2, 4, 8, 16} per shape bucket — the lint.recompiles gauge stays
# bounded no matter how occupancy fluctuates per window
GATEWAY_MAX_LANES = 16

# process-wide sharded dispatcher (see get_shared_sharded)

_SHARED_SHARDED = None
_SHARED_SHARDED_LOCK = make_lock()


def get_shared_sharded():
    """The ONE process-wide ShardedSelect, created on first demand when
    mesh routing is configured (NOMAD_TPU_MESH=1 forces it; auto
    engages on multi-device accelerator backends), else None.
    Process-wide because PlacementEngines (and their kernels) are
    rebuilt per eval — the mesh and the mesh-resident node table
    (parallel/sharded_table.py) must outlive them or the 'resident
    across evals' property is fiction. The env gate is re-read per
    call, so tests flipping NOMAD_TPU_MESH get the answer they asked
    for while the dispatcher (and its resident state) persists."""
    import os
    want = os.environ.get("NOMAD_TPU_MESH", "auto")
    if want in ("0", "off", "no"):
        return None
    try:
        n_dev = len(jax.devices())
    except Exception:
        return None
    force = want in ("1", "on", "force")
    auto = (want == "auto" and n_dev > 1
            and jax.default_backend() != "cpu")
    if n_dev > 1 and (force or auto):
        global _SHARED_SHARDED
        # check-then-set under a lock: the cold-start prefetch thread
        # (NodeTableCache.prefetch_device) races the first worker eval
        # here, and a losing duplicate would pin a second resident
        # column set across the mesh while splitting the stats
        with _SHARED_SHARDED_LOCK:
            if _SHARED_SHARDED is None:
                from ..parallel.sharded import ShardedSelect, make_mesh
                _SHARED_SHARDED = ShardedSelect(make_mesh())
        return _SHARED_SHARDED
    return None


def mesh_stats_snapshot() -> Dict[str, object]:
    """Mesh residency economics for the governor's mesh.* gauges, the
    telemetry device.* family, and the bench artifact: device count,
    resident bytes (total and per device), reshard uploads/bytes,
    delta scatters, resident hits/stale misses, and the capacity-cache
    fallback accounting. Empty dict until a mesh dispatcher exists —
    readers treat absence as 'mesh off'."""
    sh = _SHARED_SHARDED
    if sh is None:
        return {}
    return sh.stats_snapshot()


def pack_request(req: SelectRequest, n_pad: int):
    """Pad/pack a SelectRequest into the _select_scan argument dict
    (keys match the kernel's parameter names; PACK_SHARD_KINDS describes
    each argument's sharding axis). Shared by the single-device kernel
    wrapper and the mesh-sharded dispatcher."""
    if req.count > MAX_SCAN_STEPS:
        raise ValueError(
            f"count={req.count} exceeds the scan cap of {MAX_SCAN_STEPS}; "
            f"split the placement batch")
    n = len(req.feasible)
    # device economics (ISSUE 11): every pack ships n_pad rows for n
    # live ones — the pad-waste ratio the validation campaign reads
    _note_pack(n, n_pad)

    def pad1(a, fill=0.0, dtype=np.float32):
        out = np.full(n_pad, fill, dtype=dtype)
        out[:n] = a
        return out

    def pad2(a):
        out = np.zeros((n_pad, a.shape[1]), dtype=np.float32)
        out[:n] = a
        return out

    if req.affinity is not None and req.affinity_sum_weights > 0:
        affinity_norm = pad1(req.affinity / req.affinity_sum_weights)
    else:
        affinity_norm = np.zeros(n_pad, dtype=np.float32)

    s_live = min(len(req.spreads), S_MAX)
    c_axis = C_MAX + 1
    sp_codes = np.full((S_MAX, n_pad), C_MAX, dtype=np.int32)
    sp_counts = np.zeros((S_MAX, c_axis), dtype=np.float32)
    sp_present = np.zeros((S_MAX, c_axis), dtype=bool)
    sp_desired = np.full((S_MAX, c_axis), -1.0, dtype=np.float32)
    sp_weight = np.zeros(S_MAX, dtype=np.float32)
    sp_has_targets = np.zeros(S_MAX, dtype=bool)
    sp_valid = np.zeros(S_MAX, dtype=bool)
    for s, sp in enumerate(req.spreads[:S_MAX]):
        m = len(sp["codes"])
        sp_codes[s, :m] = np.minimum(sp["codes"], C_MAX)
        c = min(len(sp["counts"]), c_axis)
        sp_counts[s, :c] = sp["counts"][:c]
        sp_present[s, :c] = sp["present"][:c]
        sp_desired[s, :c] = sp["desired"][:c]
        sp_weight[s] = sp["weight"]
        sp_has_targets[s] = sp["has_targets"]
        sp_valid[s] = True

    p_live = min(len(req.distinct_props), P_MAX)
    dp_codes = np.full((P_MAX, n_pad), C_MAX, dtype=np.int32)
    dp_counts = np.zeros((P_MAX, c_axis), dtype=np.float32)
    dp_limit = np.zeros(P_MAX, dtype=np.float32)
    dp_valid = np.zeros(P_MAX, dtype=bool)
    for p, dp in enumerate(req.distinct_props[:P_MAX]):
        m = len(dp["codes"])
        dp_codes[p, :m] = np.minimum(dp["codes"], C_MAX)
        c = min(len(dp["counts"]), c_axis)
        dp_counts[p, :c] = dp["counts"][:c]
        dp_limit[p] = dp["limit"]
        dp_valid[p] = True

    # scalars stay host-side numpy: a jnp scalar would be committed to
    # the default backend and poison cross-backend dispatch with
    # device-to-device transfers (catastrophic over a tunneled TPU)
    args = dict(
        capacity=pad2(req.capacity),
        used0=pad2(req.used),
        feasible=pad1(req.feasible, False, bool),
        ask=np.asarray(req.ask, np.float32),
        k_valid=np.int32(req.count),
        tg_coll0=pad1(req.tg_collisions, 0, np.int32),
        job_count0=pad1(req.job_count, 0, np.int32),
        distinct_hosts_flag=np.float32(1.0 if req.distinct_hosts else 0.0),
        scan_exclusive=np.float32(1.0 if req.scan_exclusive else 0.0),
        penalty=pad1(req.penalty if req.penalty is not None
                     else np.zeros(n, bool), False, bool),
        affinity_norm=affinity_norm,
        desired_count=np.float32(req.desired_count),
        port_need=np.float32(req.port_need),
        free_ports=pad1(req.free_ports if req.free_ports is not None
                        else np.full(n, 1e9, np.float32)),
        port_ok=pad1(req.port_ok if req.port_ok is not None
                     else np.ones(n, bool), False, bool),
        dev_slots0=pad1(req.dev_slots if req.dev_slots is not None
                        else np.full(n, 1e9, np.float32)),
        dev_score=pad1(req.dev_score if req.dev_score is not None
                       else np.zeros(n, np.float32)),
        dev_fires=np.float32(1.0 if req.dev_fires else 0.0),
        pre_score=pad1(req.pre_score if req.pre_score is not None
                       else np.zeros(n, np.float32)),
        sp_codes=sp_codes, sp_counts0=sp_counts, sp_present0=sp_present,
        sp_desired=sp_desired, sp_weight=sp_weight,
        sp_has_targets=sp_has_targets, sp_valid=sp_valid,
        sum_spread_w=np.float32(req.sum_spread_weights),
        dp_codes=dp_codes, dp_counts0=dp_counts, dp_limit=dp_limit,
        dp_valid=dp_valid,
    )
    statics = dict(spread_alg=(req.algorithm == "spread"),
                   s_live=s_live, p_live=p_live)
    return args, statics


def _note_trace(arm: str, n_pad: int, **statics) -> bool:
    """Report this dispatch's compile key to the recompile counter
    (analysis/sanitizer.py): a NEW (arm, shape-bucket, statics) tuple
    means XLA traced and compiled. Always on — the cost is one set
    lookup — so the `nomad.lint.recompiles` governor gauge sees storms
    in production, not just under the sanitizer. Returns True when the
    signature is fresh (this dispatch pays the compile): the caller
    passes that to cost_model.observe so a compile wall is NEVER
    blended into a steady-state EWMA — per-key first-sample
    replacement can absorb only ONE compile, but one (arm, n_pad) key
    folds many lane/step buckets that each compile separately (the r11
    warm-loop pollution: three batched lane widths pushed
    chunked_batched@2048 to 72 ms 'steady state' and demoted every
    lane)."""
    from ..analysis.sanitizer import traces
    return traces.note(arm, (n_pad,) + tuple(sorted(statics.items())))


def _sanitize_request(req: SelectRequest) -> None:
    """NOMAD_TPU_SANITIZE=1 boundary guard: NaN/Inf screens on the
    columns this dispatch ships — a NaN in `used` silently wins every
    argmax (checkify analog, host-side so the device never pays)."""
    from ..analysis import sanitizer
    if not sanitizer.enabled():
        return
    sanitizer.check_finite(
        "select.request", capacity=req.capacity, used=req.used,
        ask=np.asarray(req.ask, np.float32),
        free_ports=req.free_ports, dev_slots=req.dev_slots)


def _sanitize_result(req: SelectRequest,
                     res: SelectResult) -> SelectResult:
    """NOMAD_TPU_SANITIZE=1 boundary guard on the unpacked result:
    chosen rows must be real table rows and scores finite."""
    from ..analysis import sanitizer
    if not sanitizer.enabled():
        return res
    n = len(req.feasible)
    idx = res.node_idx
    if idx.size:
        lo, hi = int(idx.min()), int(idx.max())
        if lo < -1 or hi >= n:
            raise sanitizer.SanitizerError(
                f"sanitizer[select.result]: node_idx range [{lo}, {hi}]"
                f" outside [-1, {n}) — the kernel chose a padding row")
    sanitizer.check_finite("select.result",
                           final_score=res.final_score)
    return res


def _stage_get(outs):
    """jax.device_get with bench attribution: result transfers are the
    `d2h` stage of the per-stage breakdown (the wall includes any
    remaining device compute — jax blocks the transfer on it — so d2h
    nests inside the kernel-stage window; see utils/stages)."""
    from ..utils import stages
    if not stages.enabled:
        return jax.device_get(outs)
    import time as _time
    t0 = _time.perf_counter()
    vals = jax.device_get(outs)
    stages.add("d2h", _time.perf_counter() - t0)
    return vals


def unpack_result(req: SelectRequest, outs) -> SelectResult:
    # ONE batched transfer: per-array np.asarray would serialize a
    # ~100ms device round trip per output over a tunneled TPU
    (choices, finals, s_bin, s_anti, s_pen, s_aff, s_spread, s_dev, s_pre,
     top_idx, top_scores, exhausted, _ok_counts) = _stage_get(outs)
    # meta rows (top-k, exhaustion) are materialized only on the first
    # and failing steps; forward-fill the sentinels in between
    sentinel = exhausted[:, 0] < 0
    if sentinel.any():
        top_idx = top_idx.copy()
        top_scores = top_scores.copy()
        exhausted = exhausted.copy()
        last = 0
        for s in range(len(exhausted)):
            if sentinel[s]:
                top_idx[s] = top_idx[last]
                top_scores[s] = top_scores[last]
                exhausted[s] = exhausted[last]
            else:
                last = s
    n = len(req.feasible)
    kk = req.count
    choices = choices[:kk]
    from ..analysis import sanitizer as _san
    if _san.enabled() and choices.size and int(choices.max()) >= n:
        # must run BEFORE the defensive clamp below, or a kernel bug
        # that picks a padding row is laundered into a benign
        # "unplaced" -1 and the guard never fires
        raise _san.SanitizerError(
            f"sanitizer[select.result]: kernel chose padding row "
            f"{int(choices.max())} (table has {n} rows)")
    choices = np.where(choices >= n, -1, choices)  # padding lanes
    placed = int((choices >= 0).sum())
    top_idx = np.where(top_idx >= n, -1, top_idx)
    return _sanitize_result(req, SelectResult(
        node_idx=choices,
        final_score=finals[:kk],
        scores={"binpack": s_bin[:kk], "job-anti-affinity": s_anti[:kk],
                "node-reschedule-penalty": s_pen[:kk],
                "node-affinity": s_aff[:kk],
                "allocation-spread": s_spread[:kk],
                "devices": s_dev[:kk],
                "preemption": s_pre[:kk]},
        top_idx=top_idx[:kk], top_scores=top_scores[:kk],
        nodes_evaluated=(req.n_considered if req.n_considered is not None
                         else n),
        nodes_filtered=int((req.n_considered if req.n_considered is not None
                            else n) - np.count_nonzero(req.feasible)),
        exhausted_dim=exhausted[:kk],
        placed=placed,
    ))


_CHUNKED_ARGS = ("capacity", "used0", "feasible", "ask", "k_valid",
                 "tg_coll0", "penalty", "affinity_norm", "desired_count",
                 "port_need", "free_ports", "port_ok",
                 "dev_slots0", "dev_score", "dev_fires", "pre_score")


def _node_local_scores_np(req: SelectRequest, c: int, start: int,
                          m: int):
    """Scores of sub-placements start..start+m-1 on node c, float32,
    identical math to the kernels (_local_final_score)."""
    ask = np.asarray(req.ask, np.float32)
    a = np.arange(m, dtype=np.float32)
    after = (req.used[c].astype(np.float32)[None, :]
             + (start + a[:, None] + 1.0) * ask)
    cap_cpu = np.float32(max(req.capacity[c, 0], 1e-9))
    cap_mem = np.float32(max(req.capacity[c, 1], 1e-9))
    free_cpu = np.float32(1.0) - after[:, 0] / cap_cpu
    free_mem = np.float32(1.0) - after[:, 1] / cap_mem
    total = (np.power(np.float32(10.0), free_cpu)
             + np.power(np.float32(10.0), free_mem))
    if req.algorithm == "spread":
        fit_score = np.clip(total - 2.0, 0.0, 18.0)
    else:
        fit_score = np.clip(20.0 - total, 0.0, 18.0)
    binp = (fit_score / np.float32(18.0)).astype(np.float32)
    desired = np.float32(max(req.desired_count, 1.0))
    coll = np.float32(req.tg_collisions[c]) + np.float32(start) + a
    anti_fires = coll > 0
    anti = np.where(anti_fires, -(coll + 1.0) / desired,
                    0.0).astype(np.float32)
    pen_f = bool(req.penalty[c]) if req.penalty is not None else False
    pen = np.float32(-1.0 if pen_f else 0.0)
    if req.affinity is not None and req.affinity_sum_weights > 0:
        aff = np.float32(req.affinity[c] / req.affinity_sum_weights)
    else:
        aff = np.float32(0.0)
    dev = np.float32(req.dev_score[c]) if req.dev_fires \
        and req.dev_score is not None else np.float32(0.0)
    pre = np.float32(req.pre_score[c]) if req.pre_score is not None \
        else np.float32(0.0)
    fired = (1.0 + anti_fires.astype(np.float32)
             + np.float32(1.0 if pen_f else 0.0)
             + np.float32(1.0 if aff != 0.0 else 0.0)
             + np.float32(1.0 if req.dev_fires else 0.0)
             + np.float32(1.0 if pre != 0.0 else 0.0))
    fin = ((binp + anti + pen + aff + dev + pre) / fired).astype(np.float32)
    return fin, binp, anti, pen, aff, dev, pre


def _node_local_scores_batch(req: SelectRequest, cs, starts, ms):
    """All winners of a phase at once: float32 score streams shaped
    [W, max_m] with the SAME op order and dtypes as
    _node_local_scores_np, so results stay bit-identical — the
    per-winner call overhead (30 tiny numpy ops each) dominated
    multi-batch expansion."""
    cs = np.asarray(cs, np.int32)
    starts = np.asarray(starts, np.float32)
    ms = np.asarray(ms, np.int32)
    max_m = int(ms.max()) if len(ms) else 0
    ask = np.asarray(req.ask, np.float32)
    a = np.arange(max_m, dtype=np.float32)
    # [W, max_m, D]
    after = (req.used[cs].astype(np.float32)[:, None, :]
             + (starts[:, None] + a[None, :] + 1.0)[:, :, None] * ask)
    cap = np.maximum(req.capacity[cs].astype(np.float32), 1e-9)
    free_cpu = np.float32(1.0) - after[:, :, 0] / cap[:, None, 0]
    free_mem = np.float32(1.0) - after[:, :, 1] / cap[:, None, 1]
    total = (np.power(np.float32(10.0), free_cpu)
             + np.power(np.float32(10.0), free_mem))
    if req.algorithm == "spread":
        fit_score = np.clip(total - 2.0, 0.0, 18.0)
    else:
        fit_score = np.clip(20.0 - total, 0.0, 18.0)
    binp = (fit_score / np.float32(18.0)).astype(np.float32)
    desired = np.float32(max(req.desired_count, 1.0))
    coll = (req.tg_collisions[cs].astype(np.float32)[:, None]
            + starts[:, None] + a[None, :])
    anti_fires = coll > 0
    anti = np.where(anti_fires, -(coll + 1.0) / desired,
                    0.0).astype(np.float32)
    pen_f = req.penalty[cs].astype(bool) if req.penalty is not None \
        else np.zeros(len(cs), bool)
    pen_v = np.where(pen_f, np.float32(-1.0), np.float32(0.0))
    if req.affinity is not None and req.affinity_sum_weights > 0:
        aff_v = (req.affinity[cs] / req.affinity_sum_weights
                 ).astype(np.float32)
    else:
        aff_v = np.zeros(len(cs), np.float32)
    if req.dev_fires and req.dev_score is not None:
        dev_v = req.dev_score[cs].astype(np.float32)
    else:
        dev_v = np.zeros(len(cs), np.float32)
    pre_v = req.pre_score[cs].astype(np.float32) \
        if req.pre_score is not None else np.zeros(len(cs), np.float32)
    fired = (1.0 + anti_fires.astype(np.float32)
             + pen_f.astype(np.float32)[:, None]
             + (aff_v != 0.0).astype(np.float32)[:, None]
             + np.float32(1.0 if req.dev_fires else 0.0)
             + (pre_v != 0.0).astype(np.float32)[:, None])
    fin = ((binp + anti + pen_v[:, None] + aff_v[:, None]
            + dev_v[:, None] + pre_v[:, None]) / fired).astype(np.float32)
    return fin, binp, anti, pen_v, aff_v, dev_v, pre_v


def _kway_merge_py(fin_m, nodes_v, len_v, limit):
    """Streaming k-way merge, python fallback: pop the stream whose
    CURRENT head score is max (ties -> lowest node id), advance that
    stream. Streams are NOT monotonic (binpack scores rise as a node
    fills), so this is a true merge, not a sort."""
    import heapq
    heap = []
    for k in range(len(nodes_v)):
        if len_v[k] > 0:
            heapq.heappush(heap, (-float(fin_m[k, 0]),
                                  int(nodes_v[k]), k, 0))
    ok: List[int] = []
    oj: List[int] = []
    while heap and len(ok) < limit:
        _negs, node, k, j = heapq.heappop(heap)
        ok.append(k)
        oj.append(j)
        if j + 1 < len_v[k]:
            heapq.heappush(heap, (-float(fin_m[k, j + 1]), node,
                                  k, j + 1))
    return np.asarray(ok, np.int32), np.asarray(oj, np.int32)


def _kway_merge(fin_m, nodes_v, len_v, limit):
    """The per-phase greedy merge; native (native/kway.cpp) when
    available — the python heap costs ~3-5us/instance and dominated
    multi-batch expansion."""
    from ..native import load_kway
    mod = load_kway()
    if mod is None:
        return _kway_merge_py(fin_m, nodes_v, len_v, limit)
    out = mod.merge(np.ascontiguousarray(fin_m, np.float32).tobytes(),
                    nodes_v.astype(np.int32).tobytes(),
                    len_v.astype(np.int32).tobytes(),
                    fin_m.shape[1], int(limit))
    pairs = np.frombuffer(out, np.int32)
    p = len(pairs) // 2
    return pairs[:p].copy(), pairs[p:].copy()


def _expand_kway(req: SelectRequest, rounds) -> SelectResult:
    """Expand per-phase (winners, chunks) into the exact per-instance
    greedy sequence: within a phase every winner's next-score beats the
    waterline, so true greedy order is the streaming merge of the
    winners' score streams (max CURRENT head first, ties to the lowest
    node index) — identical to the scan's argmax sequence."""
    n = len(req.feasible)
    k_total = req.count
    d = req.capacity.shape[1]

    node_idx = np.full(k_total, -1, np.int32)
    final = np.zeros(k_total, np.float32)
    comp = {name: np.zeros(k_total, np.float32)
            for name in ("binpack", "job-anti-affinity",
                         "node-reschedule-penalty", "node-affinity",
                         "devices", "preemption")}
    top_i = np.full((k_total, TOP_K), -1, np.int32)
    top_s = np.full((k_total, TOP_K), NEG_INF, np.float32)
    exh_out = np.zeros((k_total, d), np.int32)

    pos = 0
    extra: Dict[int, int] = {}          # node -> placed so far overall
    last_meta = None
    fail = None
    for (widx, chunk, ti, ts, exh, _feas) in rounds:
        for s in range(len(widx)):
            if exh[s][0] >= 0:
                last_meta = (ti[s], ts[s], exh[s])
            winners = [(int(widx[s][w]), int(chunk[s][w]))
                       for w in range(widx.shape[1])
                       if chunk[s][w] > 0 and widx[s][w] >= 0]
            if not winners:
                fail = last_meta
                continue
            # score streams for ALL winners of this phase in one
            # vectorized shot ([W, max_m]; rows past each winner's m
            # are garbage the merge never reads)
            nodes_v = np.asarray([c for c, _m in winners], np.int32)
            len_v = np.asarray([mm for _c, mm in winners], np.int32)
            starts_v = np.asarray([extra.get(c, 0)
                                   for c, _m in winners], np.float32)
            for c, mm in winners:
                extra[c] = extra.get(c, 0) + mm
            fin_m, bin_m, anti_m, pen_v, aff_v, dev_v, pre_v = \
                _node_local_scores_batch(req, nodes_v, starts_v, len_v)
            ok, oj = _kway_merge(fin_m, nodes_v, len_v, k_total - pos)
            m = len(ok)
            if m == 0:
                continue
            sl = slice(pos, pos + m)
            node_idx[sl] = nodes_v[ok]
            final[sl] = fin_m[ok, oj]
            comp["binpack"][sl] = bin_m[ok, oj]
            comp["job-anti-affinity"][sl] = anti_m[ok, oj]
            comp["node-reschedule-penalty"][sl] = pen_v[ok]
            comp["node-affinity"][sl] = aff_v[ok]
            comp["devices"][sl] = dev_v[ok]
            comp["preemption"][sl] = pre_v[ok]
            m_ti, m_ts, m_exh = last_meta if last_meta is not None else \
                (np.full(TOP_K, -1, np.int32), np.full(TOP_K, NEG_INF),
                 np.zeros(d, np.int32))
            top_i[sl] = np.where(np.asarray(m_ti) >= n, -1,
                                 np.asarray(m_ti))[None, :]
            top_s[sl] = np.asarray(m_ts)[None, :]
            exh_out[sl] = np.maximum(np.asarray(m_exh), 0)[None, :]
            pos += m
    if fail is not None and pos < k_total:
        ti_f, ts_f, exh_f = fail
        top_i[pos:] = np.where(np.asarray(ti_f) >= n, -1, np.asarray(ti_f))
        top_s[pos:] = ts_f
        exh_out[pos:] = exh_f

    considered = req.n_considered if req.n_considered is not None else n
    comp["allocation-spread"] = np.zeros(k_total, np.float32)
    return _sanitize_result(req, SelectResult(
        node_idx=node_idx,
        final_score=final,
        scores=comp,
        top_idx=top_i, top_scores=top_s,
        nodes_evaluated=considered,
        nodes_filtered=int(considered - np.count_nonzero(req.feasible)),
        exhausted_dim=exh_out,
        placed=pos,
    ))

class DispatchCostModel:
    """Measured per-shape dispatch costs, replacing the static step
    constants once warm.

    Every device phase (dispatch through result transfer) of the solo
    and batched kernel arms reports its wall clock here, keyed by
    (arm, n_pad) — batched arms report seconds PER LANE so solo and
    batched numbers compare directly. The batching and host/accel
    routing decisions then rest on what THIS host+device pair actually
    measured at this table shape rather than on constants calibrated
    on different hardware (BENCH_r05: the static model demoted every
    broker lane on real TPU — service_broker_batches=0 — while the
    shapes it demoted measured 1.42-1.61x when they fired).

    Exploration: a batched arm that is never dispatched is never
    measured, so when solo numbers are warm and batched ones are cold
    the profitability question returns True once every PROBE_EVERY
    calls — and a batched arm that measured SLOWER keeps being probed
    at the same cadence, so a stale number (e.g. one taken while the
    device was busy) cannot demote lanes forever.

    Methodology (recorded for re-anchor audits, STATUS.md §2.6): EWMA
    with alpha=0.25 over per-lane seconds, minimum 3 samples before a
    measured number overrides a formula, count variation deliberately
    folded into the EWMA (per-shape means per (arm, table size) — the
    steady state re-dispatches the same shapes, which is exactly when
    the numbers matter). Compile walls are excluded at the source: a
    dispatch that mints a NEW trace signature (_note_trace) reports
    with compiled=True and never enters the EWMA — a seconds-long
    compile would otherwise dominate it for many rounds, and one
    (arm, n_pad) key folds many separately-compiling lane/step
    buckets. Timing windows include per-request host unpack/expand on
    both the solo and batched arms, so the comparison is end-to-end
    per lane, not device-dispatch-only."""

    ALPHA = 0.25
    MIN_SAMPLES = 3
    PROBE_EVERY = 16

    def __init__(self):
        self._l = make_lock()
        self._stats: Dict[Tuple[str, int], List[float]] = {}
        self._probe = 0

    def observe(self, arm: str, n_pad: int, seconds: float,
                lanes: int = 1, compiled: bool = False) -> None:
        from ..utils import stages
        if stages.enabled:
            # every arm reports its dispatch wall here — one choke
            # point doubles as the bench's `kernel` stage accumulator
            # AND the flight recorder's kernel-span emitter: solo arms
            # attribute to the dispatching eval's thread context, a
            # gateway fire fans the shared span out to every lane's
            # trace, each carrying (arm, n_pad, lanes, fresh-compile)
            stages.add("kernel", seconds)
            from ..trace import emit_kernel
            emit_kernel(arm, n_pad, seconds, lanes=lanes,
                        fresh=compiled)
        # device economics (ISSUE 11): per-arm dispatch seconds and
        # fresh-compile counts, exported via nomad.device.* gauges and
        # the bench artifact — always on, like the recompile counter
        _note_dispatch(arm, seconds, compiled)
        key = (arm, n_pad)
        if compiled:
            # this dispatch minted a new trace signature (_note_trace):
            # its wall includes XLA compile and must not enter the
            # steady-state EWMA at all — one (arm, n_pad) key folds
            # many lane/step buckets that each compile separately, so
            # no single-replacement scheme could absorb them. The skip
            # also satisfies a restored entry's seeded marker: the
            # compile this restore was bracing for just happened
            with self._l:
                ent = self._stats.get(key)
                if ent is not None and len(ent) > 2:
                    ent[2] = False
            return
        per_lane = seconds / max(lanes, 1)
        with self._l:
            ent = self._stats.get(key)
            if ent is None:
                # compile walls never reach this point, so the first
                # recorded sample is already a steady-state one
                self._stats[key] = [per_lane, 1]
            elif len(ent) > 2 and ent[2]:
                # entry restored from a persisted snapshot whose
                # this-process compile was NOT caught by the trace
                # rule (e.g. the shape was traced earlier in-process):
                # drop one sample defensively rather than blend a
                # possible compile wall into a good persisted EWMA
                ent[2] = False
            else:
                ent[0] += self.ALPHA * (per_lane - ent[0])
                ent[1] += 1

    def estimate(self, arm: str, n_pad: int) -> Optional[float]:
        ent = self._stats.get((arm, n_pad))
        if ent is None or ent[1] < self.MIN_SAMPLES:
            return None
        return ent[0]

    def best(self, arms, n_pad: int) -> Optional[float]:
        vals = [v for v in (self.estimate(a, n_pad) for a in arms)
                if v is not None]
        return min(vals) if vals else None

    def probe_due(self) -> bool:
        with self._l:
            self._probe += 1
            return self._probe % self.PROBE_EVERY == 0

    # -- seeding (ISSUE 7: kill the cold start) ------------------------
    def seed(self, arm: str, n_pad: int, seconds: float,
             lanes: int = 1) -> None:
        """Install a steady-state measurement at MIN_SAMPLES weight so
        the very first organic dispatch decision at this shape is
        measured, not cold. A seed never overrides an entry that is
        already warm from live traffic."""
        per_lane = seconds / max(lanes, 1)
        with self._l:
            ent = self._stats.get((arm, n_pad))
            if ent is None or ent[1] < self.MIN_SAMPLES:
                self._stats[(arm, n_pad)] = [per_lane, self.MIN_SAMPLES]

    def promote(self, n_pad: int) -> int:
        """Calibration epilogue: entries at this shape count as warm
        (samples -> MIN_SAMPLES) so routing engages off the
        calibration run instead of waiting for 3+ organic samples.
        Safe because compile walls never enter the stats at all
        (observe's `compiled` flag) — any recorded sample is a
        steady-state one."""
        bumped = 0
        with self._l:
            for (_arm, np_), ent in self._stats.items():
                if np_ == n_pad and 1 <= ent[1] < self.MIN_SAMPLES:
                    ent[1] = self.MIN_SAMPLES
                    bumped += 1
        return bumped

    def load_snapshot(self, snap: Dict[str, dict]) -> int:
        """Restore persisted measurements (the snapshot() format, JSON
        next to the WAL snapshot): each entry installs at MIN_SAMPLES
        weight with a seeded marker so the first live observation —
        which pays this process's XLA compile — is dropped instead of
        blended. Entries already warm from live traffic win over the
        file."""
        loaded = 0
        for key_s, ent_d in (snap or {}).items():
            try:
                arm, np_s = key_s.rsplit("@", 1)
                n_pad = int(np_s)
                ewma = float(ent_d["ewma_s"])
            except (ValueError, KeyError, TypeError, AttributeError):
                continue
            with self._l:
                ent = self._stats.get((arm, n_pad))
                if ent is None or ent[1] < self.MIN_SAMPLES:
                    self._stats[(arm, n_pad)] = [ewma, self.MIN_SAMPLES,
                                                 True]
                    loaded += 1
        return loaded

    def snapshot(self) -> Dict[str, dict]:
        with self._l:
            return {f"{arm}@{n_pad}": {"ewma_s": round(ent[0], 6),
                                       "samples": ent[1]}
                    for (arm, n_pad), ent in sorted(self._stats.items())}


SOLO_ARMS = ("chunked", "kway", "scan")
BATCHED_ARMS = ("chunked_batched", "kway_batched", "scan_batched")

# process-wide: every SelectKernel (workers, gateways, benches) feeds
# and reads the same measured numbers
cost_model = DispatchCostModel()


# -- device-economics accounting (ISSUE 11) ----------------------------
# The north star's device economics — pad waste, per-arm dispatch time,
# fresh compiles — were trapped inside pack_request/_note_trace/
# DispatchCostModel and never exported. These counters are ALWAYS on
# (the cost is two dict adds under a lock per pack/dispatch, next to
# milliseconds of numpy work); the telemetry collector
# (nomad_tpu/telemetry/) publishes them as `nomad.device.*` gauges and
# the bench artifact records the per-round snapshot.

_DEVICE_L = make_lock()
DEVICE_STATS: Dict[str, float] = {
    # Σ live rows vs Σ padded rows shipped: 1 - n/n_pad is the fraction
    # of every dispatch's node axis spent scoring padding
    "pad_n_sum": 0.0,
    "pad_npad_sum": 0.0,
    "packs": 0.0,
}
# per-arm accumulators: {arm: [dispatch_seconds_sum, dispatches,
# fresh_compiles]} — compile walls are INCLUDED in seconds (they are
# real wall clock the eval paid; the compile count alongside is what
# attributes them)
DEVICE_ARM_STATS: Dict[str, List[float]] = {}


def _note_pack(n: int, n_pad: int) -> None:
    with _DEVICE_L:
        DEVICE_STATS["pad_n_sum"] += n
        DEVICE_STATS["pad_npad_sum"] += n_pad
        DEVICE_STATS["packs"] += 1


def _note_dispatch(arm: str, seconds: float, compiled: bool) -> None:
    with _DEVICE_L:
        ent = DEVICE_ARM_STATS.get(arm)
        if ent is None:
            ent = DEVICE_ARM_STATS[arm] = [0.0, 0.0, 0.0]
        ent[0] += seconds
        ent[1] += 1
        if compiled:
            ent[2] += 1


def device_stats_snapshot() -> Dict[str, object]:
    """One read for the bench artifact and the telemetry collector:
    pad-waste ratio plus per-arm dispatch seconds / dispatch counts /
    fresh-compile counts."""
    with _DEVICE_L:
        n_sum = DEVICE_STATS["pad_n_sum"]
        np_sum = DEVICE_STATS["pad_npad_sum"]
        packs = DEVICE_STATS["packs"]
        arms = {a: list(v) for a, v in DEVICE_ARM_STATS.items()}
    return {
        "pad_waste_ratio": round(1.0 - (n_sum / np_sum), 4)
        if np_sum > 0 else 0.0,
        "pad_rows_live": n_sum,
        "pad_rows_shipped": np_sum,
        "packs": packs,
        "dispatch_s": {a: round(v[0], 4) for a, v in sorted(
            arms.items())},
        "dispatches": {a: int(v[1]) for a, v in sorted(arms.items())},
        "compiles": {a: int(v[2]) for a, v in sorted(arms.items())},
    }


def device_hbm_bytes() -> float:
    """Device HBM in use where the backend exposes it (jax
    memory_stats; TPU/GPU runtimes report bytes_in_use, CPU returns
    None/{}): 0.0 when unavailable. Host-side runtime introspection —
    no device sync involved."""
    try:
        stats = jax.devices()[0].memory_stats()
    except Exception:
        return 0.0
    if not stats:
        return 0.0
    return float(stats.get("bytes_in_use", 0.0))


def calibrate_cost_model(n: int, count: int = 16, lanes: int = 2,
                         kernel: Optional["SelectKernel"] = None
                         ) -> Dict[str, dict]:
    """Startup calibration probe (ISSUE 7): measure the solo and the
    batched dispatch arms at the live table shape with synthetic
    requests and seed the process-wide cost model, so batched lanes are
    cost-favored (or correctly demoted) from the FIRST organic dispatch
    instead of after 3+ organic samples — the 1-in-16 exploration probe
    never fires inside short scenarios (BENCH_r05:
    service_broker_batches=0 for the whole service run).

    Two dispatches per arm: the first pays XLA compile (the cost
    model's replace-first-sample rule discards it), the second is the
    steady-state number; promote() then lifts both arms to engagement
    weight. All timing flows through select()/select_many(), which
    block on the result transfer via the `_stage_get` fence — no raw
    host syncs here (lint: host-sync stays clean). Returns the cost
    model snapshot at this shape for logging/benches."""
    k = kernel or SelectKernel()
    n_pad = _pad_n(n)
    cap = np.tile(np.array([[4000.0, 8192.0, 102400.0, 1000.0]],
                           np.float32), (n, 1))
    ask = np.array([100.0, 100.0, 10.0, 0.0], np.float32)

    def req():
        return SelectRequest(
            ask=ask, count=count, feasible=np.ones(n, bool),
            capacity=cap, used=np.zeros_like(cap),
            desired_count=float(count),
            tg_collisions=np.zeros(n, np.int32),
            job_count=np.zeros(n, np.int32))

    lanes = max(2, min(int(lanes), GATEWAY_MAX_LANES))
    for _ in range(3):          # compile round, then steady state
        k.select(req())
        k.select_many([req() for _ in range(lanes)])
    cost_model.promote(n_pad)
    snap = cost_model.snapshot()
    return {key: v for key, v in snap.items()
            if key.rsplit("@", 1)[-1] == str(n_pad)}


_accel_rtt_cache: List[float] = []


def _accel_roundtrip_s() -> float:
    """Measured host<->accelerator round-trip latency (put + get of a
    tiny buffer). On a co-located chip this is ~0.1 ms; over a tunneled
    TPU it can be ~100-250 ms, which makes per-eval device dispatch a
    latency disaster — the router below uses this number to decide."""
    if _accel_rtt_cache:
        return _accel_rtt_cache[0]
    dev = jax.devices()[0]
    small = np.zeros(8, np.float32)
    # nomad-lint: allow[host-sync] intentional probe: the sync IS the RTT measurement
    jax.device_get(jax.device_put(small, dev))  # warm the path
    t0 = __import__("time").perf_counter()
    for _ in range(2):
        # nomad-lint: allow[host-sync] intentional probe: the sync IS the RTT measurement
        jax.device_get(jax.device_put(small, dev))
    rtt = max((__import__("time").perf_counter() - t0) / 2, 1e-5)
    _accel_rtt_cache.append(rtt)
    return rtt


def _cpu_device():
    try:
        return jax.local_devices(backend="cpu")[0]
    except RuntimeError:
        return None


def decorrelation_slice(req, lane: int, total: int, cache):
    """The one shared decorrelation rule (used by both the worker's
    solo-select slicing and the BatchGateway's lane partition): a
    Knuth-mix hash assigns each node to one of `total` lanes; the
    request keeps its lane's slice only when the slice's aggregate
    capacity headroom covers ~2x the ask (so slicing is a throughput
    heuristic, never a feasibility change — callers retry on the full
    set). Returns (slice_mask or None, new_cache); `cache` is the
    caller's (key, lane_ids) memo."""
    if total <= 1:
        return None, cache
    feas = req.feasible
    n = len(feas)
    cache_key, lane_ids = cache
    if cache_key != (n, total):
        mix = (np.arange(n, dtype=np.uint64)
               * np.uint64(2654435761)) & np.uint64(0xffffffff)
        lane_ids = ((mix >> np.uint64(7)) % np.uint64(total)) \
            .astype(np.int32)
        cache = ((n, total), lane_ids)
    slice_mask = feas & (lane_ids == (lane % total))
    if int(slice_mask.sum()) < 8:
        return None, cache
    free = req.capacity - req.used
    with np.errstate(divide="ignore", invalid="ignore"):
        per = np.where(req.ask[None, :] > 0,
                       free / np.maximum(req.ask[None, :], 1e-9),
                       np.inf).min(axis=1)
    headroom = float(np.floor(per[slice_mask]).clip(min=0).sum())
    if headroom < 2.0 * req.count:
        return None, cache
    return slice_mask, cache


def partition_lanes(reqs, lane_base: int, total: int, cache):
    """Decorrelate the lanes of ONE batched dispatch: identical argmax
    sequences would make every lane place on the same winners and
    collide in the plan applier (optimistic concurrency). Applies
    decorrelation_slice per lane — hash partition + capacity-aware
    headroom — mutating each request's feasible mask in place. Returns
    (originals, cache): the original masks (None where untouched) so a
    lane that can't fill its slice retries on the FULL set —
    partitioning is a throughput heuristic and must never change
    failure semantics. Shared by the per-batch rendezvous gateway and
    the micro-batch gateway (server/worker.py)."""
    lanes = len(reqs)
    total = max(total, lanes)
    originals = [None] * lanes
    if not reqs:
        return originals, cache
    n = len(reqs[0].feasible)
    for i, req in enumerate(reqs):
        if len(req.feasible) != n:
            continue
        slice_mask, cache = decorrelation_slice(
            req, lane_base + i, total, cache)
        if slice_mask is None:
            continue
        originals[i] = req.feasible
        req.feasible = slice_mask
        # the sliced mask no longer matches the device-resident copy
        req.feas_token = None
        req.feas_residue = None
    return originals, cache


class SelectKernel:
    """Host wrapper: pads request arrays, routes the dispatch to the
    best backend, and unpacks results.

    Routing (backend="auto"): when the default backend is an
    accelerator, small placements still run on the host CPU backend —
    a per-eval device dispatch costs two host<->device round trips
    (inputs + results), which only amortizes over large batches. The
    cost model compares measured round-trip latency against estimated
    step counts; NOMAD_TPU_SELECT_BACKEND=cpu|accel|auto overrides.

    Two device kernels:
      - _select_chunked: node-local scoring (no spread/distinct/
        reserved-port exclusivity) places whole chunks per step —
        O(nodes-touched) instead of O(count) sequential steps.
      - _select_scan: the general one-instance-per-step scan.
    """

    _ACCEL_STEP_S = 150e-6   # measured TPU scan-step cost (1k-16k nodes)
    _CPU_STEP_BASE_S = 25e-6
    _CPU_STEP_PER_NODE_S = 40e-9

    def __init__(self, backend: Optional[str] = None):
        import os
        self.backend = backend or os.environ.get(
            "NOMAD_TPU_SELECT_BACKEND", "auto")
        self._mesh_tried = False
        self._sharded = None
        # cross-worker decorrelation (lane, lanes): concurrent workers
        # running exact-greedy argmax over the SAME table pick the SAME
        # winners and collide in the plan applier. When set (by the
        # scheduling worker), large batch selects restrict themselves
        # to a hash-partitioned slice of the feasible set — the
        # columnar analog of the reference's per-eval node shuffle
        # (stack.go:70-90) — retrying on the full set if the slice
        # can't hold the ask.
        self.decorrelate = None
        self._decor_cache = (None, None)

    def _mesh_sharded(self):
        """The production multi-chip path (SURVEY §2.6: shard the node
        axis instead of sampling it): when more than one device is
        visible on an accelerator backend — or NOMAD_TPU_MESH=1 forces
        it (tests/dryrun on the virtual CPU mesh) — dispatches route
        through a jax.sharding.Mesh over all devices (the process-wide
        instance; see get_shared_sharded)."""
        if self._mesh_tried:
            return self._sharded
        self._mesh_tried = True
        self._sharded = get_shared_sharded()
        return self._sharded

    # -- routing -------------------------------------------------------
    def _pick_device(self, n: int, est_steps: int, arm: str = "chunked"):
        """Returns the CPU device to force host execution, or None to
        use the default (accelerator) placement. Prefers MEASURED
        per-shape dispatch costs (cost_model) over the static step
        constants once either side is warm at this table shape."""
        if jax.default_backend() == "cpu":
            return None                      # already on host
        if self.backend == "accel":
            return None
        cpu = _cpu_device()
        if cpu is None:
            return None
        if self.backend == "cpu":
            return cpu
        meas_accel = cost_model.estimate(arm, n)
        meas_cpu = cost_model.estimate(arm + "@cpu", n)
        if meas_accel is not None and meas_cpu is not None:
            # measured walls include d2h/unpack/continuation rounds the
            # step formulas omit — only compare like against like; a
            # lone measurement never overrides the formula pair
            return cpu if meas_cpu <= meas_accel else None
        est_cpu = est_steps * (self._CPU_STEP_BASE_S
                               + n * self._CPU_STEP_PER_NODE_S)
        est_accel = 2 * _accel_roundtrip_s() + est_steps * self._ACCEL_STEP_S
        return cpu if est_cpu <= est_accel else None

    @staticmethod
    def _place_args(args: Dict, dev) -> Dict:
        if dev is None:
            return args
        return {k: (jax.device_put(v, dev) if isinstance(v, np.ndarray)
                    and v.ndim > 0 else v)
                for k, v in args.items()}

    def _resident_args(self, req: SelectRequest, n_pad: int,
                       dev) -> Optional[Dict]:
        """Device-resident replacements for the table-shaped inputs
        (capacity, used0, free_ports) when the request's NodeTable
        carries a live mirror token (ops/device_table.py): capacity
        and free_ports come straight off the resident device arrays,
        and used0 is computed ON DEVICE as resident-used + the sparse
        per-eval plan overlay — no dense table column crosses the bus.
        Returns None (dense fallback) for stale tables, host-forced
        dispatches, or overlays too wide to scatter. Assembly shared
        with the mesh path (device_table.resident_request_args)."""
        if dev is not None:
            return None                 # mirror lives on the default device
        mirror = getattr(req.table, "device_mirror", None) \
            if req.table is not None else None
        if mirror is None:
            return None
        from .device_table import resident_request_args
        return resident_request_args(mirror, req, n_pad,
                                     "nomad.select.resident")

    # -- entry ---------------------------------------------------------
    def select(self, req: SelectRequest) -> SelectResult:
        original = self._decorrelate_mask(req)
        res = self._select(req)
        if original is not None and res.placed < req.count:
            # the slice couldn't hold the ask: decorrelation is a
            # throughput heuristic and must never change failure
            # semantics — retry on the full node set
            req.feasible = original
            res = self._select(req)
        return res

    def _decorrelate_mask(self, req: SelectRequest):
        """Restrict a large batch select to this worker's hash slice of
        the feasible set when the slice's aggregate headroom still
        covers ~2x the ask. Returns the original feasible mask (caller
        restores it on shortfall) or None when untouched."""
        dec = self.decorrelate
        if dec is None:
            return None
        lane, lanes = dec
        if req.count < 256:
            return None
        slice_mask, cache = decorrelation_slice(
            req, lane, lanes, self._decor_cache)
        self._decor_cache = cache
        if slice_mask is None:
            return None
        feas = req.feasible
        req.feasible = slice_mask
        req.feas_token = None
        req.feas_residue = None
        return feas

    def _select(self, req: SelectRequest) -> SelectResult:
        _sanitize_request(req)
        sharded = self._mesh_sharded()
        if sharded is not None:
            chunk_ok = (not req.spreads and not req.distinct_props
                        and not req.distinct_hosts
                        and not req.scan_exclusive)
            n_pad_sh = sharded.pad_to_shards(len(req.feasible))
            if chunk_ok and req.count > 512 and n_pad_sh > KWAY_W:
                # big batches keep the K-way kernel on the mesh: the
                # same SPMD program, node axis sharded, top-k/gather
                # collectives inserted by XLA; table-shaped columns
                # come off the mesh-resident table when the request
                # carries a live mirror token
                args, _statics = pack_request(req, n_pad_sh)
                cargs = sharded.place_chunked_args(
                    {k: args[k] for k in _CHUNKED_ARGS},
                    capacity_src=req.capacity, req=req)
                spread_alg = req.algorithm == "spread"
                w = _kway_w(n_pad_sh)
                with sharded.mesh:
                    pending = _select_kway(**cargs,
                                           max_steps=_kway_steps(w),
                                           spread_alg=spread_alg, w=w)
                return self._finish_kway(req, cargs, spread_alg, pending,
                                         w=w)
            return sharded.select(req)
        n = len(req.feasible)
        n_pad = _pad_n(n)
        chunk_ok = (not req.spreads and not req.distinct_props
                    and not req.distinct_hosts and not req.scan_exclusive)
        if chunk_ok:
            # chunked steps ~ nodes touched + overtakes, bounded by count
            est_steps = min(req.count, 2 * n)
            arm = "kway" if req.count > 512 and n_pad > KWAY_W \
                else "chunked"
            dev = self._pick_device(n_pad, est_steps, arm=arm)
            if arm == "kway":
                # big batches: K-way phases place on the top-32 nodes at
                # once — an order of magnitude fewer sequential steps
                return self._run_kway(req, n_pad, dev)
            return self._run_chunked(req, n_pad, dev)
        import time as _time
        dev = self._pick_device(n_pad, req.count, arm="scan")
        k = _bucket_k(max(req.count, 1))
        args, statics = pack_request(req, n_pad)
        args = self._place_args(args, dev)
        resident = self._resident_args(req, n_pad, dev)
        if resident:
            args.update(resident)
        fresh = _note_trace("scan", n_pad, k_steps=k,
                            cpu=dev is not None, **statics)
        t0 = _time.perf_counter()
        _carry, outs = _select_scan(**args, k_steps=k, **statics)
        out = unpack_result(req, outs)
        cost_model.observe("scan" + ("@cpu" if dev is not None else ""),
                           n_pad, _time.perf_counter() - t0,
                           compiled=fresh)
        return out

    # -- k-way chunked path --------------------------------------------
    def _pack_kway(self, req: SelectRequest, n_pad: int, dev):
        """Pack + place the K-way kernel args; returns
        (cargs, spread_alg, w). Split from the dispatch so the cost
        model's window starts at the dispatch, like the other arms."""
        args, _statics = pack_request(req, n_pad)
        cargs = {k: args[k] for k in _CHUNKED_ARGS}
        cargs = self._place_args(cargs, dev)
        resident = self._resident_args(req, n_pad, dev)
        if resident:
            cargs.update(resident)
        return cargs, req.algorithm == "spread", _kway_w(n_pad)

    def _finish_kway(self, req: SelectRequest, cargs, spread_alg,
                     pending, w: int) -> SelectResult:
        return _expand_kway(req, self._finish_kway_rounds(
            req, cargs, spread_alg, pending, w=w))

    def _run_kway(self, req: SelectRequest, n_pad: int,
                  dev) -> SelectResult:
        import time as _time
        cargs, spread_alg, w = self._pack_kway(req, n_pad, dev)
        fresh = _note_trace("kway", n_pad, max_steps=_kway_steps(w),
                            spread_alg=spread_alg, w=w,
                            cpu=dev is not None)
        # window matches every other arm: dispatch through
        # unpack/expand, packing/placement excluded
        t0 = _time.perf_counter()
        pending = _select_kway(**cargs, max_steps=_kway_steps(w),
                               spread_alg=spread_alg, w=w)
        rounds = self._finish_kway_rounds(req, cargs, spread_alg,
                                          pending, w=w)
        out = _expand_kway(req, rounds)
        cost_model.observe("kway" + ("@cpu" if dev is not None else ""),
                           n_pad, _time.perf_counter() - t0,
                           compiled=fresh)
        return out

    def select_many(self, reqs: List[SelectRequest]) -> List[SelectResult]:
        """Place B independent requests over the SAME node table in one
        device dispatch (vmapped K-way kernel) — multi-eval batching per
        SURVEY §2.6; the production caller is the worker's batched eval
        drain (server/worker.py process_eval_batch). Under mesh routing
        the batched kernel runs SPMD with the node axis sharded and the
        batch axis replicated. Falls back to sequential select() for
        shapes the K-way kernel doesn't cover — mixed capacity tables
        (evals against different snapshots) are counted on the
        nomad.select.batch_fallback metric so a silent serialization
        regression stays visible. Results are bit-identical to
        per-request select()."""
        if not reqs:
            return []
        for r in reqs:
            _sanitize_request(r)
        from ..utils import metrics
        sharded = self._mesh_sharded()
        n = len(reqs[0].feasible)
        n_pad = sharded.pad_to_shards(n) if sharded is not None \
            else _pad_n(n)
        shared_table = all(len(r.feasible) == n
                           and r.capacity is reqs[0].capacity
                           and r.algorithm == reqs[0].algorithm
                           for r in reqs)
        def _chunk_ok(r):
            return (not r.spreads and not r.distinct_props
                    and not r.distinct_hosts and not r.scan_exclusive)

        # small/medium chunk-eligible batches take the vmapped CHUNKED
        # kernel: steps ~ slowest lane's nodes-touched, the same
        # algorithm the solo path uses — batched without paying the
        # K-way phase machinery
        if len(reqs) > 1 and shared_table and \
                all(_chunk_ok(r) and r.count <= 512 for r in reqs):
            metrics.incr_counter("nomad.select.batch_dispatch")
            return self._run_chunked_batched(reqs, n_pad, sharded)

        # small-count batches needing the full scoring surface
        # (spreads, distinct-property, reserved ports) take the vmapped
        # SCAN — count is the step bound, so this stays cheap only for
        # small counts
        if len(reqs) > 1 and shared_table and \
                all(r.count <= SCAN_BATCH_MAX for r in reqs):
            metrics.incr_counter("nomad.select.batch_dispatch")
            return self._run_scan_batched(reqs, n_pad, sharded)

        eligible = (len(reqs) > 1 and n_pad > KWAY_W and shared_table
                    and all(_chunk_ok(r) for r in reqs))
        if not eligible:
            if len(reqs) > 1:
                # ANY multi-request batch that serializes is the
                # regression this counter exists to expose — mixed
                # snapshots (not shared_table) and shapes no batched
                # arm covers both count
                metrics.incr_counter("nomad.select.batch_fallback")
            return [self.select(r) for r in reqs]
        metrics.incr_counter("nomad.select.batch_dispatch")

        packs = [pack_request(r, n_pad)[0] for r in reqs]
        cargs = self._pad_and_stack(packs, _CHUNKED_ARGS)
        spread_alg = reqs[0].algorithm == "spread"
        cargs, mesh_ctx = self._place_batched(
            cargs, sharded, reqs[0].capacity, n_pad,
            sum(min(r.count, 2 * n) for r in reqs),
            table=reqs[0].table)
        w = _kway_w(n_pad)
        fresh = _note_trace("kway_batched", n_pad,
                            max_steps=_kway_steps(w),
                            spread_alg=spread_alg, w=w,
                            lanes=len(cargs["k_valid"]))
        import time as _time
        t0 = _time.perf_counter()
        with mesh_ctx:
            carry, outs = _select_kway_batched(**cargs,
                                               max_steps=_kway_steps(w),
                                               spread_alg=spread_alg,
                                               w=w)
        packed_i, ts = _stage_get(outs)
        d = reqs[0].capacity.shape[1]
        results = []
        for i, req in enumerate(reqs):
            pi = packed_i[i]
            widx = pi[:, :w]
            chunk = pi[:, w:2 * w]
            ti = pi[:, 2 * w:2 * w + TOP_K]
            exh = pi[:, 2 * w + TOP_K:2 * w + TOP_K + d]
            feas = pi[:, -3]
            rem = int(pi[0, -2])
            steps = int(pi[0, -1])
            rounds = [(widx[:steps], chunk[:steps], ti[:steps],
                       ts[i][:steps], exh[:steps], feas[:steps])]
            if rem > 0 and steps > 0 and chunk[steps - 1].sum() > 0:
                # rare overflow of the phase budget: continue this lane
                # on the single-request kernel from its carry state
                # host copies: the continuation runs on the default
                # single-device path even when the batch ran sharded —
                # pulled through the d2h fence so the bench attributes
                # the transfer (lint: host-sync)
                lane = {k: (np.asarray(_stage_get(cargs[k]))
                            if k == "capacity"
                            else np.asarray(_stage_get(cargs[k][i])))
                        for k in _CHUNKED_ARGS}
                used0, tg0, fp0, ds0 = _stage_get(
                    (carry[0][i], carry[1][i], carry[2][i],
                     carry[3][i]))
                lane.update(
                    used0=np.asarray(used0),
                    tg_coll0=np.asarray(tg0),
                    free_ports=np.asarray(fp0),
                    dev_slots0=np.asarray(ds0),
                    k_valid=np.int32(rem))
                pending = _select_kway(**lane,
                                       max_steps=_kway_steps(w),
                                       spread_alg=spread_alg, w=w)
                cont = self._finish_kway_rounds(req, lane, spread_alg,
                                                pending, w=w)
                rounds.extend(cont)
            results.append(_expand_kway(req, rounds))
        # window includes per-lane unpack/expand so the number compares
        # end-to-end against the solo arms (which include theirs)
        cost_model.observe("kway_batched", n_pad,
                           _time.perf_counter() - t0, lanes=len(reqs),
                           compiled=fresh)
        return results

    @staticmethod
    def _pad_and_stack(packs: List[Dict], arg_names) -> Dict:
        """Shared lane assembly for every batched arm: pad the lane
        axis to a power of two (each distinct B is its own XLA
        compile, remote over the tunnel — widths must land on warmable
        buckets; padding lanes carry k_valid=0 and place nothing) and
        stack per-lane arrays. Capacity stays unstacked — all lanes
        share one table, which is the batching precondition."""
        bp = 1
        while bp < len(packs):
            bp *= 2
        if bp > len(packs):
            dummy = dict(packs[0])
            dummy["k_valid"] = np.int32(0)
            packs = packs + [dummy] * (bp - len(packs))
        cargs = {}
        for name in arg_names:
            if name == "capacity":
                cargs[name] = packs[0][name]
            else:
                cargs[name] = np.stack([p[name] for p in packs])
        return cargs

    def _place_batched(self, cargs: Dict, sharded, capacity_src,
                       n_pad: int, est_steps: int, table=None):
        """Device placement for a stacked batch: mesh shardings when
        sharded (node axis split, lane axis replicated, capacity on the
        mesh-resident table / identity cache), else the host/accel
        cost-model pick. Returns (placed_cargs, mesh_context)."""
        import contextlib
        if sharded is not None:
            placed = sharded.place_batched_chunked_args(
                cargs, capacity_src=capacity_src, table=table)
            return placed, sharded.mesh
        dev = self._pick_device(n_pad, est_steps)
        return self._place_args(cargs, dev), contextlib.nullcontext()

    def batch_dispatch_profitable(self, n: int, count_hint: int = 16,
                                  tolerance: float = 1.0) -> bool:
        """Should the worker coalesce evals into gateway lanes?

        Recalibrated (BENCH_r05: the static model demoted every broker
        lane on real TPU even where batching measured 1.42-1.61x):
        once the cost model holds MEASURED per-lane dispatch costs for
        both a batched arm and a solo arm at this table shape, the
        decision is measured-batched < measured-solo * tolerance.
        Until the batched side is warm, a periodic probe lets lanes
        fire so the measurement exists at all. The static fallback
        remains: batch only when the dispatch would route to the
        accelerator (on host-routed shapes B solo chunked dispatches
        beat one vmapped dispatch and the GIL serializes lane host
        work). Overridable with NOMAD_TPU_EVAL_BATCH=force|off (tests
        force lanes on CPU hosts).

        `tolerance` > 1 is the continuous-batching caller's setting
        (server/worker.py MicroBatchGateway): the per-lane EWMA folds
        ALL batch widths together, so on shapes where width 2 measures
        ~parity and width 8 wins, a strict < would flap coalescing off
        exactly when occupancy could grow — coalesce unless the
        batched arm measures DECISIVELY slower."""
        import os
        mode = os.environ.get("NOMAD_TPU_EVAL_BATCH", "auto")
        if mode == "force":
            return True
        if mode == "off":
            return False
        if self._mesh_sharded() is not None:
            return True
        n_pad = _pad_n(n)
        solo = cost_model.best(SOLO_ARMS, n_pad)
        batched = cost_model.best(BATCHED_ARMS, n_pad)
        if solo is not None and batched is not None:
            if batched < solo * tolerance:
                return True
            # measured demote — but keep the batched EWMA fresh: a
            # stale number (device contention, early-sample noise)
            # must not demote lanes forever, so probe at the same
            # exploration cadence
            return cost_model.probe_due()
        if jax.default_backend() == "cpu":
            return False
        if solo is not None and batched is None and \
                cost_model.probe_due():
            return True                 # exploration: measure a batch
        return self._pick_device(
            n_pad, _bucket_k(max(count_hint, 1))) is None

    def _run_chunked_batched(self, reqs: List[SelectRequest], n_pad: int,
                             sharded) -> List[SelectResult]:
        """B chunk-eligible lanes through the vmapped chunked kernel in
        one dispatch; per-lane overflow continues on the solo kernel.
        Bit-identical to per-request select()."""
        packs = [pack_request(r, n_pad)[0] for r in reqs]
        spread_alg = reqs[0].algorithm == "spread"
        maxc = max(r.count for r in reqs)
        max_steps = 64 if maxc <= 64 else 512
        cargs = self._pad_and_stack(packs, _CHUNKED_ARGS)
        fn = _chunked_batched_jit(max_steps, spread_alg)
        cargs, mesh_ctx = self._place_batched(
            cargs, sharded, reqs[0].capacity, n_pad, min(maxc, 2 * n_pad),
            table=reqs[0].table)
        fresh = _note_trace("chunked_batched", n_pad,
                            max_steps=max_steps, spread_alg=spread_alg,
                            lanes=len(cargs["k_valid"]))
        import time as _time
        t0 = _time.perf_counter()
        with mesh_ctx:
            carry, outs = fn(*[cargs[nm] for nm in _CHUNKED_ARGS])
        outs_np = _stage_get(outs)
        results = []
        for i, req in enumerate(reqs):
            (choice, chunk, ti, ts, exh, feas, rem, steps) = \
                (a[i] for a in outs_np)
            steps = int(steps)
            rem = int(rem)
            rounds = [(choice[:steps], chunk[:steps], ti[:steps],
                       ts[:steps], exh[:steps], feas[:steps])]
            if rem > 0 and steps > 0 and chunk[steps - 1] != 0:
                # step-budget overflow: continue this lane solo from
                # its carry (host copies; the default device path) —
                # pulled through the d2h fence (lint: host-sync)
                lane = {nm: (np.asarray(_stage_get(cargs[nm]))
                             if nm == "capacity"
                             else np.asarray(_stage_get(cargs[nm][i])))
                        for nm in _CHUNKED_ARGS}
                used0, tg0, fp0, ds0 = _stage_get(
                    (carry[0][i], carry[1][i], carry[2][i],
                     carry[3][i]))
                lane.update(
                    used0=np.asarray(used0),
                    tg_coll0=np.asarray(tg0),
                    free_ports=np.asarray(fp0),
                    dev_slots0=np.asarray(ds0),
                    k_valid=np.int32(rem))
                rounds.extend(self._chunked_rounds(lane, spread_alg))
            results.append(_expand_chunks(req, rounds))
        # window includes per-lane unpack/expand so the number compares
        # end-to-end against the solo arms (which include theirs)
        cost_model.observe("chunked_batched", n_pad,
                           _time.perf_counter() - t0, lanes=len(reqs),
                           compiled=fresh)
        return results

    @staticmethod
    def _chunked_rounds(cargs: Dict, spread_alg: bool,
                        max_steps: int = 4096) -> List:
        """Continuation rounds on the solo chunked kernel until the
        remaining count drains (shared by the batched arm's overflow
        path)."""
        rounds = []
        while True:
            (used, coll, freep, devs), outs = _select_chunked(
                **cargs, max_steps=max_steps, spread_alg=spread_alg)
            (choice, chunk, ti, ts, exh, feas,
             rem, steps) = _stage_get(outs)
            steps = int(steps)
            rem = int(rem)
            rounds.append((choice[:steps], chunk[:steps], ti[:steps],
                           ts[:steps], exh[:steps], feas[:steps]))
            if rem <= 0 or steps == 0 or chunk[steps - 1] == 0:
                break
            cargs.update(used0=used, tg_coll0=coll, free_ports=freep,
                         dev_slots0=devs, k_valid=np.int32(rem))
        return rounds

    def _run_scan_batched(self, reqs: List[SelectRequest], n_pad: int,
                          sharded) -> List[SelectResult]:
        """B lanes through the vmapped scan kernel in one dispatch;
        results are bit-identical to per-request select() (the chunked
        and K-way solo paths are proven scan-equivalent)."""
        packs = []
        s_live = p_live = 0
        for r in reqs:
            args, st = pack_request(r, n_pad)
            packs.append(args)
            s_live = max(s_live, st["s_live"])
            p_live = max(p_live, st["p_live"])
        spread_alg = reqs[0].algorithm == "spread"
        k = _bucket_k(max(max(r.count, 1) for r in reqs))
        cargs = self._pad_and_stack(packs, _SCAN_ARGS)
        fn = _scan_batched_jit(k, spread_alg, s_live, p_live)
        cargs, mesh_ctx = self._place_batched(
            cargs, sharded, reqs[0].capacity, n_pad, k,
            table=reqs[0].table)
        fresh = _note_trace("scan_batched", n_pad, k_steps=k,
                            s_live=s_live, p_live=p_live,
                            lanes=len(cargs["k_valid"]))
        import time as _time
        t0 = _time.perf_counter()
        with mesh_ctx:
            _carry, outs = fn(*[cargs[nm] for nm in _SCAN_ARGS])
        outs_np = _stage_get(outs)
        results = [unpack_result(r, tuple(a[i] for a in outs_np))
                   for i, r in enumerate(reqs)]
        # window includes per-lane unpack so the number compares
        # end-to-end against the solo arms (which include theirs)
        cost_model.observe("scan_batched", n_pad,
                           _time.perf_counter() - t0, lanes=len(reqs),
                           compiled=fresh)
        return results

    def _finish_kway_rounds(self, req, cargs, spread_alg, pending,
                            w: int):
        """Continuation rounds only (no expansion) — shared by the
        batched path's per-lane overflow handling."""
        d = req.capacity.shape[1]
        rounds = []
        while True:
            (used, coll, freep, devs), outs = pending
            packed_i, ts = _stage_get(outs)
            widx = packed_i[:, :w]
            chunk = packed_i[:, w:2 * w]
            ti = packed_i[:, 2 * w:2 * w + TOP_K]
            exh = packed_i[:, 2 * w + TOP_K:2 * w + TOP_K + d]
            feas = packed_i[:, -3]
            rem = int(packed_i[0, -2])
            steps = int(packed_i[0, -1])
            rounds.append((widx[:steps], chunk[:steps], ti[:steps],
                           ts[:steps], exh[:steps], feas[:steps]))
            if rem <= 0 or steps == 0:
                break
            if chunk[steps - 1].sum() == 0:
                break
            cargs.update(used0=used, tg_coll0=coll, free_ports=freep,
                         dev_slots0=devs, k_valid=np.int32(rem))
            pending = _select_kway(**cargs, max_steps=_kway_steps(w),
                                   spread_alg=spread_alg, w=w)
        return rounds

    # -- chunked path --------------------------------------------------
    def _run_chunked(self, req: SelectRequest, n_pad: int,
                     dev) -> SelectResult:
        import time as _time
        args, _statics = pack_request(req, n_pad)
        cargs = {k: args[k] for k in _CHUNKED_ARGS}
        cargs = self._place_args(cargs, dev)
        resident = self._resident_args(req, n_pad, dev)
        if resident:
            cargs.update(resident)
        spread_alg = req.algorithm == "spread"
        # near-equal node scores make chunks short (each placement is
        # overtaken after 1-2 instances), so a big count can need
        # thousands of steps — every continuation round is a full
        # host<->device round trip over the tunnel, so size the on-device
        # step budget to finish big batches in ONE dispatch
        if req.count <= 64:
            max_steps = 64
        elif req.count <= 512:
            max_steps = 512
        elif req.count <= 4096:
            max_steps = 4096
        else:
            max_steps = 16384       # covers count<=16384 in one dispatch
                                    # (a step always places >=1 or stops)
        fresh = _note_trace("chunked", n_pad, max_steps=max_steps,
                            spread_alg=spread_alg, cpu=dev is not None)
        rounds = []
        t0 = _time.perf_counter()
        while True:
            (used, coll, freep, devs), outs = _select_chunked(
                **cargs, max_steps=max_steps, spread_alg=spread_alg)
            (choice, chunk, ti, ts, exh, feas,
             rem, steps) = _stage_get(outs)
            steps = int(steps)
            rem = int(rem)
            rounds.append((choice[:steps], chunk[:steps], ti[:steps],
                           ts[:steps], exh[:steps], feas[:steps]))
            if rem <= 0 or steps == 0:
                break
            if chunk[steps - 1] == 0:
                break                        # infeasible: nothing placed
            # ran out of steps: continue from the device-resident carry
            cargs.update(used0=used, tg_coll0=coll, free_ports=freep,
                         dev_slots0=devs, k_valid=np.int32(rem))
        out = _expand_chunks(req, rounds)
        cost_model.observe(
            "chunked" + ("@cpu" if dev is not None else ""), n_pad,
            _time.perf_counter() - t0, compiled=fresh)
        return out


def _expand_chunks(req: SelectRequest, rounds) -> SelectResult:
    """Host-side expansion of per-step (node, chunk) results into the
    per-instance SelectResult the callers expect. Per-instance scores
    are recomputed with the same float32 node-local formula the kernel
    uses (each instance in a chunk sees the usage its predecessors left
    behind, exactly like the scan)."""
    n = len(req.feasible)
    k_total = req.count
    d = req.capacity.shape[1]
    ask = np.asarray(req.ask, np.float32)
    spread_alg = req.algorithm == "spread"
    desired = np.float32(max(req.desired_count, 1.0))

    node_idx = np.full(k_total, -1, np.int32)
    final = np.zeros(k_total, np.float32)
    s_bin = np.zeros(k_total, np.float32)
    s_anti = np.zeros(k_total, np.float32)
    s_pen = np.zeros(k_total, np.float32)
    s_aff = np.zeros(k_total, np.float32)
    s_dev = np.zeros(k_total, np.float32)
    s_pre = np.zeros(k_total, np.float32)
    top_i = np.full((k_total, TOP_K), -1, np.int32)
    top_s = np.full((k_total, TOP_K), NEG_INF, np.float32)
    exh_out = np.zeros((k_total, d), np.int32)

    aff_col = None
    if req.affinity is not None and req.affinity_sum_weights > 0:
        aff_col = (req.affinity / req.affinity_sum_weights).astype(np.float32)
    pen_col = req.penalty
    dev_col = req.dev_score if req.dev_fires else None
    pre_col = req.pre_score

    pos = 0
    extra = {}                               # node -> already placed here
    fail = None
    # the kernel materializes top-k/exhaustion meta only on the first
    # and failing steps; ordinary steps carry sentinels and reuse the
    # dispatch-level snapshot
    last_meta = None
    for (choice, chunk, ti, ts, exh, _feas) in rounds:
        for s in range(len(choice)):
            c = int(choice[s])
            m = int(chunk[s])
            if exh[s][0] >= 0:
                last_meta = (ti[s], ts[s], exh[s])
            if m <= 0 or c < 0:
                fail = last_meta
                continue
            m = min(m, k_total - pos)
            prior = extra.get(c, 0)
            a = np.arange(m, dtype=np.float32)
            after = (req.used[c].astype(np.float32)[None, :]
                     + (prior + a[:, None] + 1.0) * ask)
            cap_cpu = np.float32(max(req.capacity[c, 0], 1e-9))
            cap_mem = np.float32(max(req.capacity[c, 1], 1e-9))
            free_cpu = np.float32(1.0) - after[:, 0] / cap_cpu
            free_mem = np.float32(1.0) - after[:, 1] / cap_mem
            total = (np.power(np.float32(10.0), free_cpu)
                     + np.power(np.float32(10.0), free_mem))
            if spread_alg:
                fit_score = np.clip(total - 2.0, 0.0, 18.0)
            else:
                fit_score = np.clip(20.0 - total, 0.0, 18.0)
            binp = (fit_score / np.float32(18.0)).astype(np.float32)
            coll = np.float32(req.tg_collisions[c]) + np.float32(prior) + a
            anti_fires = coll > 0
            anti = np.where(anti_fires, -(coll + 1.0) / desired,
                            0.0).astype(np.float32)
            pen_f = bool(pen_col[c]) if pen_col is not None else False
            pen = np.float32(-1.0 if pen_f else 0.0)
            aff = np.float32(aff_col[c]) if aff_col is not None else \
                np.float32(0.0)
            dev = np.float32(dev_col[c]) if dev_col is not None else \
                np.float32(0.0)
            pre = np.float32(pre_col[c]) if pre_col is not None else \
                np.float32(0.0)
            fired = (1.0 + anti_fires.astype(np.float32)
                     + np.float32(1.0 if pen_f else 0.0)
                     + np.float32(1.0 if aff != 0.0 else 0.0)
                     + np.float32(1.0 if dev_col is not None else 0.0)
                     + np.float32(1.0 if pre != 0.0 else 0.0))
            fin = ((binp + anti + pen + aff + dev + pre)
                   / fired).astype(np.float32)

            sl = slice(pos, pos + m)
            node_idx[sl] = c
            final[sl] = fin
            s_bin[sl] = binp
            s_anti[sl] = anti
            s_pen[sl] = pen
            s_aff[sl] = aff
            s_dev[sl] = dev
            s_pre[sl] = pre
            m_ti, m_ts, m_exh = last_meta if last_meta is not None \
                else (ti[s], ts[s], np.zeros_like(exh[s]))
            top_i[sl] = np.where(m_ti >= n, -1, m_ti)
            top_s[sl] = m_ts
            exh_out[sl] = np.maximum(m_exh, 0)
            extra[c] = prior + m
            pos += m
    if fail is not None and pos < k_total:
        ti_f, ts_f, exh_f = fail
        top_i[pos:] = np.where(ti_f >= n, -1, ti_f)
        top_s[pos:] = ts_f
        exh_out[pos:] = exh_f

    considered = req.n_considered if req.n_considered is not None else n
    return _sanitize_result(req, SelectResult(
        node_idx=node_idx,
        final_score=final,
        scores={"binpack": s_bin, "job-anti-affinity": s_anti,
                "node-reschedule-penalty": s_pen,
                "node-affinity": s_aff,
                "allocation-spread": np.zeros(k_total, np.float32),
                "devices": s_dev, "preemption": s_pre},
        top_idx=top_i, top_scores=top_s,
        nodes_evaluated=considered,
        nodes_filtered=int(considered - np.count_nonzero(req.feasible)),
        exhausted_dim=exh_out,
        placed=pos,
    ))


# -- kernel-cache governance (governor/registry.py) --------------------

def kernel_cache_stats() -> Dict[str, int]:
    """Entry counts for the shape-keyed JIT caches this module owns.
    The batched-lane caches are true LRUs (KERNEL_CACHE_MAX); the
    plain jitted kernels report jax's internal per-function cache size
    where the running jax exposes it."""
    out = {"scan_batched": _scan_batched_jit.cache_info().currsize,
           "chunked_batched": _chunked_batched_jit.cache_info().currsize}
    for name, fn in (("scan", _select_scan),
                     ("chunked", _select_chunked),
                     ("kway", _select_kway)):
        try:
            out[name] = int(fn._cache_size())
        except Exception:
            out[name] = 0
    return out


def kernel_cache_entries() -> int:
    return sum(kernel_cache_stats().values())


def clear_kernel_caches() -> dict:
    """Governor reclaim: drop every cached compiled kernel. Rarely the
    right call on a healthy server (the LRU bound handles churn);
    exists for the watermark breach where compiled-shape cardinality
    itself is the leak. Next dispatches recompile warm shapes."""
    # the recompile gauge must see those recompiles: forget seen trace
    # signatures so re-traced warm shapes count as fresh compiles
    from ..analysis.sanitizer import traces
    traces.invalidate()
    before = kernel_cache_entries()
    _scan_batched_jit.cache_clear()
    _chunked_batched_jit.cache_clear()
    for fn in (_select_scan, _select_chunked, _select_kway):
        try:
            fn.clear_cache()
        except Exception:
            pass
    return {"evicted": before}
