"""The fused placement kernel.

One device dispatch replaces the reference's entire per-placement
iterator chain (stack.go Select -> feasible -> BinPack -> scorers ->
Limit -> MaxScore) AND the outer per-alloc loop: a `lax.scan` places all
`count` instances of a task group sequentially *on device*, with each
step seeing the previous steps' placements (usage, anti-affinity
collisions, spread histograms, distinct-hosts/-property counts carried
through the scan). Score semantics mirror:

  - bin-pack / spread fit    structs/funcs.go ScoreFitBinPack:174 (/18)
  - job anti-affinity        rank.go:502  (-(collisions+1)/desired_count)
  - reschedule penalty       rank.go:564  (-1 on penalty nodes)
  - node affinity            rank.go:637  (sum(w*match)/sum|w|)
  - spread                   spread.go:110 (targeted + even-spread boost)
  - normalization            rank.go:696  (mean over *fired* scorers)
  - selection                select.go MaxScoreIterator -> full argmax
                             (no log2(n) sampling: the whole node axis
                             is scored at once, SURVEY.md §2.6)

Shapes are padded to buckets to bound recompilation:
  N -> next power of two; steps K -> bucket; spreads S, distinct-property
  P, codes C -> fixed maxima. Padded lanes carry zero weight.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

S_MAX = 4       # max spread stanzas per task group
P_MAX = 4       # max distinct_property constraints
C_MAX = 64      # max distinct attribute values per spread/property axis
NEG_INF = -1e30
TOP_K = 5       # ScoreMetaData entries kept (reference kheap topK)


def _pad_n(n: int) -> int:
    p = 8
    while p < n:
        p *= 2
    return p


def _bucket_k(k: int) -> int:
    """Scan length bucket. Dispatch overhead dominates scan-step cost
    (~26us/step vs ~0.7s/dispatch over the axon tunnel), so buckets are
    generous: powers of two up to 1024, then multiples of 1024."""
    if k <= 1024:
        b = 1
        while b < k:
            b *= 2
        return b
    return min(-(-k // 1024) * 1024, 65536)


@dataclasses.dataclass
class SelectRequest:
    """Host-side inputs for placing `count` instances of one task group."""
    ask: np.ndarray                  # f32[D] cpu/mem/disk[/mbits] per instance
    count: int
    feasible: np.ndarray             # bool[N] all static checks combined
    capacity: np.ndarray             # f32[N,D]
    used: np.ndarray                 # f32[N,D] live + plan overlay
    desired_count: float             # anti-affinity denominator (tg count)
    tg_collisions: np.ndarray        # i32[N] proposed allocs of job+tg
    job_count: np.ndarray            # i32[N] proposed allocs of job
    distinct_hosts: bool = False
    penalty: Optional[np.ndarray] = None        # bool[N]
    affinity: Optional[np.ndarray] = None       # f32[N] weighted sum
    affinity_sum_weights: float = 0.0
    algorithm: str = "binpack"       # "binpack" | "spread"
    scan_exclusive: bool = False     # reserved-port ask: one instance/node/scan
    port_need: float = 0.0
    free_ports: Optional[np.ndarray] = None     # f32[N]
    port_ok: Optional[np.ndarray] = None        # bool[N]
    # spreads: list of dicts with codes i32[N], counts f32[C+1],
    #          present bool[C+1], desired f32[C+1] (-1 == none),
    #          has_implicit, implicit_desired, weight, has_targets
    spreads: List[Dict] = dataclasses.field(default_factory=list)
    sum_spread_weights: float = 0.0
    # distinct_property: list of dicts with codes i32[N], counts f32[C+1],
    #          limit f32
    distinct_props: List[Dict] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class SelectResult:
    """Result of one multi-placement kernel dispatch."""
    node_idx: np.ndarray             # i32[K] chosen node per step (-1 none)
    final_score: np.ndarray          # f32[K]
    scores: Dict[str, np.ndarray]    # component -> f32[K]
    top_idx: np.ndarray              # i32[K, TOP_K]
    top_scores: np.ndarray           # f32[K, TOP_K]
    nodes_evaluated: int
    nodes_filtered: int
    exhausted_dim: np.ndarray        # i32[K, D] counts per DIM_NAMES dim
    placed: int


@partial(jax.jit, static_argnames=("k_steps", "spread_alg", "s_live", "p_live"))
def _select_scan(capacity, used0, feasible, ask, k_valid,
                 tg_coll0, job_count0, distinct_hosts_flag, scan_exclusive,
                 penalty, affinity_norm, desired_count,
                 port_need, free_ports, port_ok,
                 sp_codes, sp_counts0, sp_present0, sp_desired,
                 sp_weight, sp_has_targets, sp_valid, sum_spread_w,
                 dp_codes, dp_counts0, dp_limit, dp_valid,
                 *, k_steps: int, spread_alg: bool, s_live: int, p_live: int):
    """The fused kernel. Shapes:
    capacity/used0 f32[N,3]; feasible bool[N]; ask f32[3];
    sp_* [S, ...] with code axis C+1; dp_* [P, ...].
    Returns per-step choices, scores, metrics, and the final usage state.
    """
    n = capacity.shape[0]
    cap_cpu = jnp.maximum(capacity[:, 0], 1e-9)
    cap_mem = jnp.maximum(capacity[:, 1], 1e-9)

    def step(carry, step_i):
        (used, tg_coll, job_cnt, scan_placed, free_p,
         sp_counts, sp_present, dp_counts) = carry

        # ---- feasibility beyond the static mask -----------------------
        feas = feasible
        feas &= jnp.where(distinct_hosts_flag > 0, job_cnt == 0, True)
        # reserved-port asks make instances mutually exclusive per node
        # within this scan (the same host port would collide)
        feas &= jnp.where(scan_exclusive > 0, scan_placed == 0, True)
        feas &= free_p >= port_need
        feas &= port_ok
        # distinct_property: count(value)+1 <= limit, missing attr fails
        for p in range(p_live):
            codes = dp_codes[p]
            cnt = dp_counts[p][codes]
            missing = codes == dp_counts.shape[-1] - 1
            ok = (cnt + 1.0 <= dp_limit[p]) & ~missing
            feas &= jnp.where(dp_valid[p], ok, True)

        # ---- fit (AllocsFit over the node axis) -----------------------
        after = used + ask[None, :]
        fit_dims = after <= capacity + 1e-6
        fit = jnp.all(fit_dims, axis=1)
        # first-failing-dimension counts (metrics), dimension-generic in
        # DIM_NAMES order (cpu > memory > disk > network)
        prefix_ok = jnp.cumprod(fit_dims.astype(jnp.int32), axis=1)
        earlier_ok = jnp.concatenate(
            [jnp.ones((n, 1), dtype=bool), prefix_ok[:, :-1].astype(bool)],
            axis=1)
        first_fail = feas[:, None] & earlier_ok & ~fit_dims
        exhausted = first_fail.sum(axis=0).astype(jnp.int32)

        # ---- bin-pack / spread fit score ------------------------------
        free_cpu = 1.0 - after[:, 0] / cap_cpu
        free_mem = 1.0 - after[:, 1] / cap_mem
        total = jnp.power(10.0, free_cpu) + jnp.power(10.0, free_mem)
        if spread_alg:
            fit_score = jnp.clip(total - 2.0, 0.0, 18.0)
        else:
            fit_score = jnp.clip(20.0 - total, 0.0, 18.0)
        binpack = fit_score / 18.0

        # ---- job anti-affinity ---------------------------------------
        coll = tg_coll.astype(jnp.float32)
        anti_fires = coll > 0
        anti = jnp.where(anti_fires,
                         -(coll + 1.0) / jnp.maximum(desired_count, 1.0),
                         0.0)

        # ---- reschedule penalty --------------------------------------
        pen_fires = penalty
        pen = jnp.where(pen_fires, -1.0, 0.0)

        # ---- node affinity -------------------------------------------
        aff_fires = affinity_norm != 0.0
        aff = affinity_norm

        # ---- spread ---------------------------------------------------
        spread_total = jnp.zeros(n, dtype=jnp.float32)
        for s in range(s_live):
            codes = sp_codes[s]
            c_axis = sp_counts.shape[-1]
            missing = codes == c_axis - 1
            used_cnt = sp_counts[s][codes] + 1.0
            desired = sp_desired[s][codes]
            has_desired = desired >= 0.0
            w = sp_weight[s] / jnp.maximum(sum_spread_w, 1e-9)
            targeted = jnp.where(
                has_desired,
                (desired - used_cnt) / jnp.maximum(desired, 1e-9) * w,
                -1.0)
            # even-spread scoring (spread.go evenSpreadScoreBoost)
            pres = sp_present[s]
            cnts = sp_counts[s]
            big = 1e30
            min_cnt = jnp.min(jnp.where(pres, cnts, big))
            max_cnt = jnp.max(jnp.where(pres, cnts, -big))
            any_present = jnp.any(pres)
            cur = sp_counts[s][codes]
            even = jnp.where(
                min_cnt == 0.0,
                -1.0,
                (min_cnt - cur) / jnp.maximum(min_cnt, 1e-9))
            at_min = cur == min_cnt
            even = jnp.where(
                at_min,
                jnp.where(min_cnt == max_cnt, -1.0,
                          jnp.where(min_cnt == 0.0, 1.0,
                                    (max_cnt - min_cnt) /
                                    jnp.maximum(min_cnt, 1e-9))),
                even)
            even = jnp.where(any_present, even, 0.0)
            even = jnp.where(missing, -1.0, even)
            contrib = jnp.where(sp_has_targets[s],
                                jnp.where(missing, -1.0, targeted), even)
            spread_total += jnp.where(sp_valid[s], contrib, 0.0)
        spread_fires = spread_total != 0.0

        # ---- normalization (mean over fired scorers) ------------------
        fired = (1.0 + anti_fires.astype(jnp.float32)
                 + pen_fires.astype(jnp.float32)
                 + aff_fires.astype(jnp.float32)
                 + spread_fires.astype(jnp.float32))
        final = (binpack + anti + pen + aff + spread_total) / fired

        # ---- masked argmax -------------------------------------------
        ok = feas & fit
        masked = jnp.where(ok, final, NEG_INF)
        choice = jnp.argmax(masked)
        valid = (masked[choice] > NEG_INF / 2) & (step_i < k_valid)
        choice_out = jnp.where(valid, choice, -1)

        top_scores, top_idx = jax.lax.top_k(masked, TOP_K)

        # ---- carry updates (the placement happens here) ---------------
        onehot = (jnp.arange(n) == choice) & valid
        used = used + jnp.where(onehot[:, None], ask[None, :], 0.0)
        tg_coll = tg_coll + onehot.astype(jnp.int32)
        job_cnt = job_cnt + onehot.astype(jnp.int32)
        scan_placed = scan_placed + onehot.astype(jnp.int32)
        free_p = free_p - onehot.astype(jnp.float32) * port_need
        c_axis = sp_counts.shape[-1]
        chosen_sp_codes = sp_codes[:, choice]           # [S]
        sp_upd = (jax.nn.one_hot(chosen_sp_codes, c_axis,
                                 dtype=sp_counts.dtype) *
                  jnp.where(valid, 1.0, 0.0))
        sp_counts = sp_counts + sp_upd
        sp_present = sp_present | (sp_upd > 0)
        chosen_dp_codes = dp_codes[:, choice]
        dp_upd = (jax.nn.one_hot(chosen_dp_codes, dp_counts.shape[-1],
                                 dtype=dp_counts.dtype) *
                  jnp.where(valid, 1.0, 0.0))
        dp_counts = dp_counts + dp_upd

        out = (choice_out.astype(jnp.int32),
               jnp.where(valid, masked[jnp.maximum(choice, 0)], 0.0),
               jnp.where(valid, binpack[jnp.maximum(choice, 0)], 0.0),
               jnp.where(valid, anti[jnp.maximum(choice, 0)], 0.0),
               jnp.where(valid, pen[jnp.maximum(choice, 0)], 0.0),
               jnp.where(valid, aff[jnp.maximum(choice, 0)], 0.0),
               jnp.where(valid, spread_total[jnp.maximum(choice, 0)], 0.0),
               top_idx.astype(jnp.int32), top_scores,
               exhausted, ok.sum().astype(jnp.int32))
        return (used, tg_coll, job_cnt, scan_placed, free_p,
                sp_counts, sp_present, dp_counts), out

    carry0 = (used0, tg_coll0, job_count0,
              jnp.zeros(n, dtype=jnp.int32), free_ports,
              sp_counts0, sp_present0, dp_counts0)
    carry, outs = jax.lax.scan(step, carry0, jnp.arange(k_steps))
    return carry, outs


# Kinds for each packed argument: how its leading axis shards over a
# node-axis mesh (parallel/sharded.py). "node"=[N], "node2"=[N,d],
# "code"=[S,N] style, "rep"=replicated small state, "scalar"=0-d.
PACK_SHARD_KINDS = {
    "capacity": "node2", "used0": "node2", "feasible": "node",
    "ask": "rep", "k_valid": "scalar",
    "tg_coll0": "node", "job_count0": "node",
    "distinct_hosts_flag": "scalar", "scan_exclusive": "scalar",
    "penalty": "node", "affinity_norm": "node", "desired_count": "scalar",
    "port_need": "scalar", "free_ports": "node", "port_ok": "node",
    "sp_codes": "code", "sp_counts0": "rep", "sp_present0": "rep",
    "sp_desired": "rep", "sp_weight": "rep", "sp_has_targets": "rep",
    "sp_valid": "rep", "sum_spread_w": "scalar",
    "dp_codes": "code", "dp_counts0": "rep", "dp_limit": "rep",
    "dp_valid": "rep",
}

MAX_SCAN_STEPS = 65536


def pack_request(req: SelectRequest, n_pad: int):
    """Pad/pack a SelectRequest into the _select_scan argument dict
    (keys match the kernel's parameter names; PACK_SHARD_KINDS describes
    each argument's sharding axis). Shared by the single-device kernel
    wrapper and the mesh-sharded dispatcher."""
    if req.count > MAX_SCAN_STEPS:
        raise ValueError(
            f"count={req.count} exceeds the scan cap of {MAX_SCAN_STEPS}; "
            f"split the placement batch")
    n = len(req.feasible)

    def pad1(a, fill=0.0, dtype=np.float32):
        out = np.full(n_pad, fill, dtype=dtype)
        out[:n] = a
        return out

    def pad2(a):
        out = np.zeros((n_pad, a.shape[1]), dtype=np.float32)
        out[:n] = a
        return out

    if req.affinity is not None and req.affinity_sum_weights > 0:
        affinity_norm = pad1(req.affinity / req.affinity_sum_weights)
    else:
        affinity_norm = np.zeros(n_pad, dtype=np.float32)

    s_live = min(len(req.spreads), S_MAX)
    c_axis = C_MAX + 1
    sp_codes = np.full((S_MAX, n_pad), C_MAX, dtype=np.int32)
    sp_counts = np.zeros((S_MAX, c_axis), dtype=np.float32)
    sp_present = np.zeros((S_MAX, c_axis), dtype=bool)
    sp_desired = np.full((S_MAX, c_axis), -1.0, dtype=np.float32)
    sp_weight = np.zeros(S_MAX, dtype=np.float32)
    sp_has_targets = np.zeros(S_MAX, dtype=bool)
    sp_valid = np.zeros(S_MAX, dtype=bool)
    for s, sp in enumerate(req.spreads[:S_MAX]):
        m = len(sp["codes"])
        sp_codes[s, :m] = np.minimum(sp["codes"], C_MAX)
        c = min(len(sp["counts"]), c_axis)
        sp_counts[s, :c] = sp["counts"][:c]
        sp_present[s, :c] = sp["present"][:c]
        sp_desired[s, :c] = sp["desired"][:c]
        sp_weight[s] = sp["weight"]
        sp_has_targets[s] = sp["has_targets"]
        sp_valid[s] = True

    p_live = min(len(req.distinct_props), P_MAX)
    dp_codes = np.full((P_MAX, n_pad), C_MAX, dtype=np.int32)
    dp_counts = np.zeros((P_MAX, c_axis), dtype=np.float32)
    dp_limit = np.zeros(P_MAX, dtype=np.float32)
    dp_valid = np.zeros(P_MAX, dtype=bool)
    for p, dp in enumerate(req.distinct_props[:P_MAX]):
        m = len(dp["codes"])
        dp_codes[p, :m] = np.minimum(dp["codes"], C_MAX)
        c = min(len(dp["counts"]), c_axis)
        dp_counts[p, :c] = dp["counts"][:c]
        dp_limit[p] = dp["limit"]
        dp_valid[p] = True

    args = dict(
        capacity=pad2(req.capacity),
        used0=pad2(req.used),
        feasible=pad1(req.feasible, False, bool),
        ask=np.asarray(req.ask, np.float32),
        k_valid=jnp.int32(req.count),
        tg_coll0=pad1(req.tg_collisions, 0, np.int32),
        job_count0=pad1(req.job_count, 0, np.int32),
        distinct_hosts_flag=jnp.float32(1.0 if req.distinct_hosts else 0.0),
        scan_exclusive=jnp.float32(1.0 if req.scan_exclusive else 0.0),
        penalty=pad1(req.penalty if req.penalty is not None
                     else np.zeros(n, bool), False, bool),
        affinity_norm=affinity_norm,
        desired_count=jnp.float32(req.desired_count),
        port_need=jnp.float32(req.port_need),
        free_ports=pad1(req.free_ports if req.free_ports is not None
                        else np.full(n, 1e9, np.float32)),
        port_ok=pad1(req.port_ok if req.port_ok is not None
                     else np.ones(n, bool), False, bool),
        sp_codes=sp_codes, sp_counts0=sp_counts, sp_present0=sp_present,
        sp_desired=sp_desired, sp_weight=sp_weight,
        sp_has_targets=sp_has_targets, sp_valid=sp_valid,
        sum_spread_w=jnp.float32(req.sum_spread_weights),
        dp_codes=dp_codes, dp_counts0=dp_counts, dp_limit=dp_limit,
        dp_valid=dp_valid,
    )
    statics = dict(spread_alg=(req.algorithm == "spread"),
                   s_live=s_live, p_live=p_live)
    return args, statics


def unpack_result(req: SelectRequest, outs) -> SelectResult:
    (choices, finals, s_bin, s_anti, s_pen, s_aff, s_spread,
     top_idx, top_scores, exhausted, _ok_counts) = [
        np.asarray(o) for o in outs]
    n = len(req.feasible)
    kk = req.count
    choices = choices[:kk]
    choices = np.where(choices >= n, -1, choices)  # padding lanes
    placed = int((choices >= 0).sum())
    top_idx = np.where(top_idx >= n, -1, top_idx)
    return SelectResult(
        node_idx=choices,
        final_score=finals[:kk],
        scores={"binpack": s_bin[:kk], "job-anti-affinity": s_anti[:kk],
                "node-reschedule-penalty": s_pen[:kk],
                "node-affinity": s_aff[:kk],
                "allocation-spread": s_spread[:kk]},
        top_idx=top_idx[:kk], top_scores=top_scores[:kk],
        nodes_evaluated=n,
        nodes_filtered=int(n - np.count_nonzero(req.feasible)),
        exhausted_dim=exhausted[:kk],
        placed=placed,
    )


class SelectKernel:
    """Host wrapper: pads request arrays, dispatches the scan kernel, and
    unpacks results."""

    def select(self, req: SelectRequest) -> SelectResult:
        n_pad = _pad_n(len(req.feasible))
        k = _bucket_k(max(req.count, 1))
        args, statics = pack_request(req, n_pad)
        _carry, outs = _select_scan(**args, k_steps=k, **statics)
        return unpack_result(req, outs)
