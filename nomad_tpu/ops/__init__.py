"""The TPU compute path: columnar node/alloc tables and the batched
placement kernels that replace the reference's per-node iterator chain
(scheduler/{stack,rank,feasible,spread,select}.go).

Split of labor (SURVEY.md §7.1):
  - targets.py  host-side vectorized target resolution + constraint ->
                bool[N] mask evaluation (regex/version/semver evaluated
                once per *distinct value*, not per node)
  - tables.py   NodeTable / proposed-allocation index builders
  - versions.py go-version/semver constraint parsing
  - select.py   the fused jitted kernel: feasibility -> fit -> score ->
                masked argmax, multi-placement via lax.scan
"""

from .tables import NodeTable, ProposedIndex
from .select import SelectKernel, SelectRequest, SelectResult
