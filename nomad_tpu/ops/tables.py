"""Columnar device state: the NodeTable and per-eval proposed-allocation
index.

This is the data layout that replaces the reference's one-node-at-a-time
iterator state (SURVEY.md §7.1): node capacities/usages are (N, 3)
float32 arrays [cpu_shares, memory_mb, disk_mb]; attributes resolve to
columns through ops/targets.py; allocation accounting becomes
segment-sums over node indices.

Build is O(nodes + allocs) from a state snapshot and cached per state
index epoch; the scheduler calls `NodeTable.build` once per eval at most
(and usually hits the cache across evals of the same snapshot).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from functools import lru_cache

from ..models import NetworkIndex
from ..models.job import (CONSTRAINT_DISTINCT_HOSTS,
                          CONSTRAINT_DISTINCT_PROPERTY)
from .targets import TargetColumns, constraint_mask
from ..utils.locks import make_lock

RES_DIMS = 4  # cpu_shares, memory_mb, disk_mb, network_mbits
DIM_NAMES = ("cpu", "memory", "disk", "network")

# table-maintenance accounting (governor gauges + the steady-state
# smoke test): full column builds vs incremental delta refreshes. A
# healthy steady state performs ZERO full builds — every refresh rides
# the delta path; the counters make that checkable instead of assumed.
BUILD_STATS: Dict[str, int] = {"full_builds": 0, "delta_refreshes": 0}


# usage rows memoized by the identity of the alloc's resources object:
# fleets share identical AllocatedResources shapes (and the C2M replay
# seed shares ONE flyweight row across millions of allocs), so a 2M-row
# table build becomes 2M dict hits instead of 2M ComparableResources
# constructions. Values are immutable once allocated; holding the key
# object in the memo pins its id() against reuse — which is also why
# the memos must stay SMALL: every entry pins a full resources graph
# (~2 KB) past its alloc's death. A churning server mints one fresh
# resources object per placement wave, so the old clear-at-100k policy
# accreted ~100-200 MB of dead graphs between resets (the r6 soak's
# residual RSS slope). FIFO-evict at a working-set-sized bound
# instead: misses just recompute.
# sized for the real working set: live flyweights being added/removed
# during a refresh (a handful), not history — verified by the r6 soak
# instrumentation: post-fix object growth over 2000 evals is ~1
_MEMO_MAX = 4096
_usage_memo: Dict[int, Tuple[object, Tuple[float, float, float, float]]] = {}
_port_bits_memo: Dict[int, Tuple[object, int]] = {}


def _memo_insert(memo: Dict, key: int, value) -> None:
    if len(memo) >= _MEMO_MAX:
        # dicts preserve insertion order: drop the oldest entry.
        # Concurrent scheduler lanes share these module-level memos
        # unlocked, so two threads can race to evict the same key
        # (KeyError) or mutate between iter() and next() (RuntimeError)
        # — tolerate both rather than lock the hot path; the bound
        # only overshoots by the thread count
        try:
            memo.pop(next(iter(memo)), None)
        except (StopIteration, RuntimeError):
            pass
    memo[key] = value


def resource_memo_len() -> int:
    """Governor accounting: pinned resources-graph entries across the
    identity memos."""
    return len(_usage_memo) + len(_port_bits_memo)

# inlined Allocation.terminal_status for the 2M-row build loop
from ..models.alloc import (  # noqa: E402
    ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_FAILED, ALLOC_CLIENT_LOST,
    ALLOC_DESIRED_EVICT, ALLOC_DESIRED_STOP)

TERMINAL_DESIRED = frozenset((ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT))
TERMINAL_CLIENT = frozenset((ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_FAILED,
                             ALLOC_CLIENT_LOST))


@lru_cache(maxsize=4096)
def _reserved_port_bits(spec: str) -> int:
    """A node's reserved-host-port bitmask. Equivalent to
    NetworkIndex.set_node + merging used_ports (the reserved range is
    applied to every IP identically, so the merge IS the range);
    memoized because fleets share a handful of reserved-port configs
    and a 50k-node table init was re-parsing each one."""
    from ..models.networks import parse_port_ranges
    try:
        ports = parse_port_ranges(spec)
    except ValueError:
        return 0
    bits = 0
    for p in ports:
        bits |= 1 << p
    return bits


def _res_port_bits(res) -> int:
    """Port bitmask of one AllocatedResources graph (the unmemoized
    core of NodeTable._alloc_port_bits; the columnar cold build calls
    it once per unique resources-pool entry)."""
    if res is None:
        return 0
    bits = 0
    for nw in res.shared.networks:
        for ports in (nw.reserved_ports, nw.dynamic_ports):
            for p in ports:
                if p.value > 0:
                    bits |= 1 << p.value
    for task in res.tasks.values():
        for nw in task.networks:
            for ports in (nw.reserved_ports, nw.dynamic_ports):
                for p in ports:
                    if p.value > 0:
                        bits |= 1 << p.value
    return bits


def _alloc_usage(alloc) -> Tuple[float, float, float, float]:
    res = alloc.allocated_resources
    if res is not None:
        hit = _usage_memo.get(id(res))
        if hit is not None and hit[0] is res:
            return hit[1]
    c = alloc.comparable_resources()
    if c is None:
        return (0.0, 0.0, 0.0, 0.0)
    mbits = sum(nw.mbits for nw in c.networks)
    out = (float(c.cpu_shares), float(c.memory_mb), float(c.disk_mb),
           float(mbits))
    if res is not None:
        _memo_insert(_usage_memo, id(res), (res, out))
    return out


class NodeTable:
    """Columnar view of the ready node set + live allocation usage."""

    def __init__(self, nodes: List):
        self.nodes = nodes
        self.n = len(nodes)
        self.ids = [n.id for n in nodes]
        self.id_to_idx = {nid: i for i, nid in enumerate(self.ids)}
        self.cols = TargetColumns(nodes)
        # applied-alloc registry for the delta path (alloc id -> the
        # object version whose usage is currently accounted). ONE plain
        # dict SHARED across clone_for_deltas generations: the registry
        # is only ever read/written inside the serialized table-refresh
        # path (NodeTableCache.get holds its lock), never by concurrent
        # eval readers of older versions — so it needs no MVCC, and a
        # 10k-alloc refresh costs 10k dict stores instead of a
        # 2M-entry copy-on-write storm (round-5 profile: 111 ms/eval)
        self.alloc_by_id: Dict[str, object] = {}
        # attribute dictionary-encodings, valid per table version
        self._attr_codes_cache: Dict[str, Tuple[np.ndarray, List[str]]] = {}
        # ready-in-datacenters masks, valid per table version
        self._ready_dc_cache: Dict[Tuple, Tuple] = {}
        # until finalize() seals the table it is private to its builder:
        # bulk loads append rows in place and batch the registry, avoiding
        # O(allocs-per-node^2) copy-on-write during build
        self._sealed = False
        self._pending_allocs: List[Tuple[str, object]] = []
        # cross-eval static feasibility memoization, content-addressed by
        # constraint/driver/volume set (the columnar analog of computed-
        # node-class memoization, feasible.go:1026-1118); valid for this
        # table version — node attribute columns are immutable here
        self.mask_cache: Dict[Tuple, List] = {}
        # cross-eval preemption victim cache keyed on the node's
        # live-alloc ROW IDENTITY (rows are replaced copy-on-write, so
        # an unchanged row means unchanged candidates) + the asking
        # shape; entries pin their row so id() can't be recycled
        # (scheduler/preemption.py PreemptionRound)
        self.preempt_cache: Dict[Tuple, tuple] = {}
        # device-resident mirror token (ops/device_table.py): set by
        # NodeTableCache on tables it serves; a kernel dispatch uses
        # the mirror's arrays only while the token still matches the
        # mirror's version (stale snapshots fall back to dense H2D)
        self.device_mirror = None
        self.device_version = -1

        self.capacity = np.zeros((self.n, RES_DIMS), dtype=np.float32)
        self.ready = np.zeros(self.n, dtype=bool)
        self.datacenters = np.empty(self.n, dtype=object)
        for i, node in enumerate(nodes):
            res = node.comparable_resources()
            reserved = node.comparable_reserved_resources()
            self.capacity[i, 0] = res.cpu_shares - reserved.cpu_shares
            self.capacity[i, 1] = res.memory_mb - reserved.memory_mb
            self.capacity[i, 2] = res.disk_mb - reserved.disk_mb
            # network bandwidth as a fit dimension: the reference checks
            # it per-device inside BinPackIterator via AssignNetwork
            # (structs/network.go:406); here total free mbits is a kernel
            # column so the scan never over-commits a node the host-side
            # assigner would then reject
            networks = (node.node_resources.networks
                        if node.node_resources else [])
            self.capacity[i, 3] = sum(nw.mbits for nw in networks)
            self.ready[i] = node.ready()
            self.datacenters[i] = node.datacenter

        # live (non-terminal) alloc usage per node + the live alloc lists
        self.base_used = np.zeros((self.n, RES_DIMS), dtype=np.float32)
        self.live_allocs: List[List] = [[] for _ in range(self.n)]
        # per-node port bitsets (python bigints) for precise conflict checks
        self._net_bits: List[int] = [0] * self.n
        self.free_ports = np.zeros(self.n, dtype=np.float32)
        self._port_col_cache: Dict[int, np.ndarray] = {}

        for i, node in enumerate(nodes):
            reserved = node.reserved_resources
            spec = reserved.reserved_host_ports if reserved else ""
            if spec:
                self._net_bits[i] = _reserved_port_bits(spec)

        self._free_ports_dirty = None  # None == all rows dirty

    @staticmethod
    def _merge_bits(idx: NetworkIndex) -> int:
        bits = 0
        for b in idx.used_ports.values():
            bits |= b
        return bits

    @classmethod
    def build(cls, snapshot, datacenters: Optional[List[str]] = None,
              include_all: bool = False) -> "NodeTable":
        """Build from a state snapshot; restrict to ready nodes in the
        given datacenters (readyNodesInDCs, scheduler/util.go:233)."""
        nodes = []
        for node in snapshot.nodes():
            if not include_all and not node.ready():
                continue
            if datacenters is not None and node.datacenter not in datacenters:
                continue
            nodes.append(node)
        nodes.sort(key=lambda n: n.id)
        BUILD_STATS["full_builds"] += 1
        t = cls(nodes)
        # bulk accumulation: per-alloc numpy scalar adds cost ~4 ops x
        # 2M rows; instead collect (node idx, usage-code) pairs in one
        # tight pass and land them with a single np.add.at (usage rows
        # dedupe heavily — fleets share identical resource shapes).
        # Float adds stay elementwise-sequential, so results match the
        # incremental path bit for bit.
        id_to_idx = t.id_to_idx
        rows = t.live_allocs
        net_bits = t._net_bits
        idx_list: List[int] = []
        code_list: List[int] = []
        code_of: Dict[Tuple, int] = {}
        lut: List[Tuple] = []
        # hot loop: at C2M scale this visits 2M allocs, so every name
        # is a local, the terminal check is inlined attr reads, and the
        # usage-code + port-bits lookups are ONE fused memo keyed by
        # the resources object's identity (bulk-loaded fleets share a
        # flyweight row, so the memo hits ~100%)
        idx_append = idx_list.append
        code_append = code_list.append
        idx_get = id_to_idx.get
        memo: Dict[int, tuple] = {}
        memo_get = memo.get
        term_desired = TERMINAL_DESIRED
        term_client = TERMINAL_CLIENT
        for alloc in snapshot.allocs():
            if alloc.desired_status in term_desired or \
                    alloc.client_status in term_client:
                continue
            i = idx_get(alloc.node_id)
            if i is None:
                continue
            res = alloc.allocated_resources
            hit = memo_get(id(res))
            if hit is None or hit[2] is not res:
                u = _alloc_usage(alloc)
                c = code_of.get(u)
                if c is None:
                    c = len(lut)
                    code_of[u] = c
                    lut.append(u)
                bits = t._alloc_port_bits(alloc)
                if res is not None:
                    memo[id(res)] = hit = (c, bits, res)
                else:
                    hit = (c, bits, None)
            c = hit[0]
            bits = hit[1]
            idx_append(i)
            code_append(c)
            rows[i].append(alloc)
            if bits:
                net_bits[i] |= bits
        # the alloc-id registry is derived from the row lists at seal
        # time (one pass there beats 2M tuple appends here)
        t._bulk_rows_pending = True
        if idx_list:
            ii = np.fromiter(idx_list, np.int32, len(idx_list))
            cc = np.fromiter(code_list, np.int32, len(code_list))
            np.add.at(t.base_used, ii,
                      np.asarray(lut, np.float32)[cc])
        t.finalize()
        return t

    @classmethod
    def build_all(cls, snapshot) -> "NodeTable":
        """Resident-table build: ALL nodes regardless of status/DC —
        readiness and datacenter become per-eval feasibility masks so
        one table serves every eval (SURVEY §7.2 step 8)."""
        return cls.build(snapshot, datacenters=None, include_all=True)

    @classmethod
    def build_from_columns(cls, snapshot, cold) -> "NodeTable":
        """Vectorized cold build from a columnar restore's decoded
        alloc columns (state/columnar.py ColdAllocColumns — ISSUE 8):
        used-resources lands as ONE np.add.at scatter over (node row,
        resources-pool code), with usage and port bits computed once
        per UNIQUE pool entry instead of once per alloc. Produces a
        table identical to build_all(snapshot) on the same state
        (liveness, row lists, port bits — parity-tested in
        tests/test_cold_start.py)."""
        nodes = sorted(snapshot.nodes(), key=lambda n: n.id)
        BUILD_STATS["column_builds"] = \
            BUILD_STATS.get("column_builds", 0) + 1
        t = cls(nodes)
        n_rows = len(cold.allocs)
        if n_rows:
            idx_get = t.id_to_idx.get
            node_idx = np.fromiter(
                (idx_get(nid, -1) for nid in cold.node_ids),
                np.int32, n_rows)
            sel = cold.live & (node_idx >= 0)
            # usage LUT + port bits once per unique resources row;
            # code -1 (no resources) lands on the trailing zero row
            pool = cold.res_pool
            lut = np.zeros((len(pool) + 1, RES_DIMS), np.float32)
            pool_bits: List[int] = []
            for c, res in enumerate(pool):
                comp = res.comparable()
                lut[c] = (float(comp.cpu_shares), float(comp.memory_mb),
                          float(comp.disk_mb),
                          float(sum(nw.mbits for nw in comp.networks)))
                pool_bits.append(_res_port_bits(res))
            if cold.res_codes is not None:
                # astype always copies: frombuffer views are read-only
                codes = cold.res_codes.astype(np.int32)
                codes[codes < 0] = len(pool)
            else:
                codes = np.full(n_rows, len(pool), np.int32)
            live_rows = np.nonzero(sel)[0]
            ii = node_idx[live_rows]
            np.add.at(t.base_used, ii, lut[codes[live_rows]])
            rows = t.live_allocs
            allocs = cold.allocs
            sel_nodes = ii.tolist()
            for j, i in zip(live_rows.tolist(), sel_nodes):
                rows[i].append(allocs[j])
            if any(pool_bits):
                net_bits = t._net_bits
                npool = len(pool)
                for i, c in zip(sel_nodes, codes[live_rows].tolist()):
                    if c < npool:
                        b = pool_bits[c]
                        if b:
                            net_bits[i] |= b
            t._bulk_rows_pending = True
        t.finalize()
        return t

    def clone_for_deltas(self) -> "NodeTable":
        """Copy-on-write clone sharing the immutable node columns
        (capacity, attrs, ids) but with private usage state, so alloc
        deltas applied to the clone never mutate a version an in-flight
        eval is reading (MVCC for the device-facing cache)."""
        t = NodeTable.__new__(NodeTable)
        t.nodes = self.nodes
        t.n = self.n
        t.ids = self.ids
        t.id_to_idx = self.id_to_idx
        t.cols = self.cols
        t.capacity = self.capacity
        t.ready = self.ready
        t.datacenters = self.datacenters
        t.base_used = self.base_used.copy()
        # outer list copied; ROW lists are immutable by convention (the
        # mutators replace rows instead of appending in place), so inner
        # lists are shared between versions
        t.live_allocs = self.live_allocs[:]
        t._net_bits = self._net_bits[:]
        t.free_ports = self.free_ports.copy()
        t._port_col_cache = {}
        t._free_ports_dirty = (None if self._free_ports_dirty is None
                               else set(self._free_ports_dirty))
        self._seal()
        # shared on purpose — see the registry invariant in __init__
        t.alloc_by_id = self.alloc_by_id
        t.mask_cache = self.mask_cache  # node columns shared => masks too
        t.preempt_cache = self.preempt_cache  # row identity keys the entries
        t._attr_codes_cache = self._attr_codes_cache
        t._ready_dc_cache = self._ready_dc_cache  # status cols shared
        t._sealed = True
        t._pending_allocs = []
        t.device_mirror = None      # stamped by the cache per version
        t.device_version = -1
        return t

    @staticmethod
    def _alloc_port_bits(alloc) -> int:
        res = alloc.allocated_resources
        if res is None:
            return 0
        hit = _port_bits_memo.get(id(res))
        if hit is not None and hit[0] is res:
            return hit[1]
        bits = _res_port_bits(res)
        _memo_insert(_port_bits_memo, id(res), (res, bits))
        return bits

    def add_alloc_usage(self, i: int, alloc) -> None:
        u = _alloc_usage(alloc)
        self.base_used[i, 0] += u[0]
        self.base_used[i, 1] += u[1]
        self.base_used[i, 2] += u[2]
        self.base_used[i, 3] += u[3]
        if self._sealed:
            self.live_allocs[i] = self.live_allocs[i] + [alloc]  # row CoW
            self.alloc_by_id[alloc.id] = alloc
        else:
            self.live_allocs[i].append(alloc)
            self._pending_allocs.append((alloc.id, alloc))
        self._net_bits[i] |= self._alloc_port_bits(alloc)
        self._mark_ports_dirty(i)

    def remove_alloc_usage(self, i: int, alloc) -> None:
        """Inverse of add_alloc_usage. Port bits are simply cleared:
        host ports are exclusive per node, so no other live alloc can
        hold the same bit."""
        u = _alloc_usage(alloc)
        self.base_used[i, 0] -= u[0]
        self.base_used[i, 1] -= u[1]
        self.base_used[i, 2] -= u[2]
        self.base_used[i, 3] -= u[3]
        self._seal()
        self.live_allocs[i] = [a for a in self.live_allocs[i]
                               if a.id != alloc.id]
        self.alloc_by_id.pop(alloc.id, None)
        bits = self._alloc_port_bits(alloc)
        # keep ports that the node itself reserves (reserved_host_ports)
        node_bits = 0
        node = self.nodes[i]
        if node.reserved_resources and \
                node.reserved_resources.reserved_host_ports:
            idx = NetworkIndex()
            idx.set_node(node)
            node_bits = self._merge_bits(idx)
        self._net_bits[i] &= ~(bits & ~node_bits)
        self._mark_ports_dirty(i)

    def apply_alloc_change(self, snapshot, alloc_id: str) -> None:
        """Reconcile one alloc's accounted usage with the snapshot's
        current version (the resident-table delta path)."""
        old = self.alloc_by_id.get(alloc_id)
        new = snapshot.alloc_by_id(alloc_id)
        new_live = new is not None and not new.terminal_status()
        if old is not None:
            i = self.id_to_idx.get(old.node_id)
            if i is not None:
                self.remove_alloc_usage(i, old)
        if new_live:
            i = self.id_to_idx.get(new.node_id)
            if i is not None:
                self.add_alloc_usage(i, new)

    def apply_alloc_changes(self, snapshot, alloc_ids) -> set:
        """Batched delta replay: one vectorized usage scatter-add plus
        one row CoW per touched node, instead of per-alloc scalar numpy
        ops (a 10k-alloc plan apply replays in ~50 ms instead of
        ~700 ms — round-5 profile). The remove half of every change
        (update or disappearance) stays on the scalar path — rare in
        steady state; every alloc with a live new version (brand-new or
        updated) is re-added via the batch path.

        Returns the set of touched node row indices — the cache ships
        exactly these rows to the device mirror as a scatter delta."""
        adds = []
        touched: set = set()
        by_id_get = self.alloc_by_id.get
        idx_get = self.id_to_idx.get
        for aid in dict.fromkeys(alloc_ids):
            old = by_id_get(aid)
            new = snapshot.alloc_by_id(aid)
            new_live = new is not None and not new.terminal_status()
            if old is not None:
                i = idx_get(old.node_id)
                if i is not None:
                    self.remove_alloc_usage(i, old)
                    touched.add(i)
            if new_live:
                i = idx_get(new.node_id)
                if i is not None:
                    adds.append((i, new))
                    touched.add(i)
        if not adds:
            return touched
        self._seal()
        idxs = np.fromiter((i for i, _ in adds), np.int32, len(adds))
        usage = np.asarray([_alloc_usage(a) for _, a in adds], np.float32)
        np.add.at(self.base_used, idxs, usage)
        per_node: Dict[int, List] = {}
        for i, a in adds:
            lst = per_node.get(i)
            if lst is None:
                per_node[i] = [a]
            else:
                lst.append(a)
        by_id = self.alloc_by_id
        rows = self.live_allocs
        for i, lst in per_node.items():
            rows[i] = rows[i] + lst          # one row CoW per node
        for _i, a in adds:
            by_id[a.id] = a
        port_bits = self._alloc_port_bits
        for i, a in adds:
            bits = port_bits(a)
            if bits:
                self._net_bits[i] |= bits
                self._mark_ports_dirty(i)
        return touched

    def _mark_ports_dirty(self, i: int) -> None:
        if self._free_ports_dirty is None:
            return  # already fully dirty
        self._free_ports_dirty.add(i)

    def _seal(self) -> None:
        if self._sealed:
            return
        self._sealed = True
        if getattr(self, "_bulk_rows_pending", False):
            # cold build: derive the alloc-id registry from the row
            # lists in one pass
            self._bulk_rows_pending = False
            reg = self.alloc_by_id
            for row in self.live_allocs:
                for alloc in row:
                    reg[alloc.id] = alloc
        if self._pending_allocs:
            reg = self.alloc_by_id
            for aid, alloc in self._pending_allocs:
                reg[aid] = alloc
            self._pending_allocs = []

    def finalize(self) -> None:
        """Seal the bulk-load phase and recompute derived port columns
        for rows whose usage changed."""
        self._seal()
        dirty = self._free_ports_dirty
        if dirty is None:
            rows = range(self.n)
        elif dirty:
            rows = dirty
        else:
            return
        from ..models.networks import MIN_DYNAMIC_PORT, MAX_DYNAMIC_PORT
        span = MAX_DYNAMIC_PORT - MIN_DYNAMIC_PORT + 1
        mask = ((1 << span) - 1) << MIN_DYNAMIC_PORT
        for i in rows:
            self.free_ports[i] = span - (self._net_bits[i] & mask).bit_count()
        self._free_ports_dirty = set()
        self._port_col_cache.clear()

    # -- feasibility columns ------------------------------------------
    def port_used_col(self, port: int) -> np.ndarray:
        """bool[N]: is this host port already used on each node?"""
        col = self._port_col_cache.get(port)
        if col is None:
            bit = 1 << port
            col = np.fromiter(((b & bit) != 0 for b in self._net_bits),
                              dtype=bool, count=self.n)
            self._port_col_cache[port] = col
        return col

    def reserved_ports_ok(self, ports: List[int]) -> np.ndarray:
        """bool[N]: all requested reserved host ports free on the node."""
        ok = np.ones(self.n, dtype=bool)
        for p in ports:
            ok &= ~self.port_used_col(p)
        return ok

    def driver_mask(self, driver: str) -> np.ndarray:
        """DriverChecker (feasible.go:398): driver detected AND healthy.
        Falls back to the attribute form driver.<name>=1."""
        out = np.zeros(self.n, dtype=bool)
        for i, node in enumerate(self.nodes):
            info = node.drivers.get(driver)
            if info is not None:
                out[i] = info.detected and info.healthy
            else:
                out[i] = node.attributes.get(f"driver.{driver}", "") not in ("", "0", "false")
        return out

    def dc_mask(self, datacenters: List[str]) -> np.ndarray:
        dcs = set(datacenters)
        return np.fromiter((d in dcs for d in self.datacenters),
                           dtype=bool, count=self.n)

    def ready_in_dcs(self, datacenters: List[str]):
        """(mask bool[N], n_ready, {dc: count}) of ready nodes in the
        eval's datacenters — readyNodesInDCs (scheduler/util.go:233) as
        cached columns. Node status and DC membership are immutable per
        table version, so one 50k-row pass serves every eval against
        this version instead of a python scan per eval."""
        key = tuple(sorted(set(datacenters)))
        hit = self._ready_dc_cache.get(key)
        if hit is None:
            import collections
            mask = self.ready & self.dc_mask(list(key))
            by_dc = dict(collections.Counter(
                self.datacenters[mask].tolist()))
            hit = (mask, int(mask.sum()), by_dc)
            self._ready_dc_cache[key] = hit
        return hit

    def host_volume_mask(self, volumes: Dict[str, object]) -> np.ndarray:
        """HostVolumeChecker (feasible.go:117)."""
        out = np.ones(self.n, dtype=bool)
        wanted = [(name, req) for name, req in volumes.items()
                  if getattr(req, "type", "host") == "host"]
        if not wanted:
            return out
        for i, node in enumerate(self.nodes):
            for _, req in wanted:
                vol = node.host_volumes.get(req.source)
                if vol is None:
                    out[i] = False
                    break
                if getattr(req, "read_only", False) is False and vol.get("read_only", False):
                    out[i] = False
                    break
        return out

    def attr_codes(self, attribute: str) -> Tuple[np.ndarray, List[str]]:
        """Dictionary-encode one attribute over nodes.
        Returns (codes i32[N] with code==len(values) meaning missing,
        values list). Cached per table version (attributes immutable)."""
        hit = self._attr_codes_cache.get(attribute)
        if hit is not None:
            return hit
        vals, found = self.cols.resolve(attribute)
        mapping: Dict[str, int] = {}
        codes = np.zeros(self.n, dtype=np.int32)
        for i in range(self.n):
            if not found[i]:
                codes[i] = -1
                continue
            v = vals[i]
            c = mapping.get(v)
            if c is None:
                c = len(mapping)
                mapping[v] = c
            codes[i] = c
        values = list(mapping.keys())
        missing = len(values)
        codes[codes == -1] = missing
        self._attr_codes_cache[attribute] = (codes, values)
        return codes, values


class NodeTableCache:
    """Resident node table shared across evals (SURVEY §7.2 step 8).

    Each refresh produces a NEW table version via copy-on-write
    (clone_for_deltas), so snapshots taken earlier keep reading their
    version — the device-facing analog of the store's MVCC roots.
    Alloc changes apply as row deltas from the store changelog; node-set
    changes (rare: registration, status flips, drain) trigger a full
    rebuild because they invalidate the attribute columns.

    Each served table carries a device-mirror token
    (ops/device_table.py): the dense columns live on device across
    evals and advance by the same row deltas as scatter-sets, so
    `get` hands the kernel a device handle + delta log instead of a
    rebuild + re-upload. `NOMAD_TPU_TABLE_DELTA=0` forces the old
    rebuild path for bisection."""

    def __init__(self):
        from .device_table import DeviceNodeTable
        self._lock = make_lock()
        self._table: Optional[NodeTable] = None
        self._index = -1
        self.device = DeviceNodeTable()
        self.stats: Dict[str, int] = {"full_builds": 0,
                                      "delta_refreshes": 0}

    def _stamp(self, t: NodeTable, version: int) -> NodeTable:
        t.device_mirror = self.device
        t.device_version = version
        return t

    def prime(self, snapshot, cold=None) -> None:
        """Cold-start install (ISSUE 8 — server/core.py restore
        pipeline): build the resident table ONCE at the restored index,
        from the snapshot's decoded alloc columns when available
        (NodeTable.build_from_columns), so the first eval after
        recovery takes the delta path instead of paying a dense
        rebuild inside its latency budget. Pair with prefetch_device()
        to overlap the device H2D upload with WAL tail replay."""
        from ..utils import stages
        t0 = time.perf_counter() if stages.enabled else 0.0
        t = (NodeTable.build_from_columns(snapshot, cold)
             if cold is not None else NodeTable.build_all(snapshot))
        with self._lock:
            self._table = self._stamp(t, self.device.note_rebuild())
            self._index = snapshot.latest_index()
            self.stats["primes"] = self.stats.get("primes", 0) + 1
        if stages.enabled:
            stages.add("table_build", time.perf_counter() - t0)

    def prefetch_device(self) -> None:
        """Materialize the device mirror for the current table (full
        H2D upload). Run on a background thread at cold start so the
        upload overlaps WAL replay; a no-op when nothing is primed.
        When mesh routing is configured, the mesh-resident table is
        uploaded too — one SHARDED H2D per column (the shard-aware
        build_from_columns landing), so the first eval after recovery
        rides sharded residency instead of paying per-eval re-puts."""
        with self._lock:
            t = self._table
        if t is None:
            return
        try:
            self.device.arrays_for(t)
        except Exception:       # pragma: no cover — defensive: a dead
            pass                # device falls back to dense shipping
        try:
            from .select import get_shared_sharded
            sh = get_shared_sharded()
            if sh is not None:
                sh.resident.arrays_for(t)
        except Exception:       # pragma: no cover — defensive: the
            pass                # mesh path falls back to dense shipping

    def fold_mesh(self) -> dict:
        """Reclaim for the governor's mesh.reshard_debt watermark:
        replace the mesh-resident table's scatter history with one
        contiguous sharded re-upload from the current host table."""
        from .select import _SHARED_SHARDED
        sh = _SHARED_SHARDED
        with self._lock:
            t = self._table
        if sh is None:
            return {"folded": False, "reason": "no mesh"}
        if t is None:
            return {"folded": False, "reason": "no table"}
        return sh.resident.fold(t, t.device_version)

    def mesh_reshard_debt(self) -> int:
        """Rows scattered into the mesh-resident table since its last
        contiguous upload (0 when no mesh dispatcher exists)."""
        from .select import _SHARED_SHARDED
        sh = _SHARED_SHARDED
        return sh.resident.debt() if sh is not None else 0

    def get(self, snapshot, build: bool = True) -> Optional[NodeTable]:
        from ..utils import stages
        from .device_table import delta_enabled
        store = snapshot._store
        target = snapshot.latest_index()
        with self._lock:
            if self._table is not None and self._index == target:
                return self._table
            if self._table is not None and target < self._index:
                # older snapshot than the cache: serve it a private
                # build — or nothing, for callers that would rather
                # fall back than pay a full build
                return NodeTable.build_all(snapshot) if build else None
            t0 = time.perf_counter() if stages.enabled else 0.0
            if self._table is None:
                if not build:
                    return None
                self.stats["full_builds"] += 1
                self._table = self._stamp(NodeTable.build_all(snapshot),
                                          self.device.note_rebuild())
                self._index = target
                if stages.enabled:
                    stages.add("table_build", time.perf_counter() - t0)
                return self._table
            changes = store.changes_since(self._index, target)
            if changes is None or any(k == "node" for k, _ in changes) \
                    or (changes and not delta_enabled()):
                if not build:
                    return None
                self.stats["full_builds"] += 1
                self._table = self._stamp(NodeTable.build_all(snapshot),
                                          self.device.note_rebuild())
                self._index = target
                if stages.enabled:
                    stages.add("table_build", time.perf_counter() - t0)
                return self._table
            if changes:
                # last-write-wins dedupe, then row deltas on a fresh
                # clone; the touched rows ship to the device mirror as
                # an async scatter (the double-buffered half of the
                # pipelined worker loop — the device applies them while
                # the host builds the next eval's masks)
                seen = dict.fromkeys(aid for _k, aid in changes)
                t = self._table.clone_for_deltas()
                rows = t.apply_alloc_changes(snapshot, seen)
                t.finalize()
                BUILD_STATS["delta_refreshes"] += 1
                self.stats["delta_refreshes"] += 1
                self._table = self._stamp(
                    t, self.device.note_delta(t, rows))
                if stages.enabled:
                    stages.add("table_build", time.perf_counter() - t0)
            self._index = target
            return self._table

    # -- governor integration (fold-to-rebuild reclaim) ----------------
    def device_delta_debt(self) -> int:
        return self.device.debt()

    def device_delta_log_len(self) -> int:
        return self.device.log_len()

    def device_mirror_bytes(self) -> int:
        """Bytes the device-resident mirror holds (telemetry
        `nomad.device.mirror_bytes`; 0 until materialized)."""
        return self.device.device_bytes()

    def fold_device(self) -> dict:
        """Reclaim: replace the mirror's scatter history with one
        contiguous re-upload from the current host table (registered
        as the node_table.delta_debt watermark's reclaim)."""
        with self._lock:
            if self._table is None:
                return {"folded": False, "reason": "no table"}
            return self.device.fold(self._table,
                                    self._table.device_version)

    def preempt_cache_len(self) -> int:
        """Victim-set memo entries on the current table (the dict is
        shared across delta clones, so this IS the live memo size) —
        the governor's preemption.victim_cache_entries gauge."""
        with self._lock:
            t = self._table
        return len(t.preempt_cache) if t is not None else 0

    def clear_preempt_cache(self) -> dict:
        """Reclaim for governor_preempt_cache_high: drop every victim
        memo entry (each pins a live-alloc row list + victim allocs);
        the next preemption round re-derives misses columnar."""
        with self._lock:
            t = self._table
        if t is None:
            return {"dropped": 0}
        dropped = len(t.preempt_cache)
        t.preempt_cache.clear()
        from ..scheduler.preemption import PREEMPT_STATS
        PREEMPT_STATS["cache_clears"] += 1
        return {"dropped": dropped}


class ProposedIndex:
    """Per-eval view of the job's proposed allocations: existing live
    allocs of this job plus the in-flight plan, minus stops/preemptions
    (context.go:120-157 ProposedAllocs), projected onto node indices."""

    def __init__(self, table: NodeTable, job, existing_allocs: List,
                 plan=None):
        self.table = table
        self.job = job
        self.plan = plan
        n = table.n
        # per-node usage delta from the plan (stops/preemptions free
        # resources; in-flight placements consume them); touched rows
        # tracked so the overlay can ship sparsely to a device-resident
        # table (used_sparse)
        self.plan_delta = np.zeros((n, RES_DIMS), dtype=np.float32)
        self._plan_touched: set = set()
        # counts of this job's proposed allocs per node / per task group
        self.job_count = np.zeros(n, dtype=np.int32)
        self.tg_count: Dict[str, np.ndarray] = {}
        # job's proposed allocs grouped by node idx (for property counts)
        self.job_allocs_by_node: Dict[int, List] = {}
        # flat (node row, task group) per proposed alloc, in count
        # order — the scatter-ready form the vectorized property
        # counts read (ops/spread.property_counts_vec, ISSUE 20)
        self._prop_rows: List[int] = []
        self._prop_tgs: List[str] = []
        self._prop_arrays: Optional[Tuple[np.ndarray, np.ndarray]] = None

        stopped_ids = set()
        if plan is not None:
            for allocs in plan.node_update.values():
                for a in allocs:
                    stopped_ids.add(a.id)
            for allocs in plan.node_preemptions.values():
                for a in allocs:
                    stopped_ids.add(a.id)

        for a in existing_allocs:
            if a.terminal_status() or a.id in stopped_ids:
                continue
            i = table.id_to_idx.get(a.node_id)
            if i is None:
                continue
            self._count(i, a)

        if plan is not None:
            # stops/preemptions of *any* job free resources on the node
            all_stopped = {}
            for allocs in plan.node_update.values():
                for a in allocs:
                    all_stopped[a.id] = a
            for allocs in plan.node_preemptions.values():
                for a in allocs:
                    all_stopped.setdefault(a.id, a)
            for a in all_stopped.values():
                i = table.id_to_idx.get(a.node_id)
                if i is None:
                    continue
                # the stub may lack resources; look it up in live allocs
                usage = _alloc_usage(a)
                if not any(usage):
                    for live in table.live_allocs[i]:
                        if live.id == a.id:
                            usage = _alloc_usage(live)
                            break
                self.plan_delta[i] -= usage
                self._plan_touched.add(i)
            for node_id, allocs in plan.node_allocation.items():
                i = table.id_to_idx.get(node_id)
                if i is None:
                    continue
                self._plan_touched.add(i)
                for a in allocs:
                    self.plan_delta[i] += _alloc_usage(a)
                    if a.job_id == job.id and a.namespace == job.namespace:
                        self._count(i, a)

    def _count(self, i: int, alloc) -> None:
        self.job_count[i] += 1
        tg = alloc.task_group
        arr = self.tg_count.get(tg)
        if arr is None:
            arr = np.zeros(self.table.n, dtype=np.int32)
            self.tg_count[tg] = arr
        arr[i] += 1
        self.job_allocs_by_node.setdefault(i, []).append(alloc)
        self._prop_rows.append(i)
        self._prop_tgs.append(tg)

    def prop_arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        """(rows i32[M], tgs str[M]) per proposed alloc — materialized
        once per eval (construction is the only mutator)."""
        hit = self._prop_arrays
        if hit is None:
            m = len(self._prop_rows)
            rows = (np.asarray(self._prop_rows, dtype=np.int32)
                    if m else np.zeros(0, dtype=np.int32))
            tgs = (np.asarray(self._prop_tgs)
                   if m else np.zeros(0, dtype="U1"))
            hit = self._prop_arrays = (rows, tgs)
        return hit

    def used(self) -> np.ndarray:
        """f32[N,3] effective usage: live + plan overlay."""
        return self.table.base_used + self.plan_delta

    def used_sparse(self) -> Tuple[np.ndarray, np.ndarray]:
        """(rows i32[M], deltas f32[M,D]) such that used() equals
        table.base_used with deltas scattered at rows — the per-eval
        plan overlay in sparse form, so a device-resident dispatch
        ships M touched rows instead of the dense (N, D) column."""
        if not self._plan_touched:
            return (np.zeros(0, np.int32),
                    np.zeros((0, RES_DIMS), np.float32))
        rows = np.fromiter(sorted(self._plan_touched), np.int32,
                           len(self._plan_touched))
        return rows, self.plan_delta[rows]

    def tg_counts(self, tg_name: str) -> np.ndarray:
        arr = self.tg_count.get(tg_name)
        if arr is None:
            return np.zeros(self.table.n, dtype=np.int32)
        return arr

    def property_counts(self, attribute: str, values: List[str],
                        tg_name: Optional[str] = None) -> Tuple[np.ndarray, np.ndarray]:
        """(counts f32[C+1], present bool[C+1]) of this job's proposed
        allocs per attribute value (propertyset.go UsedCount semantics;
        tg_name restricts to one task group). Index C is the
        missing-attribute bucket."""
        c = len(values)
        # ride the table's cached dictionary encoding — a cols.resolve
        # here would re-scan all N nodes per spread per eval
        tcodes, tvals = self.table.attr_codes(attribute)
        if tvals is values:
            from .spread import enabled as _residue_on, \
                property_counts_vec
            if _residue_on():
                # one gather + np.add.at over the proposed rows'
                # codes replaces the per-alloc Python walk (ISSUE 20)
                return property_counts_vec(self, tcodes, c, tg_name)
        counts = np.zeros(c + 1, dtype=np.float32)
        present = np.zeros(c + 1, dtype=bool)
        missing = len(tvals)
        if tvals is values:
            remap = None
        else:
            code_of = {v: i for i, v in enumerate(values)}
            remap = [code_of.get(v) for v in tvals]
        for i, allocs in self.job_allocs_by_node.items():
            tcode = int(tcodes[i])
            if tcode == missing:
                continue
            code = tcode if remap is None else remap[tcode]
            if code is None:
                continue
            for a in allocs:
                if tg_name is not None and a.task_group != tg_name:
                    continue
                counts[code] += 1
                present[code] = True
        return counts, present
