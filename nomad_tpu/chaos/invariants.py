"""Invariant checks for chaos cells (ISSUE 15).

Each check returns a plain dict — ``{"name", "pass", ...detail}`` —
that the matrix records verbatim in the cell's artifact section, so a
failed run carries the evidence, not just the verdict. The checks read
ONLY operator-visible state: the state store, ``Server.cluster_stats``
(the r17 observability rollup), the governor event ring, and the r18
race monitor. If an invariant can't be judged from what an operator
can see, the observability plane is missing a signal — that's a
finding too.
"""

from __future__ import annotations

import time
from collections import Counter
from typing import Dict, Iterable, List, Optional, Tuple

from ..models import JOB_TYPE_SYSTEM


def check(name: str, passed: bool, **detail) -> dict:
    return {"name": name, "pass": bool(passed), **detail}


def _live_allocs(store, namespace: str, job_id: str) -> list:
    return [a for a in store.allocs_by_job(namespace, job_id)
            if not a.terminal_status()]


def alloc_intent(store, intent: Dict[Tuple[str, str], int],
                 name: str = "no_lost_or_duplicated_alloc") -> dict:
    """The workload's intent reconciles: for every job, each task-group
    slot name carries EXACTLY ONE non-terminal alloc — a missing name
    is a LOST alloc (a placement the workload asked for that nothing
    carries), a doubled name is a DUPLICATED one (the double-commit /
    double-reschedule class a worker kill or recovery replay would
    introduce)."""
    lost: List[str] = []
    dup: List[str] = []
    placed = 0
    for (ns, job_id), expected in intent.items():
        live = _live_allocs(store, ns, job_id)
        names = Counter(a.name for a in live)
        placed += len(live)
        dup.extend(f"{n} x{c}" for n, c in names.items() if c > 1)
        if len(names) < expected:
            lost.append(f"{job_id}: {len(names)}/{expected} names live")
        elif len(live) > expected and not dup:
            # same count of names but extra rows means duplicate names
            # already caught above; extra NAMES beyond intent is an
            # over-placement (count overrun)
            lost.append(f"{job_id}: {len(live)} live > {expected} asked")
    return check(name, not lost and not dup,
                 jobs=len(intent), live_allocs=placed,
                 lost=lost[:8], duplicated=dup[:8])


def system_fanout(store, job, expected_node_ids: Iterable[str]) -> dict:
    """SystemScheduler cross-check: exactly one live alloc of the
    system job on every expected (feasible, ready) node, zero
    elsewhere — the reference's scheduler/system.go contract."""
    expected = set(expected_node_ids)
    live = _live_allocs(store, job.namespace, job.id)
    by_node = Counter(a.node_id for a in live)
    missing = [n[:8] for n in expected if n not in by_node]
    doubled = [n[:8] for n, c in by_node.items() if c > 1]
    strays = [n[:8] for n in by_node if n not in expected]
    return check("system_fanout_covers_feasible_nodes",
                 not missing and not doubled and not strays,
                 expected_nodes=len(expected), live_allocs=len(live),
                 missing=missing[:8], doubled=doubled[:8],
                 strays=strays[:8])


def no_plan_committed_twice(store, intent, injector,
                            bound_s: Optional[float] = None) -> dict:
    """Across a worker kill: the killed eval's plan committed ONCE.
    Observable consequence — after the broker redelivers and the retry
    settles, the intent still holds with no duplicated name, AND every
    eval the injector killed reached a terminal status (the redelivery
    actually happened; a kill that silently wedges an eval forever is
    its own failure). Polls for the redelivery within the visibility
    bound — the nack path is delayed by design."""
    from .faults import DEFAULTS
    bound = DEFAULTS["visibility_bound_s"] if bound_s is None else bound_s

    def unsettled_now() -> List[str]:
        out = []
        for eid in injector.killed_evals:
            ev = store.eval_by_id(eid)
            if ev is None or ev.status not in ("complete", "failed",
                                               "canceled"):
                out.append(f"{eid[:8]}:"
                           f"{getattr(ev, 'status', 'missing')}")
        return out

    deadline = time.monotonic() + bound
    unsettled = unsettled_now()
    while unsettled and time.monotonic() < deadline:
        time.sleep(0.1)
        unsettled = unsettled_now()
    base = alloc_intent(store, intent, name="no_plan_committed_twice")
    base["killed_evals"] = len(injector.killed_evals)
    base["unsettled_killed_evals"] = unsettled
    base["pass"] = bool(base["pass"] and injector.killed_evals
                        and not unsettled)
    return base


def failure_visibility(server, expected_down: int,
                       bound_s: Optional[float] = None,
                       expected_stale: int = 0) -> dict:
    """The r17 rollup reflects injected failures within the bound:
    `cluster.nodes_down` reaches the injected count (and
    `stale_heartbeats` the dropped-payload count) within
    chaos_visibility_bound_s of the check starting. Polls — failure
    detection is asynchronous by design; the INVARIANT is the bound."""
    from .faults import DEFAULTS
    bound = DEFAULTS["visibility_bound_s"] if bound_s is None else bound_s
    t0 = time.monotonic()
    deadline = t0 + bound
    cs = server.cluster_stats()
    while time.monotonic() < deadline and (
            cs["nodes_down"] < expected_down
            or cs["stale_heartbeats"] < expected_stale):
        time.sleep(0.1)
        cs = server.cluster_stats()
    elapsed = time.monotonic() - t0
    ok = (cs["nodes_down"] >= expected_down
          and cs["stale_heartbeats"] >= expected_stale)
    return check("failure_visibility_within_bound", ok,
                 bound_s=bound, elapsed_s=round(elapsed, 2),
                 nodes_down=cs["nodes_down"],
                 expected_down=expected_down,
                 stale_heartbeats=cs["stale_heartbeats"],
                 expected_stale=expected_stale)


def used_vs_allocated(server, expect_divergence: bool,
                      min_allocated_ratio: float = 0.02,
                      used_floor_ratio: float = 0.5) -> dict:
    """Placement-without-execution detection (r17 economics): a
    scenario that 'places' allocs nothing runs shows the allocated
    ratio rising while host-truth used stays flat. Cells with real
    clients assert NO divergence (used tracks allocated); cells whose
    nodes are synthetic assert the signal FIRES — a detector that
    can't see its own scenario is broken."""
    cs = server.cluster_stats()
    alloc_r = max(cs["fleet_cpu_allocated_ratio"],
                  cs["fleet_mem_allocated_ratio"])
    used_r = max(cs["fleet_cpu_used_ratio"], cs["fleet_mem_used_ratio"])
    diverged = bool(alloc_r >= min_allocated_ratio
                    and used_r < alloc_r * used_floor_ratio)
    ok = diverged if expect_divergence else \
        bool(alloc_r < min_allocated_ratio or not diverged)
    return check("used_vs_allocated_divergence", ok,
                 expect_divergence=expect_divergence, diverged=diverged,
                 allocated_ratio=round(alloc_r, 4),
                 used_ratio=round(used_r, 4),
                 nodes_reporting=cs["nodes_reporting"])


def drained_nodes_empty(store, node_ids: Iterable[str]) -> dict:
    """After a drain storm settles, drained nodes carry no live
    allocs destined to run (migrating allocs moved or stopped)."""
    node_ids = list(node_ids)
    still = []
    for nid in node_ids:
        live = [a for a in store.allocs_by_node(nid)
                if not a.terminal_status()
                and not a.client_terminal_status()]
        if live:
            still.append(f"{nid[:8]}:{len(live)}")
    return check("drained_nodes_carry_no_live_allocs", not still,
                 drained=len(node_ids), still_occupied=still[:8])


def allocs_on_live_nodes(store, intent,
                         dead_node_ids: Iterable[str]) -> dict:
    """After a mass client failure reschedules, no live alloc of the
    intent jobs sits on a dead node (system jobs exempt — they are
    node-pinned and die with the node)."""
    dead = set(dead_node_ids)
    strayed = []
    for (ns, job_id) in intent:
        job = store.job_by_id(ns, job_id)
        if job is not None and job.type == JOB_TYPE_SYSTEM:
            continue
        for a in _live_allocs(store, ns, job_id):
            if a.node_id in dead:
                strayed.append(f"{a.name}@{a.node_id[:8]}")
    return check("no_live_alloc_on_dead_node", not strayed,
                 dead_nodes=len(dead), strayed=strayed[:8])


def per_node_saturation(store, intent, max_util: float = 0.85) -> dict:
    """Hot-spot bound under spread/anti-affinity topologies: the p99
    per-node allocated-cpu RATIO (the workload's allocs over the
    node's comparable capacity) stays under saturation — the
    scheduling-side analog of the per-node utilization p99 the r17
    rollup reports from host truth. Bin-packing concentrates by
    design; what spread must prevent is a saturated hot spot."""
    import numpy as np
    per_node: Dict[str, float] = {}
    total = 0
    for (ns, job_id) in intent:
        for a in _live_allocs(store, ns, job_id):
            cpu = sum(t.cpu.cpu_shares
                      for t in a.allocated_resources.tasks.values())
            per_node[a.node_id] = per_node.get(a.node_id, 0.0) + cpu
            total += 1
    nodes = store.nodes()
    if total == 0 or not nodes:
        return check("per_node_utilization_p99_bound", False,
                     reason="nothing placed")
    utils = []
    for n in nodes:
        cap = n.comparable_resources().cpu_shares
        utils.append(per_node.get(n.id, 0.0) / cap if cap > 0 else 0.0)
    p99 = float(np.percentile(np.asarray(utils), 99))
    return check("per_node_utilization_p99_bound", p99 <= max_util,
                 per_node_util_p99=round(p99, 4), bound=max_util,
                 hottest_util=round(max(utils), 4))


def spread_coverage(store, intent, attr_of_node,
                    min_distinct: int, attr: str = "attr") -> dict:
    """The spread/anti-affinity contract, per job: each job's live
    allocs cover at least `min_distinct` distinct values of the
    spread attribute (a job that doubles a rack while racks sit empty
    has lost its spread)."""
    thin = []
    for (ns, job_id) in intent:
        seen = set()
        for a in _live_allocs(store, ns, job_id):
            node = store.node_by_id(a.node_id)
            if node is not None:
                seen.add(attr_of_node(node))
        if len(seen) < min_distinct:
            thin.append(f"{job_id}: {len(seen)} {attr}s")
    return check(f"spread_coverage_{attr}", not thin,
                 min_distinct=min_distinct, thin=thin[:8])


def blocked_evals_drained(server) -> dict:
    """After the thundering herd unblocks, no eval is still parked in
    the blocked tracker and the broker holds no unacked backlog."""
    stats = server.blocked_evals.stats
    broker = server.eval_broker.stats.as_dict()
    blocked = stats.total_blocked + stats.total_escaped
    ok = blocked == 0 and broker["unacked"] == 0
    return check("blocked_evals_drained", ok,
                 blocked=stats.total_blocked,
                 escaped=stats.total_escaped,
                 broker_unacked=broker["unacked"])


# -- race sanitizer coupling (r18) ------------------------------------

def race_baseline() -> Optional[int]:
    """Unsuppressed finding count before the cell (None = shims off)."""
    from ..analysis import race
    if not race.enabled():
        return None
    return race.monitor.unsuppressed_count()


def race_clean(baseline: Optional[int]) -> dict:
    """Zero NEW unsuppressed `NOMAD_TPU_RACE` findings during the cell
    — the per-cell form of tests/test_race_ratchet.py's assertion.
    With the shims off the check reports pass with race='off' (CI runs
    the quick cells under NOMAD_TPU_RACE=1 where it has teeth)."""
    from ..analysis import race
    if baseline is None or not race.enabled():
        return check("race_findings_zero", True, race="off",
                     findings=0)
    now = race.monitor.unsuppressed_count()
    delta = now - baseline
    detail = {}
    if delta:
        detail["new_findings"] = [
            {k: f.get(k) for k in ("rule", "site", "message", "kind")}
            for f in race.monitor.findings(include_suppressed=False)
            [baseline:]]
    return check("race_findings_zero", delta == 0, race="on",
                 findings=delta, **detail)
