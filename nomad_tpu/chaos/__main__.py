"""`python -m nomad_tpu.chaos` / `nomad dev chaos` — run the scenario
matrix (or one cell) and emit a CHAOS_rNN.json artifact.

Local tooling like `nomad dev lint`: no agent connection — the cells
build their own in-process servers. Exit status is the matrix verdict
(non-zero when any cell failed), so CI can gate on it directly.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m nomad_tpu.chaos",
        description="scenario matrix + fault injection harness")
    p.add_argument("-cell", default="",
                   help="comma-separated cell names (default: every "
                        "quick cell)")
    p.add_argument("-full", action="store_true",
                   help="full-scale cells (bigger fleets, soak "
                        "flatness bounds) instead of quick")
    p.add_argument("-seed", type=int, default=None,
                   help="override the per-cell derived fault seed")
    p.add_argument("-list", action="store_true", dest="list_cells",
                   help="list cells and exit")
    p.add_argument("-output", default="",
                   help="artifact path (default: next free "
                        "CHAOS_rNN.json in the cwd)")
    p.add_argument("-no-artifact", action="store_true",
                   dest="no_artifact", help="print JSON to stdout only")
    p.add_argument("-q", action="store_true", dest="quiet",
                   help="suppress per-cell progress logging")
    args = p.parse_args(argv)

    from .scenarios import SCENARIOS
    if args.list_cells:
        for s in SCENARIOS.values():
            kind = "cluster" if s.cluster else \
                ("quick" if s.quick else "full")
            print(f"{s.name:24s} [{kind:7s}] {s.title}")
        return 0

    logging.basicConfig(
        level=logging.ERROR if args.quiet else logging.WARNING)
    # chaos cells are a correctness harness — they never need an
    # accelerator, and a dead TPU tunnel must not hang them
    from ..utils.platform import force_cpu_platform
    import jax
    if not jax.config.jax_platforms:        # respect an explicit choice
        force_cpu_platform(1)

    from .matrix import run_matrix, write_artifact
    names = [n.strip() for n in args.cell.split(",") if n.strip()] \
        or None
    try:
        result = run_matrix(names=names, quick=not args.full,
                            seed=args.seed)
    except KeyError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 2

    for cell in result["cells"]:
        verdict = "PASS" if cell["pass"] else "FAIL"
        flat = cell["flatness"].get("pass")
        flat_s = {True: "flat", False: "DRIFTING",
                  None: "flatness n/a"}[flat]
        print(f"{cell['name']:24s} {verdict}  "
              f"{cell['placements_per_sec']:8.1f} placements/s  "
              f"p99 {cell['settle_p99_ms']:8.1f} ms  {flat_s}  "
              f"invariants {len(cell['invariants']) - len(cell['invariants_failed'])}"
              f"/{len(cell['invariants'])}"
              + (f"  failed: {cell['invariants_failed']}"
                 if cell["invariants_failed"] else ""))
    s = result["summary"]
    print(f"{s['passed']}/{s['cells']} cells passed, "
          f"{s['invariants_checked']} invariants checked "
          f"({s['invariants_failed']} failed), race: "
          f"{result['race']} ({s['race_findings']} findings)")

    if args.no_artifact:
        json.dump(result, sys.stdout, indent=1, default=str)
        print()
    else:
        path = write_artifact(result, path=args.output or None)
        print(f"artifact: {path}")
    return 0 if s["passed"] == s["cells"] else 1


if __name__ == "__main__":
    sys.exit(main())
