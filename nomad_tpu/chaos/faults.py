"""Deterministic fault injection for the scenario matrix (ISSUE 15).

Jepsen-style chaos needs two halves: a WORKLOAD (chaos/scenarios.py)
and a NEMESIS. This module is the nemesis — a seeded schedule of
injections delivered through explicit hook points compiled into the
production code:

  worker.plan_committed   EvalLane.submit_plan, after the plan future
                          resolved (plan IS committed) and BEFORE the
                          worker acks the eval — raising here is a
                          worker dying mid-commit; the broker's nack
                          path redelivers the eval and the retry must
                          reconcile, not double-place
  swim.probe              SwimDetector._ping/_indirect_ping — a truthy
                          interposer verdict fails the probe, so a
                          victim set partitions away at the SWIM layer
                          while its process stays healthy
  server.heartbeat        Server.heartbeat — a truthy verdict drops
                          the beat in transit (the client believes it
                          beat; the TTL timer and the stale-stats
                          clock both keep running)
  raft.replicate          RaftNode._replicate_peer, before the round
                          trip — a truthy verdict drops the whole
                          AppendEntries exchange on the LEADER side,
                          so the victim's log/store lags while its
                          process stays healthy (the follower
                          snapshot-fence fault, ISSUE 16)
  raft.election           RaftNode._ticker, at an expired election
                          deadline — a truthy verdict resets the
                          deadline instead of campaigning, so a
                          replication-lagged victim stays a lagging
                          follower instead of deposing the leader
  plan.group_commit       PlanApplier.apply_group, after the group's
                          raft entry is appended but before any
                          submitter future resolves — the observation
                          point for killing a leader mid-group-commit

plus two direct actions that need no hook: `corrupt_wal_tail` (flip a
byte range at the end of raft.log between a shutdown and a reboot) and
`FaultInjector.force_governor_reclaim` (drive a registered reclaim
callback mid-wave — the governor-pressure fault).

Cost discipline: the hook points guard on the module-level `ACTIVE`
bool, so production traffic pays one attribute read + branch per hook
site and the interposer dictionary is consulted only while an injector
is installed. This module imports nothing from the server tree —
server/worker/swim import IT, the matrix imports them.

Every injection is recorded on the injector (`injector.events`) with a
monotonic timestamp, so a cell's artifact section carries the exact
fault schedule its invariants were judged under.
"""

from __future__ import annotations

import logging
import os
import random
import time
from typing import Callable, Dict, List, Optional, Set

from ..utils.locks import make_lock

LOG = logging.getLogger("nomad_tpu.chaos")

# fast-path gate read by the production hook sites; flipped only by
# FaultInjector.install/uninstall below
ACTIVE = False

_INSTALL_L = make_lock()
_INJECTOR: Optional["FaultInjector"] = None

# ServerConfig wiring (the race.configure idiom): Server.__init__
# pushes its chaos_* knobs here so cells that don't pin their own get
# the operator-configured defaults
DEFAULTS = {
    "seed": 0,
    "visibility_bound_s": 15.0,
}


def configure(seed: Optional[int] = None,
              visibility_bound_s: Optional[float] = None) -> None:
    """Install ServerConfig.chaos_* knob values as module defaults."""
    if seed is not None:
        DEFAULTS["seed"] = int(seed)
    if visibility_bound_s is not None:
        DEFAULTS["visibility_bound_s"] = float(visibility_bound_s)


class WorkerKilled(Exception):
    """Raised at the worker.plan_committed hook: the worker 'dies'
    after its plan committed but before it acked the eval. The
    process_eval exception path nacks, the broker redelivers, and the
    retried eval's reconcile must find the committed placements."""


def fire(point: str, **kw):
    """Called from the production hook sites (guarded on ACTIVE).
    Returns the installed injector's verdict for `point`, or None when
    no interposer covers it. An interposer may raise (worker kill)."""
    inj = _INJECTOR
    if inj is None:
        return None
    fn = inj._interposers.get(point)
    if fn is None:
        return None
    return fn(**kw)


class FaultInjector:
    """One cell's seeded nemesis. Use as a context manager:

        with FaultInjector(seed=7) as inj:
            inj.kill_worker_on_commit(nth=2)
            ... drive the workload ...

    Only one injector is installed at a time (cells are sequential);
    installing a second raises."""

    def __init__(self, seed: Optional[int] = None):
        self.seed = DEFAULTS["seed"] if seed is None else int(seed)
        self.rng = random.Random(0xFA117 ^ (self.seed * 2654435761))
        self.events: List[dict] = []
        self._l = make_lock()
        self._interposers: Dict[str, Callable] = {}
        # worker-kill arm state
        self._kill_at: Optional[int] = None
        self._commits_seen = 0
        self.killed_evals: List[str] = []
        # partition arm state
        self._victims: Set[str] = set()
        # heartbeat arm state
        self._hb_victims: Optional[Set[str]] = None   # None == all
        self._hb_drop_prob = 0.0
        self.dropped_beats = 0
        # replication-lag arm state (ISSUE 16)
        self._repl_victims: Set[str] = set()
        self.dropped_replications = 0
        # wire-latency arm state (ISSUE 16): per-round-trip delay on
        # the leader's replication pumps, modelling inter-server RTT
        self._wire_rtt_s = 0.0
        # group-commit trip arm state (ISSUE 16): the cell's MAIN
        # thread waits on this and performs the leader kill itself —
        # killing from inside the hook would deadlock the shutdown
        # join against the very committer thread the hook runs on
        import threading as _threading
        self.group_commit_tripped = _threading.Event()
        self._trip_at: Optional[int] = None
        self._groups_seen = 0
        self.tripped_group_index = 0

    # -- lifecycle -----------------------------------------------------
    def install(self) -> "FaultInjector":
        global ACTIVE, _INJECTOR
        with _INSTALL_L:
            if _INJECTOR is not None and _INJECTOR is not self:
                raise RuntimeError("a FaultInjector is already installed")
            _INJECTOR = self
            ACTIVE = True
        return self

    def uninstall(self) -> None:
        global ACTIVE, _INJECTOR
        with _INSTALL_L:
            if _INJECTOR is self:
                _INJECTOR = None
                ACTIVE = False

    def __enter__(self) -> "FaultInjector":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    def record(self, kind: str, **detail) -> None:
        with self._l:
            self.events.append({"kind": kind, "t": time.monotonic(),
                                **detail})

    # -- worker kill mid-commit ----------------------------------------
    def kill_worker_on_commit(self, nth: int = 1) -> None:
        """Arm: the nth plan commit observed after arming kills its
        worker (raises WorkerKilled between commit and ack)."""
        self._kill_at = max(1, int(nth))
        self._commits_seen = 0
        self._interposers["worker.plan_committed"] = self._on_commit
        self.record("arm", fault="worker_kill", nth=self._kill_at)

    def _on_commit(self, eval_id: str = "", placements: int = 0):
        with self._l:
            self._commits_seen += 1
            due = (self._kill_at is not None
                   and self._commits_seen == self._kill_at)
            if due:
                self._kill_at = None        # one-shot
                self.killed_evals.append(eval_id)
        if due:
            self.record("worker_kill", eval_id=eval_id,
                        placements=placements)
            raise WorkerKilled(
                f"chaos: worker killed mid-commit (eval {eval_id[:8]}, "
                f"{placements} placements committed, ack withheld)")
        return None

    # -- SWIM partition ------------------------------------------------
    def partition(self, victims) -> None:
        """Probes (direct and indirect) to any victim address fail
        until heal_partition(). The victim processes stay healthy —
        this is the network's fault, not theirs."""
        self._victims = set(victims)
        self._interposers["swim.probe"] = self._on_probe
        self.record("partition", victims=sorted(self._victims))

    def heal_partition(self) -> None:
        healed = sorted(self._victims)
        self._victims = set()
        self.record("heal_partition", victims=healed)

    def _on_probe(self, target: str = "", **_kw):
        if target in self._victims:
            self.record("probe_dropped", target=target)
            return True     # truthy == fail the probe
        return None

    # -- heartbeat delay/drop ------------------------------------------
    def drop_heartbeats(self, node_ids=None, prob: float = 1.0) -> None:
        """Beats from the given nodes (all when None) are dropped in
        transit with probability `prob` (seeded RNG — deterministic
        per injector seed). Trips the TTL -> node-down path and ages
        the heartbeat stats payloads into `stale_heartbeats`."""
        self._hb_victims = None if node_ids is None else set(node_ids)
        self._hb_drop_prob = float(prob)
        self._interposers["server.heartbeat"] = self._on_heartbeat
        self.record("arm", fault="heartbeat_drop",
                    nodes=(sorted(n[:8] for n in self._hb_victims)
                           if self._hb_victims is not None else "all"),
                    prob=prob)

    def allow_heartbeats(self) -> None:
        self._hb_victims = set()
        self._hb_drop_prob = 0.0
        self.record("heal", fault="heartbeat_drop")

    def _on_heartbeat(self, node_id: str = "", **_kw):
        victims = self._hb_victims
        if victims is not None and node_id not in victims:
            return None
        if self._hb_drop_prob >= 1.0 or \
                self.rng.random() < self._hb_drop_prob:
            with self._l:
                self.dropped_beats += 1
            return True     # truthy == drop the beat
        return None

    # -- replication lag (ISSUE 16) ------------------------------------
    def lag_replication(self, victims) -> None:
        """AppendEntries round trips from the leader to any victim
        address are dropped until heal_replication() — the victim's
        raft log (and MVCC store) falls behind while its process, RPC
        listener, and SWIM probes all stay healthy. The same arming
        suppresses the victims' election timeouts: a lagging follower
        must stay a follower, not bump the term and depose the leader
        whose lag the cell is measuring."""
        self._repl_victims = set(victims)
        self._interposers["raft.replicate"] = self._on_replicate
        self._interposers["raft.election"] = self._on_election
        self.record("replication_lag", victims=sorted(self._repl_victims))

    def wire_latency(self, rtt_s: float) -> None:
        """Arm: every AppendEntries round trip from the leader is
        stretched by `rtt_s` before dispatch — a stand-in for real
        inter-server network distance on the commit path. Unlike
        lag_replication nothing is dropped: commit latency rises
        uniformly. The multiserver bench arms this identically in both
        arms so a loopback ring exercises the LAN-ring latencies the
        follower plane exists to hide."""
        self._wire_rtt_s = float(rtt_s)
        self._interposers["raft.replicate"] = self._on_replicate
        self.record("wire_latency", rtt_s=rtt_s)

    def heal_replication(self) -> None:
        healed = sorted(self._repl_victims)
        self._repl_victims = set()
        self.record("heal_replication", victims=healed)

    def _on_replicate(self, target: str = "", **_kw):
        if self._wire_rtt_s > 0.0:
            time.sleep(self._wire_rtt_s)
        if target in self._repl_victims:
            with self._l:
                self.dropped_replications += 1
            return True     # truthy == drop the round trip
        return None

    def _on_election(self, addr: str = "", **_kw):
        if addr in self._repl_victims:
            self.record("election_suppressed", addr=addr)
            return True     # truthy == reset deadline, don't campaign
        return None

    # -- leader kill mid-group-commit (ISSUE 16) -----------------------
    def trip_on_group_commit(self, nth: int = 1) -> None:
        """Arm: the nth plan-group commit observed after arming sets
        `group_commit_tripped` (and records the group's raft index).
        The hook itself only OBSERVES — the cell's main thread waits on
        the event and kills the leader from outside, because a kill
        from the committer/applier thread would join against itself."""
        self._trip_at = max(1, int(nth))
        self._groups_seen = 0
        self.group_commit_tripped.clear()
        self._interposers["plan.group_commit"] = self._on_group_commit
        self.record("arm", fault="group_commit_trip", nth=self._trip_at)

    def _on_group_commit(self, index: int = 0, plans: int = 0):
        with self._l:
            self._groups_seen += 1
            due = (self._trip_at is not None
                   and self._groups_seen == self._trip_at)
            if due:
                self._trip_at = None            # one-shot
                self.tripped_group_index = index
        if due:
            self.record("group_commit_trip", index=index, plans=plans)
            self.group_commit_tripped.set()
        return None

    # -- governor pressure ---------------------------------------------
    def force_governor_reclaim(self, server, structure: str = "") -> List[dict]:
        """Drive registered reclaim callbacks NOW (watermark and rate
        limit bypassed) — the mid-wave memory-pressure fault. With
        `structure` empty every reclaimable registration fires; the
        reclaims are the same closures the real watermarks run, so a
        cell proves the workload survives reclamation at the worst
        moment, not just at idle."""
        gov = getattr(server, "governor", None)
        if gov is None:
            self.record("governor_reclaim", skipped="no governor")
            return []
        fired = gov.force_reclaim(structure or None)
        self.record("governor_reclaim", structure=structure or "all",
                    fired=[f["structure"] for f in fired])
        return fired


def corrupt_wal_tail(data_dir: str, span: int = 48,
                     seed: Optional[int] = None) -> dict:
    """Flip every byte in the last `span` bytes of the WAL (XOR with a
    seeded byte stream, guaranteed non-identity) — the torn/corrupt
    tail a crash or bad disk leaves. Run between a shutdown and a
    reboot; RaftLog.replay treats the first undecodable frame as the
    end of history, so the committed prefix must fully recover and the
    lost tail is what the scheduler re-derives from intent."""
    path = os.path.join(data_dir, "raft.log")
    size = os.path.getsize(path)
    span = min(int(span), size)
    if span <= 0:
        return {"path": path, "corrupted_bytes": 0, "wal_bytes": size}
    rng = random.Random(0xBADF ^ ((seed or 0) * 2654435761))
    with open(path, "r+b") as f:
        f.seek(size - span)
        tail = bytearray(f.read(span))
        for i in range(len(tail)):
            tail[i] ^= rng.randint(1, 255)
        f.seek(size - span)
        f.write(tail)
        f.flush()
        os.fsync(f.fileno())
    LOG.warning("chaos: corrupted %d WAL tail bytes of %s", span, path)
    return {"path": path, "corrupted_bytes": span, "wal_bytes": size}
