"""Scenario matrix + fault injection (ISSUE 15).

Import-light on purpose: server/worker/swim import `chaos.faults` for
their hook points, so this package must never import the server tree
at module load. The matrix/scenario halves (which DO build servers)
load lazily through `run_matrix`/`list_scenarios`.
"""

from . import faults  # noqa: F401  (the hook-point half)


def run_matrix(*args, **kwargs):
    from .matrix import run_matrix as _run
    return _run(*args, **kwargs)


def list_scenarios():
    from .scenarios import SCENARIOS
    return list(SCENARIOS)
