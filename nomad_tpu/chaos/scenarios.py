"""The scenario matrix's cells (ISSUE 15): everything the reference's
scheduler surface covers that the bench ladder didn't, each run under
an injected fault with invariant checks.

Cells (chaos/matrix.py runs them; `nomad dev chaos -cell NAME` runs
one):

  system_fanout          system job fanned to every feasible node,
                         cross-checked against the SystemScheduler
                         placement contract, under dropped heartbeats
  spread_antiaffinity    spread/rack-anti-affinity multi-DC topology
                         with a forced governor reclaim mid-wave
  batch_backfill         batch backfill behind service traffic with a
                         worker killed mid-commit (plan committed,
                         ack withheld) — the no-double-commit cell
  drain_storm            node-drain storm + rolling upgrade: drain
                         wave, clean shutdown, WAL tail corrupted,
                         reboot — recovery must reconcile to intent
  client_failure_burst   mass client failure -> reschedule burst onto
                         the surviving fleet
  blocked_herd           blocked-eval thundering herd: overload, then
                         a capacity burst wakes every blocked eval
  swim_partition         (cluster cell, excluded from quick sets) a
                         3-server raft cluster with one follower
                         partitioned at the SWIM layer
  follower_fence         (cluster) the distributed scheduler plane
                         (ISSUE 16) under replication lag: the sole
                         scheduling follower's fence blocks, stale
                         plans demote at leader verify, heal recovers
  leader_failover_commit (cluster) leader killed the instant a remote
                         plan's group-commit entry is dispatched; the
                         new leader restores the broker and the
                         intent settles exactly once

Workload generators draw every mock id through the promoted
`mock.seeded_mock_ids` context (r17's fix for unreproducible "seeded"
scenarios), so a cell's content is a pure function of its seed.
"""

from __future__ import annotations

import logging
import shutil
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

from . import invariants
from .matrix import Cell, Scenario

LOG = logging.getLogger("nomad_tpu.chaos")


# -- workload helpers -------------------------------------------------

def _mk_nodes(cell: Cell, n: int, dcs: int = 1, racks: int = 8):
    """n seeded mock nodes registered THROUGH the server (raft apply +
    TTL timer), spread over datacenters and racks."""
    from ..mock import fixtures as mock
    from ..mock import seeded_mock_ids
    nodes = []
    with seeded_mock_ids(cell.seed):
        for i in range(n):
            node = mock.node()
            node.name = f"cnode-{i}"
            node.datacenter = f"dc{(i % dcs) + 1}"
            node.meta["rack"] = f"r{i % racks}"
            node.compute_class()
            nodes.append(node)
    return nodes


def _register_nodes(srv, nodes) -> None:
    for node in nodes:
        srv.register_node(node)


def _svc_job(cell: Cell, jid: str, count: int, priority: int = 50,
             cpu: int = 300, mem: int = 128, dcs: int = 1,
             job_type: str = "service"):
    """A seeded service/batch job with the port ask stripped (cells
    measure scheduling + recovery semantics, not port bookkeeping)."""
    from ..mock import fixtures as mock
    from ..mock import seeded_mock_ids
    with seeded_mock_ids(cell.seed):
        job = mock.job() if job_type == "service" else mock.batch_job()
    job.id = jid
    job.name = jid
    job.type = job_type
    job.priority = priority
    job.datacenters = [f"dc{d + 1}" for d in range(dcs)]
    tg = job.task_groups[0]
    tg.count = count
    tg.networks = []
    for t in tg.tasks:
        t.resources.networks = []
        t.resources.cpu = cpu
        t.resources.memory_mb = mem
    job.canonicalize()
    return job


def _live(store, job) -> list:
    return [a for a in store.allocs_by_job(job.namespace, job.id)
            if not a.terminal_status()]


def _settle(cell: Cell, srv, job, timeout_s: float = 25.0) -> bool:
    """Register the job and wait until its full count is live with
    distinct names; the settle latency is the cell's workload metric
    (placements/s, p50/p99) and the flatness window sample."""
    count = sum(tg.count for tg in job.task_groups)
    t0 = time.perf_counter()
    srv.register_job(job)
    ok = cell.wait_for(
        lambda: len({a.name for a in _live(srv.store, job)}) >= count,
        timeout_s=timeout_s)
    cell.note_latency(time.perf_counter() - t0,
                      placements=count if ok else 0)
    return ok


def _intent(jobs) -> Dict[Tuple[str, str], int]:
    return {(j.namespace, j.id): sum(tg.count for tg in j.task_groups)
            for j in jobs}


class _Beater:
    """Fake client heartbeats for store-registered mock nodes: renews
    every node's TTL on a cadence, attaching an r17 host-stats payload
    (low cpu/mem use — these nodes execute nothing, which is exactly
    what the used-vs-allocated divergence invariant should see). Beats
    route through Server.heartbeat, so the chaos drop-heartbeat hook
    interposes them like real ones."""

    def __init__(self, srv, node_ids: List[str],
                 interval_s: float = 0.3):
        self.srv = srv
        self.node_ids = list(node_ids)
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="chaos-beater")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            for nid in self.node_ids:
                try:
                    self.srv.heartbeat(nid, stats={
                        "cpu_pct": 2.0, "mem_used_mb": 128.0,
                        "mem_total_mb": 8192.0, "disk_used_mb": 1.0,
                        "disk_total_mb": 102400.0})
                except Exception:
                    pass            # node gone / server stopping

    def shutdown(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)


class _SimClients:
    """The minimal client behavior chaos cells need without real
    agents: acknowledge desired-stop/evict allocs as client-complete
    (a drain can't finish while the server waits on a kill ack that
    no client will ever send)."""

    def __init__(self, srv, interval_s: float = 0.1):
        self.srv = srv
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="chaos-simclients")
        self._thread.start()

    def _run(self) -> None:
        from dataclasses import replace
        while not self._stop.wait(self.interval_s):
            try:
                acks = []
                for a in self.srv.store.allocs():
                    if a.server_terminal_status() and \
                            not a.client_terminal_status():
                        acks.append(replace(a, client_status="complete"))
                if acks:
                    self.srv.update_alloc_status_from_client(acks)
            except Exception:
                pass                # server stopping mid-scan

    def shutdown(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)


# -- cell 1: system-job fan-out under dropped heartbeats --------------

def _run_system_fanout(cell: Cell) -> None:
    from ..mock import fixtures as mock
    from ..mock import seeded_mock_ids
    n_dc1 = 18 if cell.quick else 72
    n_dc2 = 6 if cell.quick else 24
    srv = cell.server(heartbeat_ttl_s=1.2, stats_stale_after_s=2.0)
    nodes = _mk_nodes(cell, n_dc1 + n_dc2, dcs=1)
    for node in nodes[n_dc1:]:
        node.datacenter = "dc2"
        node.compute_class()
    _register_nodes(srv, nodes)
    beater = _Beater(srv, [n.id for n in nodes])
    cell.track(beater)

    with seeded_mock_ids(cell.seed):
        job = mock.system_job()
    job.id = "chaos-system"
    for t in job.task_groups[0].tasks:
        t.resources.networks = []
    job.task_groups[0].networks = []
    job.canonicalize()

    dc1_ids = [n.id for n in nodes[:n_dc1]]
    with cell.window():
        t0 = time.perf_counter()
        srv.register_job(job)
        ok = cell.wait_for(
            lambda: len(_live(srv.store, job)) >= n_dc1, timeout_s=25)
        cell.note_latency(time.perf_counter() - t0,
                          placements=n_dc1 if ok else 0)
    # the SystemScheduler contract: one alloc on every feasible node
    # (dc1, ready), none on dc2
    cell.check(invariants.system_fanout(srv.store, job, dc1_ids))

    # the fleet must be REPORTING before the fault: a node that never
    # landed a stats payload can't age into stale_heartbeats (fast
    # settles beat the first 0.3s heartbeat tick)
    cell.wait_for(lambda: srv.cluster_stats()["nodes_reporting"]
                  >= len(nodes), timeout_s=10)
    # fault: the network eats a victim set's heartbeats — TTL expiry
    # must mark them down, their stats payloads must age into
    # stale_heartbeats, and the system job's allocs there must die
    victims = dc1_ids[:4]
    cell.injector.drop_heartbeats(victims)
    with cell.window():
        cell.check(invariants.failure_visibility(
            srv, expected_down=len(victims),
            expected_stale=len(victims)))
        live_ids = [n for n in dc1_ids if n not in victims]
        t0 = time.perf_counter()
        ok = cell.wait_for(
            lambda: {a.node_id for a in _live(srv.store, job)}
            == set(live_ids), timeout_s=20)
        cell.note_latency(time.perf_counter() - t0)
    cell.check(invariants.system_fanout(srv.store, job, live_ids))
    cell.check(invariants.used_vs_allocated(srv,
                                            expect_divergence=True))
    cell.metrics["nodes"] = len(nodes)
    cell.metrics["nodes_failed"] = len(victims)


# -- cell 2: spread/anti-affinity topology under governor pressure ----

def _run_spread_antiaffinity(cell: Cell) -> None:
    from ..models import Affinity, Spread, SpreadTarget
    n_nodes = 32 if cell.quick else 96
    waves, jobs_per_wave, count = (4, 2, 8) if cell.quick else (5, 4, 16)
    srv = cell.server()
    nodes = _mk_nodes(cell, n_nodes, dcs=4, racks=8)
    _register_nodes(srv, nodes)

    jobs = []
    for w in range(waves):
        with cell.window():
            for j in range(jobs_per_wave):
                job = _svc_job(cell, f"chaos-spread-{w}-{j}", count,
                               cpu=200, mem=96, dcs=4)
                tg = job.task_groups[0]
                tg.spreads = [
                    Spread(attribute="${node.datacenter}", weight=50,
                           spread_target=[SpreadTarget("dc1", 40),
                                          SpreadTarget("dc2", 30)]),
                    Spread(attribute="${meta.rack}", weight=30)]
                # rack anti-affinity: repel one rack, so feasibility
                # and ranking both carry attribute pressure
                tg.affinities = [Affinity(ltarget="${meta.rack}",
                                          rtarget="r0", operand="=",
                                          weight=-50)]
                jobs.append(job)
                if not _settle(cell, srv, job):
                    cell.check(invariants.check(
                        "wave_settled", False, job=job.id, wave=w))
        if w == 1:
            # the governor-pressure fault: every registered reclaim
            # (engine caches, victim memos, columnar index folds,
            # table-delta folds) fires MID-WAVE; later waves must
            # still place correctly on the reclaimed structures
            fired = cell.injector.force_governor_reclaim(srv)
            cell.metrics["reclaims_forced"] = len(fired)
    forced = [e for e in srv.governor.events()
              if e.get("kind") == "reclaim" and e.get("forced")]
    cell.check(invariants.check(
        "governor_reclaim_recorded", len(forced) > 0,
        forced_reclaims=len(forced)))
    cell.check(invariants.alloc_intent(srv.store, _intent(jobs)))
    cell.check(invariants.per_node_saturation(srv.store, _intent(jobs)))
    # each job's 8 allocs must fan across the racks and DCs its
    # spread stanzas name (count==8 over 8 racks -> all distinct)
    cell.check(invariants.spread_coverage(
        srv.store, _intent(jobs), lambda n: n.meta.get("rack"),
        min_distinct=min(count, 8) - 1, attr="rack"))
    cell.check(invariants.spread_coverage(
        srv.store, _intent(jobs), lambda n: n.datacenter,
        min_distinct=4, attr="datacenter"))


# -- cell 3: batch backfill + worker killed mid-commit ----------------

def _run_batch_backfill(cell: Cell) -> None:
    srv = cell.server()
    nodes = _mk_nodes(cell, 16 if cell.quick else 48)
    _register_nodes(srv, nodes)

    service = [_svc_job(cell, f"chaos-svc-{i}", 8, priority=70,
                        cpu=600) for i in range(2)]
    with cell.window():
        for job in service:
            if not _settle(cell, srv, job):
                cell.check(invariants.check("service_settled", False,
                                            job=job.id))

    # arm AFTER the service wave settles: the next plan to commit is a
    # batch backfill plan, and its worker dies between commit and ack
    cell.injector.kill_worker_on_commit(nth=1)
    batch = [_svc_job(cell, f"chaos-batch-{i}", 8, priority=30,
                      cpu=300, job_type="batch") for i in range(3)]
    with cell.window():
        for job in batch:
            # the killed eval redelivers after the broker's nack
            # delay; settle must absorb it
            if not _settle(cell, srv, job, timeout_s=40):
                cell.check(invariants.check("backfill_settled", False,
                                            job=job.id))
    all_jobs = service + batch
    cell.check(invariants.no_plan_committed_twice(
        srv.store, _intent(all_jobs), cell.injector))
    cell.check(invariants.alloc_intent(srv.store, _intent(all_jobs)))
    cell.check(invariants.blocked_evals_drained(srv))
    cell.metrics["workers_killed"] = len(cell.injector.killed_evals)


# -- cell 4: drain storm + rolling upgrade over a corrupted WAL -------

def _run_drain_storm(cell: Cell) -> None:
    from ..models.node import DrainSpec, DrainStrategy
    from ..models.job import MigrateStrategy
    from . import faults as chaos_faults
    data_dir = tempfile.mkdtemp(prefix="chaos-wal-")
    try:
        srv = cell.server(data_dir=data_dir, snapshot_every=10**6)
        nodes = _mk_nodes(cell, 12 if cell.quick else 32)
        _register_nodes(srv, nodes)
        sim = _SimClients(srv)

        jobs = []
        for i in range(2):
            job = _svc_job(cell, f"chaos-drain-{i}", 8, cpu=300)
            job.task_groups[0].migrate = MigrateStrategy(max_parallel=4)
            job.canonicalize()
            jobs.append(job)
        with cell.window():
            for job in jobs:
                if not _settle(cell, srv, job):
                    cell.check(invariants.check(
                        "drain_wave_settled", False, job=job.id))

        # drain storm: a third of the fleet drains at once
        drained = [n.id for n in nodes[:4 if cell.quick else 10]]
        with cell.window():
            t0 = time.perf_counter()
            for nid in drained:
                srv.update_node_drain(nid, DrainStrategy(
                    drain_spec=DrainSpec(deadline_s=60.0)))
            ok = cell.wait_for(
                lambda: all(
                    srv.store.node_by_id(nid).drain_strategy is None
                    for nid in drained)
                and all(len({a.name for a in _live(srv.store, j)})
                        >= j.task_groups[0].count for j in jobs),
                timeout_s=40)
            cell.note_latency(time.perf_counter() - t0)
            cell.check(invariants.check("drain_storm_completed", ok))
        cell.check(invariants.drained_nodes_empty(srv.store, drained))

        # rolling upgrade: clean shutdown, then the disk corrupts the
        # WAL tail before the new binary boots — replay must stop at
        # the first bad frame and the scheduler re-derives the lost
        # tail from intent
        sim.shutdown()
        srv.shutdown()
        cell.release(srv)
        detail = chaos_faults.corrupt_wal_tail(
            data_dir, span=96, seed=cell.seed)
        cell.injector.record("wal_corruption", **detail)
        cell.metrics["wal_corrupted_bytes"] = detail["corrupted_bytes"]

        srv2 = cell.server(data_dir=data_dir, snapshot_every=10**6)
        cell.track(_SimClients(srv2))
        for k, v in srv2.cold_start_stats.items():
            cell.metrics[f"recovery_{k}"] = round(float(v), 4)
        with cell.window():
            t0 = time.perf_counter()
            # re-assert intent on the upgraded server (idempotent
            # re-register, the operator's post-upgrade step): the
            # reconciler places whatever the lost tail dropped
            for job in jobs:
                srv2.register_job(job)
            ok = cell.wait_for(
                lambda: all(len({a.name for a in _live(srv2.store, j)})
                            >= j.task_groups[0].count for j in jobs),
                timeout_s=40)
            cell.note_latency(time.perf_counter() - t0)
            cell.check(invariants.check("recovered_after_corruption",
                                        ok))
        cell.check(invariants.alloc_intent(srv2.store, _intent(jobs)))
        cell.check(invariants.drained_nodes_empty(srv2.store, drained))
    finally:
        # tear the tracked servers down BEFORE the data dir goes away
        # (a shutdown snapshot/cost-model write into a removed dir is
        # just noise); run_cell's teardown then finds an empty list
        cell.teardown()
        shutil.rmtree(data_dir, ignore_errors=True)


# -- cell 5: mass client failure -> reschedule burst ------------------

def _run_client_failure_burst(cell: Cell) -> None:
    srv = cell.server(heartbeat_ttl_s=1.2, stats_stale_after_s=2.5)
    nodes = _mk_nodes(cell, 16 if cell.quick else 48)
    _register_nodes(srv, nodes)
    beater = _Beater(srv, [n.id for n in nodes])
    cell.track(beater)

    jobs = [_svc_job(cell, f"chaos-burst-{i}", 8, cpu=300)
            for i in range(3)]
    with cell.window():
        for job in jobs:
            if not _settle(cell, srv, job):
                cell.check(invariants.check("burst_wave_settled",
                                            False, job=job.id))

    # the fleet must be reporting before the fault (same reason as
    # the system_fanout cell: no payload, no staleness to observe)
    cell.wait_for(lambda: srv.cluster_stats()["nodes_reporting"]
                  >= len(nodes), timeout_s=10)
    # mass failure: the most-loaded third of the fleet stops beating
    by_load = sorted(nodes, key=lambda n: -len([
        a for a in srv.store.allocs_by_node(n.id)
        if not a.terminal_status()]))
    victims = [n.id for n in by_load[:len(nodes) // 3]]
    cell.injector.drop_heartbeats(victims)
    with cell.window():
        cell.check(invariants.failure_visibility(
            srv, expected_down=len(victims),
            expected_stale=len(victims)))
        t0 = time.perf_counter()
        ok = cell.wait_for(
            lambda: all(
                len({a.name for a in _live(srv.store, j)})
                >= j.task_groups[0].count
                and not any(a.node_id in set(victims)
                            for a in _live(srv.store, j))
                for j in jobs),
            timeout_s=30)
        cell.note_latency(time.perf_counter() - t0)
        cell.check(invariants.check("reschedule_burst_settled", ok))
    cell.check(invariants.alloc_intent(srv.store, _intent(jobs)))
    cell.check(invariants.allocs_on_live_nodes(srv.store,
                                               _intent(jobs), victims))
    cell.check(invariants.used_vs_allocated(srv,
                                            expect_divergence=True))
    cell.metrics["nodes_failed"] = len(victims)


# -- cell 6: blocked-eval thundering herd -----------------------------

def _run_blocked_herd(cell: Cell) -> None:
    srv = cell.server()
    small = _mk_nodes(cell, 4 if cell.quick else 8)
    _register_nodes(srv, small)

    n_jobs = 12 if cell.quick else 32
    jobs = [_svc_job(cell, f"chaos-herd-{i}", 4, cpu=1200, mem=512)
            for i in range(n_jobs)]
    with cell.window():
        t0 = time.perf_counter()
        for job in jobs:
            srv.register_job(job)
        # overload: capacity holds ~a quarter of the demand, the rest
        # must park as blocked evals
        herd = cell.wait_for(
            lambda: (srv.blocked_evals.stats.total_blocked
                     + srv.blocked_evals.stats.total_escaped)
            >= n_jobs // 2, timeout_s=25)
        cell.note_latency(time.perf_counter() - t0)
        cell.metrics["herd_blocked_peak"] = (
            srv.blocked_evals.stats.total_blocked
            + srv.blocked_evals.stats.total_escaped)
        cell.check(invariants.check("herd_built", herd,
                                    blocked=cell.metrics[
                                        "herd_blocked_peak"]))

    # capacity burst: every blocked eval wakes at once and the herd
    # must drain to exactly-once placements
    burst = _mk_nodes(cell, 16 if cell.quick else 44)
    with cell.window():
        t0 = time.perf_counter()
        _register_nodes(srv, burst)
        total = sum(j.task_groups[0].count for j in jobs)
        ok = cell.wait_for(
            lambda: sum(len({a.name for a in _live(srv.store, j)})
                        for j in jobs) >= total, timeout_s=40)
        cell.note_latency(time.perf_counter() - t0,
                          placements=total if ok else 0)
        cell.check(invariants.check("herd_drained_to_placements", ok))
    cell.wait_for(lambda: srv.eval_broker.stats.as_dict()["unacked"]
                  == 0, timeout_s=10)
    cell.check(invariants.alloc_intent(srv.store, _intent(jobs)))
    cell.check(invariants.blocked_evals_drained(srv))


# -- cell 7 (cluster): SWIM-layer partition ---------------------------

def _run_swim_partition(cell: Cell) -> None:
    from ..mock import fixtures as mock
    from ..rpc import RpcServer
    servers, rpcs = [], []
    for _ in range(3):
        srv = cell.server(start=False, num_schedulers=0,
                          dead_server_cleanup_s=0.0)
        rpc = RpcServer(srv, port=0)
        servers.append(srv)
        rpcs.append(rpc)
        cell.track(rpc)
    addrs = [r.addr for r in rpcs]
    for srv, rpc in zip(servers, rpcs):
        srv.attach_raft(rpc, addrs)
        rpc.start()
        srv.start()

    def leader():
        live = [s for s in servers if s.raft.is_leader()]
        return live[0] if len(live) == 1 else None

    ok = cell.wait_for(lambda: leader() is not None
                       and len(leader().store.server_members() or [])
                       == 3, timeout_s=30)
    cell.check(invariants.check("cluster_formed", ok))
    lead = leader()
    victim_addr = next(a for a in addrs if a != lead.raft.self_addr)

    def quorum_write() -> bool:
        """One flatness sample: a write commits and is visible on a
        majority of the non-victim members — the SAME operation in
        every window, so p99 drift across the partition is a real
        claim (writes must not degrade when a follower partitions)."""
        lead_now = leader()
        if lead_now is None:
            return False
        node = mock.node()
        t0 = time.perf_counter()
        try:
            lead_now.register_node(node)
            ok = cell.wait_for(
                lambda: sum(1 for s in servers
                            if s.raft.self_addr != victim_addr
                            and s.store.node_by_id(node.id)
                            is not None) >= 2, timeout_s=20)
        except Exception:
            ok = False
        cell.note_latency(time.perf_counter() - t0,
                          placements=1 if ok else 0)
        return ok

    with cell.window():                     # healthy baseline
        cell.check(invariants.check("quorum_write_healthy",
                                    quorum_write()))

    # the partition: SWIM probes (direct, indirect, and the leader's
    # verification) to the victim fail; the victim's process stays up
    cell.injector.partition({victim_addr})
    t0 = time.perf_counter()
    with cell.window():                     # partitioned, pre-removal:
        wrote_during = quorum_write()       # 2 of 3 is still a quorum
    removed = cell.wait_for(
        lambda: victim_addr not in (leader().store.server_members()
                                    if leader() else [victim_addr]),
        timeout_s=45)
    cell.check(invariants.check(
        "partitioned_member_removed", removed,
        detect_s=round(time.perf_counter() - t0, 1)))
    with cell.window():                     # shrunken cluster
        wrote_after = quorum_write()
    cell.check(invariants.check("quorum_writes_survive",
                                wrote_during and wrote_after))

    # heal: the victim answers probes again (its process never died)
    cell.injector.heal_partition()
    lead_final = leader()
    alive = lead_final is not None and \
        lead_final.swim.probe_for_peer(victim_addr)
    cell.check(invariants.check("victim_process_survived_partition",
                                alive))


# -- cluster-cell helpers (ISSUE 16) ----------------------------------

def _mk_ring(cell: Cell, n: int = 3, **cfg):
    """An n-server raft ring with the distributed scheduler plane on:
    no local workers anywhere (num_schedulers=0), so every placement
    must flow follower-dequeue -> local schedule -> Plan.Submit ->
    leader group-commit. Returns (servers, rpcs, addrs)."""
    from ..rpc import RpcServer
    servers, rpcs = [], []
    for _ in range(n):
        srv = cell.server(start=False, num_schedulers=0,
                          heartbeat_ttl_s=300.0,
                          dead_server_cleanup_s=0.0, **cfg)
        rpc = RpcServer(srv, port=0)
        servers.append(srv)
        rpcs.append(rpc)
        cell.track(rpc)
    addrs = [r.addr for r in rpcs]
    for srv, rpc in zip(servers, rpcs):
        srv.attach_raft(rpc, addrs)
        rpc.start()
        srv.start()
    return servers, rpcs, addrs


def _ring_leader(servers):
    live = [s for s in servers
            if not getattr(s, "_shutdown", False)
            and s.raft.is_leader()]
    return live[0] if len(live) == 1 else None


def _ring_formed(cell: Cell, servers) -> bool:
    return cell.wait_for(
        lambda: _ring_leader(servers) is not None
        and len(_ring_leader(servers).store.server_members() or [])
        == len(servers), timeout_s=30)


def _applied_index(srv) -> int:
    return srv.raft._handle_status({})["applied_index"]


def _extra_nodes(cell: Cell, n: int, salt: int, dc: str):
    """A LATER batch of seeded nodes with ids disjoint from every
    other batch: _mk_nodes replays the cell seed's id stream from the
    start, so calling it twice re-issues the same node ids — which
    re-registers (mutates) existing nodes instead of adding capacity."""
    from ..mock import fixtures as mock
    from ..mock import seeded_mock_ids
    out = []
    with seeded_mock_ids(cell.seed ^ salt):
        for i in range(n):
            node = mock.node()
            node.name = f"cnode-{dc}-{salt:x}-{i}"
            node.datacenter = dc
            node.compute_class()
            out.append(node)
    return out


# -- cell 8 (cluster): follower scheduling over a lagging fence -------

def _run_follower_fence(cell: Cell) -> None:
    """The snapshot-fence contract under replication lag. One follower
    (the victim) is the ONLY scheduler in the ring; its local MVCC
    store is the snapshot every plan is built on. Three phases:
    healthy baseline; replication lagged so a new eval's fence blocks
    (and unblocks on heal with a passing verify); and two evals
    admitted BEFORE the lag planned against the frozen snapshot, whose
    conflicting placements the leader's group-commit verify must
    demote — never commit — with full recovery after the heal."""
    servers, rpcs, addrs = _mk_ring(
        cell, follower_fence_timeout_s=8.0, follower_max_remote=2)
    cell.check(invariants.check("cluster_formed",
                                _ring_formed(cell, servers)))
    lead = _ring_leader(servers)
    followers = [s for s in servers if s is not lead]
    victim, other = followers[0], followers[1]
    victim_addr = victim.raft.self_addr
    # the victim must be the sole scheduler: park the other follower's
    # remote workers and let any in-flight remote dequeue poll drain
    other.follower_sched.set_pause(True)
    time.sleep(3.0)

    nodes = _mk_nodes(cell, 8)
    _register_nodes(lead, nodes)

    # phase 1: healthy baseline through the remote plane
    job_a = _svc_job(cell, "chaos-fence-a", 8, cpu=300)
    with cell.window():
        if not _settle(cell, lead, job_a):
            cell.check(invariants.check("fence_baseline_settled",
                                        False, job=job_a.id))
    cell.check(invariants.check(
        "placements_flowed_remote",
        lead.eval_leases.stats["remote_plans"] >= 1,
        **lead.eval_leases.snapshot_stats()))

    # phase 2: lag the victim's replication; a NEW eval's
    # modify_index now sits past the victim's applied index, so its
    # fence must BLOCK (lease held, nothing placed), then pass verify
    # once the heal lets the store catch up
    cell.injector.lag_replication({victim_addr})
    job_d = _svc_job(cell, "chaos-fence-d", 4, cpu=300)
    lead.register_job(job_d)
    leased = cell.wait_for(
        lambda: lead.eval_leases.outstanding() >= 1, timeout_s=10)
    blocked = len(_live(lead.store, job_d)) == 0
    cell.check(invariants.check("fence_blocked_while_lagged",
                                leased and blocked, leased=leased,
                                placed_while_lagged=not blocked))
    time.sleep(0.8)          # hold the fence long enough to measure
    cell.injector.heal_replication()
    with cell.window():
        t0 = time.perf_counter()
        ok = cell.wait_for(
            lambda: len({a.name for a in _live(lead.store, job_d)})
            >= 4, timeout_s=20)
        cell.note_latency(time.perf_counter() - t0,
                          placements=4 if ok else 0)
    cell.check(invariants.check("fence_released_on_heal", ok))
    cell.check(invariants.check(
        "fence_wait_observed",
        victim.follower_sched.fence_wait_p99_ms() >= 50.0,
        fence_wait_p99_ms=victim.follower_sched.fence_wait_p99_ms()))

    # phase 3: stale-plan demotion. Two evals are admitted while the
    # victim's workers are parked, the victim catches up PAST both,
    # then replication lags — both fences pass against the frozen
    # snapshot, both plans are built blind to each other on an
    # exactly-8-slot capacity domain (2 nodes x 4 asks), and the
    # leader's verify must demote the conflicting placements
    dc2 = _extra_nodes(cell, 2, 0x9E37, "dc2")
    _register_nodes(lead, dc2)
    victim.follower_sched.set_pause(True)
    time.sleep(3.0)
    job_c = _svc_job(cell, "chaos-fence-c", 4, cpu=900, dcs=1)
    job_b = _svc_job(cell, "chaos-fence-b", 8, cpu=900, dcs=1)
    for j in (job_c, job_b):
        j.datacenters = ["dc2"]
        j.canonicalize()
        lead.register_job(j)
    caught_up = cell.wait_for(
        lambda: _applied_index(victim) >= _applied_index(lead),
        timeout_s=15)
    cell.check(invariants.check("victim_caught_up_before_lag",
                                caught_up))
    cell.injector.lag_replication({victim_addr})
    victim.follower_sched.set_pause(False)
    demoted = cell.wait_for(
        lambda: lead.eval_leases.stats["remote_demotions"] >= 1,
        timeout_s=20, interval_s=0.02)
    cell.injector.heal_replication()
    cell.check(invariants.check(
        "stale_plan_demoted_not_committed", demoted,
        remote_demotions=lead.eval_leases.stats["remote_demotions"]))
    # post-heal recovery: extra capacity wakes whatever the demotion
    # reblocked; every slot must settle exactly once
    more = _extra_nodes(cell, 2, 0x51ED, "dc2")
    _register_nodes(lead, more)
    jobs = [job_a, job_d, job_c, job_b]
    with cell.window():
        t0 = time.perf_counter()
        ok = cell.wait_for(
            lambda: all(
                len({a.name for a in _live(lead.store, j)})
                >= sum(tg.count for tg in j.task_groups)
                for j in jobs), timeout_s=40)
        cell.note_latency(time.perf_counter() - t0,
                          placements=12 if ok else 0)
    cell.check(invariants.check("recovered_after_heal", ok))
    cell.check(invariants.alloc_intent(lead.store, _intent(jobs)))
    # the last remote ack races the settle; drain before the check
    cell.wait_for(lambda: lead.eval_broker.stats.as_dict()["unacked"]
                  == 0, timeout_s=10)
    cell.check(invariants.blocked_evals_drained(lead))
    cell.metrics["remote_demotions"] = \
        lead.eval_leases.stats["remote_demotions"]
    cell.metrics["fence_wait_p99_ms"] = \
        victim.follower_sched.fence_wait_p99_ms()


# -- cell 9 (cluster): leader killed mid-group-commit -----------------

def _run_leader_failover_commit(cell: Cell) -> None:
    """Leadership transfer at the worst instant: the leader dies right
    after dispatching a remote plan's group-commit raft entry —
    before the quorum ack, before the eval ack, with the follower's
    Plan.Submit RPC still in flight. Both races must converge: if the
    entry reached a majority the new leader carries the placements and
    the restored eval's replan is a no-op; if it was lost, the replan
    places from scratch. Either way the intent holds with no lost or
    duplicated alloc, and no plan commits twice."""
    servers, rpcs, addrs = _mk_ring(cell, follower_max_remote=2)
    cell.check(invariants.check("cluster_formed",
                                _ring_formed(cell, servers)))
    lead = _ring_leader(servers)
    nodes = _mk_nodes(cell, 12)
    _register_nodes(lead, nodes)

    job_a = _svc_job(cell, "chaos-failover-a", 8, cpu=300)
    with cell.window():
        if not _settle(cell, lead, job_a):
            cell.check(invariants.check("failover_baseline_settled",
                                        False, job=job_a.id))

    # arm the tripwire, then drive one more remote plan through the
    # applier; the hook fires on the applier thread the instant the
    # group's raft entry is dispatched, and THIS thread does the kill
    cell.injector.trip_on_group_commit(nth=1)
    job_b = _svc_job(cell, "chaos-failover-b", 8, cpu=300)
    t0 = time.perf_counter()
    lead.register_job(job_b)
    tripped = cell.injector.group_commit_tripped.wait(timeout=25)
    cell.check(invariants.check(
        "group_commit_tripped", tripped,
        tripped_index=cell.injector.tripped_group_index))
    old = lead
    old_rpc = rpcs[servers.index(old)]
    old_rpc.shutdown()
    old.shutdown()
    cell.release(old_rpc)
    cell.injector.record("leader_killed", addr=old.raft.self_addr,
                         at_group_index=cell.injector.
                         tripped_group_index)

    survivors = [s for s in servers if s is not old]
    elected = cell.wait_for(
        lambda: _ring_leader(survivors) is not None, timeout_s=30)
    cell.check(invariants.check("new_leader_elected", elected))
    new_lead = _ring_leader(survivors)
    with cell.window():
        ok = cell.wait_for(
            lambda: len({a.name
                         for a in _live(new_lead.store, job_b)})
            >= 8, timeout_s=60)
        cell.note_latency(time.perf_counter() - t0,
                          placements=8 if ok else 0)
    cell.check(invariants.check("workload_settled_after_failover",
                                ok))
    cell.check(invariants.alloc_intent(new_lead.store,
                                       _intent([job_a, job_b])))
    # did the in-flight entry survive the kill? Both outcomes are
    # legal; record which race this run exercised
    cell.metrics["tripped_group_index"] = \
        cell.injector.tripped_group_index
    cell.metrics["inflight_entry_survived"] = int(
        _applied_index(new_lead) >= cell.injector.tripped_group_index
        > 0)


SCENARIOS: Dict[str, Scenario] = {s.name: s for s in [
    Scenario(
        name="system_fanout",
        title="System-job fan-out under dropped heartbeats",
        description="system job on every feasible node, cross-checked "
                    "against the SystemScheduler contract; a victim "
                    "set's heartbeats are dropped in transit",
        run=_run_system_fanout),
    Scenario(
        name="spread_antiaffinity",
        title="Spread/rack-anti-affinity multi-DC topology",
        description="4-DC, 8-rack fleet; spread + anti-affinity "
                    "waves with a forced governor reclaim mid-wave; "
                    "per-node p99 hot-spot bound",
        run=_run_spread_antiaffinity),
    Scenario(
        name="batch_backfill",
        title="Batch backfill behind service traffic, worker killed "
              "mid-commit",
        description="service wave, then batch backfill; one worker "
                    "dies after its plan committed but before the "
                    "eval ack — no plan may commit twice",
        run=_run_batch_backfill),
    Scenario(
        name="drain_storm",
        title="Node-drain storm + rolling upgrade over a corrupted "
              "WAL tail",
        description="a third of the fleet drains, the server "
                    "restarts over a corrupted WAL tail, recovery "
                    "reconciles to intent",
        run=_run_drain_storm),
    Scenario(
        name="client_failure_burst",
        title="Mass client failure -> reschedule burst",
        description="the most-loaded third of the fleet stops "
                    "heartbeating at once; every alloc must land "
                    "exactly once on the survivors",
        run=_run_client_failure_burst),
    Scenario(
        name="blocked_herd",
        title="Blocked-eval thundering herd",
        description="4x overload parks a herd of blocked evals; a "
                    "capacity burst wakes them all at once",
        run=_run_blocked_herd),
    Scenario(
        name="swim_partition",
        title="SWIM-layer partition of a raft follower",
        description="3-server cluster; probes to a victim fail at "
                    "the SWIM layer while its process stays up — "
                    "detection, removal, quorum writes, heal",
        run=_run_swim_partition, quick=False, cluster=True),
    Scenario(
        name="follower_fence",
        title="Follower scheduling over a lagging snapshot fence",
        description="3-server ring, one follower is the sole "
                    "scheduler; its replication lags — new evals "
                    "fence-block until heal, stale plans are demoted "
                    "by leader verify, never committed",
        run=_run_follower_fence, quick=False, cluster=True),
    Scenario(
        name="leader_failover_commit",
        title="Leader killed mid-group-commit",
        description="the leader dies the instant a remote plan's "
                    "group raft entry is dispatched; the new leader "
                    "restores the broker from the store and the "
                    "intent settles with no lost or duplicated alloc",
        run=_run_leader_failover_commit, quick=False, cluster=True),
]}
