"""The scenario-matrix core (ISSUE 15): Scenario cells, the Cell
runtime each scenario drives, and the runner that executes cells
against a real in-process Server (+simulated or real clients) and
folds one artifact section per cell.

A cell's artifact section carries, per the FoundationDB/Jepsen shape
the ROADMAP names: the seeded workload's throughput (placements/s,
p50/p99 of the workload's settle latencies), EVERY invariant verdict
with its evidence, a flatness verdict over the cell's windows (the
SAME `bench/soak.flatness_verdict` math the soak and the live
/v1/operator/flatness route use), the exact fault schedule the
injector delivered, and the r18 race-sanitizer finding count when the
cell ran under NOMAD_TPU_RACE=1.

Entry points: `run_matrix` (the `nomad dev chaos` CLI and
`bench_scenario_matrix` in bench/ladder.py), `run_cell` (tests drive
single cells), `write_artifact`/`latest_artifact` (CHAOS_rNN.json;
`nomad operator debug` bundles the latest one as chaos.json).
"""

from __future__ import annotations

import json
import logging
import os
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

import numpy as np

from . import faults, invariants

LOG = logging.getLogger("nomad_tpu.chaos")

ARTIFACT_PREFIX = "CHAOS_r"


@dataclass
class Scenario:
    """One matrix cell: a seeded workload generator + fault schedule +
    invariant checks + flatness verdict, all inside `run(cell)`."""
    name: str
    title: str
    description: str
    run: Callable[["Cell"], None]
    # safe for tier-1 / quick bench (seconds, single process)
    quick: bool = True
    # needs a multi-server raft cluster (excluded from quick sets)
    cluster: bool = False
    tags: tuple = ()


class Cell:
    """The runtime a scenario drives: server lifecycle, the seeded
    injector, latency windows for the flatness verdict, and the
    invariant ledger."""

    def __init__(self, scenario: Scenario, seed: int, quick: bool):
        self.scenario = scenario
        self.name = scenario.name
        self.seed = seed
        self.quick = quick
        self.injector = faults.FaultInjector(seed=seed)
        self.checks: List[dict] = []
        self.metrics: Dict[str, float] = {}
        self._servers: List = []
        self._lat: List[float] = []          # all settle latencies (s)
        self._windows: List[dict] = []
        self._win_lat: Optional[List[float]] = None
        self._t0 = time.perf_counter()
        self.placements = 0

    # -- environment ---------------------------------------------------
    def server(self, start: bool = True, **cfg_kw):
        """Build + start a tracked Server. Chaos defaults: telemetry
        collector built but not free-running (cells call
        cluster_stats/sample_once at their own clock), governor on at
        a tight interval so watermark/backpressure machinery is live
        inside the cell. `start=False` for cluster cells that must
        attach raft before leadership."""
        from ..server import Server, ServerConfig
        cfg_kw.setdefault("num_schedulers", 2)
        cfg_kw.setdefault("heartbeat_ttl_s", 30.0)
        cfg_kw.setdefault("telemetry_sample_interval_s", 3600.0)
        cfg_kw.setdefault("governor_interval_s", 0.2)
        srv = Server(ServerConfig(**cfg_kw))
        if start:
            srv.start()
        self._servers.append(srv)
        return srv

    def track(self, obj) -> None:
        """Track any object with .shutdown() for teardown (clients,
        rpc servers)."""
        self._servers.append(obj)

    def teardown(self) -> None:
        for obj in reversed(self._servers):
            try:
                obj.shutdown()
            except Exception:       # pragma: no cover — best effort
                LOG.exception("chaos cell %s: teardown failed",
                              self.name)
        self._servers.clear()

    def release(self, obj) -> None:
        """Stop tracking (the scenario shut it down itself — e.g. the
        rolling-restart cell's first server generation)."""
        if obj in self._servers:
            self._servers.remove(obj)

    # -- invariants ----------------------------------------------------
    def check(self, result: dict) -> dict:
        self.checks.append(result)
        return result

    # -- workload instrumentation --------------------------------------
    def note_latency(self, seconds: float, placements: int = 0) -> None:
        self._lat.append(seconds)
        self.placements += placements
        if self._win_lat is not None:
            self._win_lat.append(seconds)

    @contextmanager
    def window(self):
        """One flatness window: settle latencies noted inside fold to
        the window's p99, RSS sampled at close. Scenarios run their
        workload in waves, one wave per window."""
        from ..governor.governor import rss_mb
        self._win_lat = []
        w_t0 = time.perf_counter()
        try:
            yield
        finally:
            lats = self._win_lat or [0.0]
            self._win_lat = None
            self._windows.append({
                "t_min": (time.perf_counter() - self._t0) / 60.0,
                "dur_s": round(time.perf_counter() - w_t0, 3),
                "p99_ms": float(np.percentile(
                    np.asarray(lats), 99) * 1e3),
                "rss_mb": rss_mb(),
                "samples": len(lats),
            })

    def wait_for(self, pred, timeout_s: float = 20.0,
                 interval_s: float = 0.05) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if pred():
                return True
            time.sleep(interval_s)
        return False

    # -- verdict assembly ----------------------------------------------
    def flatness(self) -> dict:
        """The soak's verdict math over this cell's windows. Quick
        cells run seconds-long windows, where an RSS least-squares
        slope extrapolated to MB/HOUR is dominated by allocator noise
        (the r15 live-verdict note measured -10161 MB/h on a healthy
        agent) — so quick mode widens the bounds and records that it
        did; the full matrix uses the soak's production bounds."""
        from ..bench.soak import flatness_verdict
        if self.quick:
            # bound TOTAL growth, not the hourly extrapolation: allow
            # <=192 MB across the whole quick cell (JIT compiles +
            # bounded caches filling to plateau), expressed as the
            # equivalent slope over the cell's actual span so the
            # verdict's units match the soak's
            span_h = max((self._windows[-1]["t_min"]
                          - self._windows[0]["t_min"]) / 60.0, 1e-4)
            verdict = flatness_verdict(self._windows,
                                       max_p99_ratio=3.0,
                                       max_rss_slope=192.0 / span_h)
            verdict["quick_windows"] = True
            return verdict
        return flatness_verdict(self._windows)

    def result(self, error: Optional[str] = None) -> dict:
        elapsed = time.perf_counter() - self._t0
        lat = np.asarray(self._lat) if self._lat else np.zeros(1)
        inv_failed = [c["name"] for c in self.checks if not c["pass"]]
        flat = self.flatness() if self._windows else {
            "pass": None, "reason": "no windows"}
        out = {
            "name": self.name,
            "title": self.scenario.title,
            "seed": self.seed,
            "quick": self.quick,
            "elapsed_s": round(elapsed, 2),
            "placements": self.placements,
            "placements_per_sec": round(self.placements / elapsed, 1)
            if elapsed > 0 else 0.0,
            "settle_p50_ms": round(float(np.percentile(lat, 50)) * 1e3,
                                   2),
            "settle_p99_ms": round(float(np.percentile(lat, 99)) * 1e3,
                                   2),
            "invariants": self.checks,
            "invariants_failed": inv_failed,
            "flatness": flat,
            "faults": self.injector.events,
            "windows": self._windows,
            **self.metrics,
        }
        if error:
            out["error"] = error
        # the cell verdict: every invariant held and the run completed.
        # Flatness is reported but gates only the FULL matrix (quick
        # windows are too short to indict a leak)
        out["pass"] = bool(not error and not inv_failed
                           and (self.quick or flat.get("pass")
                                is not False))
        return out


def run_cell(scenario: Scenario, seed: Optional[int] = None,
             quick: bool = True) -> dict:
    """Execute one cell: install the seeded injector, run the scenario
    against real servers, always record the race-finding delta, tear
    everything down, and return the artifact section."""
    if seed is None:
        import zlib
        base = faults.DEFAULTS["seed"]
        # derive a stable per-cell seed so every cell differs but the
        # matrix is reproducible from one number (crc32, NOT hash():
        # str hashing is salted per process)
        seed = (base or 0xC0FFEE) ^ \
            (zlib.crc32(scenario.name.encode()) & 0xFFFF)
    cell = Cell(scenario, seed, quick)
    race_base = invariants.race_baseline()
    error = None
    cell.injector.install()
    try:
        scenario.run(cell)
    except Exception as e:          # a crashed cell is a FAILED cell,
        LOG.exception("chaos cell %s crashed", scenario.name)
        error = f"{type(e).__name__}: {e}"   # not a crashed matrix
    finally:
        cell.injector.uninstall()
        cell.teardown()
    cell.check(invariants.race_clean(race_base))
    return cell.result(error)


def run_matrix(names: Optional[List[str]] = None, quick: bool = True,
               seed: Optional[int] = None) -> dict:
    """Run the named cells (default: every quick cell when quick, the
    whole single-process matrix otherwise) and fold the artifact."""
    from .scenarios import SCENARIOS
    selected: List[Scenario] = []
    if names:
        unknown = [n for n in names if n not in SCENARIOS]
        if unknown:
            raise KeyError(
                f"unknown chaos cells {unknown}; have "
                f"{sorted(SCENARIOS)}")
        selected = [SCENARIOS[n] for n in names]
    else:
        selected = [s for s in SCENARIOS.values()
                    if (s.quick or not quick) and not s.cluster]
    from ..analysis import race
    cells = []
    for sc in selected:
        LOG.info("chaos: running cell %s", sc.name)
        cells.append(run_cell(sc, seed=seed, quick=quick))
    passed = [c for c in cells if c["pass"]]
    return {
        "schema": "nomad-tpu/chaos/1",
        "quick": quick,
        "race": "on" if race.enabled() else "off",
        "cells": cells,
        "summary": {
            "cells": len(cells),
            "passed": len(passed),
            "failed": [c["name"] for c in cells if not c["pass"]],
            "invariants_checked": sum(len(c["invariants"])
                                      for c in cells),
            "invariants_failed": sum(len(c["invariants_failed"])
                                     for c in cells),
            "race_findings": sum(
                c0.get("findings", 0) for c in cells
                for c0 in c["invariants"]
                if c0["name"] == "race_findings_zero"),
        },
    }


# -- artifact files ---------------------------------------------------

def next_artifact_path(directory: str = ".") -> str:
    """First free CHAOS_rNN.json in `directory` (r01, r02, ...)."""
    n = 1
    while True:
        path = os.path.join(directory, f"{ARTIFACT_PREFIX}{n:02d}.json")
        if not os.path.exists(path):
            return path
        n += 1


def latest_artifact(directory: str = ".") -> Optional[str]:
    """Newest CHAOS_rNN.json in `directory`, or None. `nomad operator
    debug` bundles it as chaos.json."""
    def run_no(name: str) -> int:
        try:
            return int(name[len(ARTIFACT_PREFIX):-len(".json")])
        except ValueError:
            return -1
    try:
        names = sorted((f for f in os.listdir(directory)
                        if f.startswith(ARTIFACT_PREFIX)
                        and f.endswith(".json")),
                       key=run_no)   # numeric: r100 sorts after r99
    except OSError:
        return None
    return os.path.join(directory, names[-1]) if names else None


def write_artifact(result: dict, path: Optional[str] = None,
                   directory: str = ".") -> str:
    path = path or next_artifact_path(directory)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=1, default=str, sort_keys=False)
        f.write("\n")
    os.replace(tmp, path)
    return path
