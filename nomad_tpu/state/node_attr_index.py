"""Write-through interned node-attribute column store (ISSUE 17).

The constraint/feasibility path was the last O(N)-Python wall: every
node-set rebuild re-resolved each constraint target with a per-node
Python loop (ops/targets.py TargetColumns.resolve) and threw the
columns away with the table. This module keeps ONE resident set of
dictionary-encoded attribute columns on the StateStore — unique value
-> i32 code, the r12 dedup-pool trick applied to node attrs/meta/
class/datacenter — advanced incrementally by node register/update/
deregister through the store's mutation path, exactly like
state/alloc_index.py: O(changes) per advance, never O(nodes).

Layout and lifecycle:

  - rows are swap-delete dense (the JobAllocColumns idiom), one row
    per store node; `ids_epoch` bumps ONLY when the node-id set
    changes (register/deregister), so a pure attribute update keeps
    every row number — and therefore every cached mask — valid;
  - columns are built lazily, one O(N) pass the FIRST time a
    constraint target is evaluated, then maintained per changed row.
    Synthetic targets (driver health, host-volume access mode) are
    just more columns, keyed by tuples so they can't collide with
    real `${...}` target strings;
  - intern tables are APPEND-ONLY: a value's code never changes, so
    per-(operand, rtarget) verdict LUTs in the compiler
    (scheduler/feasible_compiler.py) extend monotonically instead of
    recomputing;
  - every node write appends a (raft index, op, payload) delta under
    the store lock; the next read applies pending deltas up to its
    snapshot's node-table index. Updates within one ids_epoch land in
    `row_log` — the mask journal the compiler (and the device-mirror
    mask store) replays to re-evaluate ONE row per changed node
    instead of rebuilding bool[N];
  - a column whose intern table outgrows `INTERN_MAX_VALUES`
    (ServerConfig.feas_intern_max_values) is flagged `overflow` and
    its operands fall back to the scalar reference path.

Concurrency contract: unlike the per-job alloc index, this index is
GLOBAL — concurrent evals of different jobs read it simultaneously.
All sync, column builds, and compiler mask work therefore run under
`cache.lock`; writers take store lock -> cache lock (note_*), readers
take cache lock alone, and the first-build install takes store lock
-> cache lock like AllocIndexCache.get — one consistent order, no
inversion.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils.locks import make_lock
from ..ops.targets import (
    driver_ok, host_volume_value, node_target_value,
)

# columns whose intern table outgrows this fall back to the scalar
# reference path (feasible_compiler re-checks per eval); poked by
# feasible_compiler.configure from ServerConfig.feas_intern_max_values
INTERN_MAX_VALUES = 4096

# update events journaled per ids_epoch before the oldest half is
# dropped; masks older than the retained window rebuild dense
ROW_LOG_MAX = 4096

_MISSING = object()

_SERIAL = [0]


def _column_entry(node, key):
    """The interned value of one (node, column) cell, or _MISSING.
    String keys are constraint targets; tuple keys are the synthetic
    driver/device-inventory/host-volume columns."""
    if isinstance(key, tuple):
        kind, name = key
        if kind == "driver":
            return "1" if driver_ok(node, name) else _MISSING
        if kind == "dev":
            # device-inventory flag (ISSUE 20): present iff the node
            # reports ANY device group — deviceless rows are False for
            # every non-empty ask, so the compiler's flagged-row check
            # (feasible_compiler.device_rows_check) only walks these
            res = getattr(node, "node_resources", None)
            devs = getattr(res, "devices", None) if res else None
            return "1" if devs else _MISSING
        v = host_volume_value(node, name)
        return v if v is not None else _MISSING
    v, found = node_target_value(node, key)
    return v if found else _MISSING


class AttrColumn:
    """One interned code column: `values` is the append-only intern
    table, `codes[row]` its i32 code per index row (-1 == missing).
    `luts` holds the compiler's per-(operand, rtarget) verdict tables,
    cached here so they survive mask-cache reclaims and extend in
    place as values are interned."""

    __slots__ = ("values", "code_of", "codes", "overflow", "luts")

    def __init__(self, cap: int):
        self.values: List = []
        self.code_of: Dict = {}
        self.codes = np.full(cap, -1, dtype=np.int32)
        self.overflow = False
        self.luts: Dict[Tuple, np.ndarray] = {}

    def intern(self, v) -> int:
        try:
            c = self.code_of.get(v)
        except TypeError:           # unhashable attribute value
            self.overflow = True
            return -1
        if c is None:
            if len(self.values) >= INTERN_MAX_VALUES:
                self.overflow = True
                return -1
            c = len(self.values)
            self.values.append(v)
            self.code_of[v] = c
        return c

    def set_row(self, row: int, node, key) -> None:
        v = _column_entry(node, key)
        self.codes[row] = -1 if v is _MISSING else self.intern(v)


class NodeAttrIndex:
    """The resident column set. All mutation happens under the owning
    NodeAttrIndexCache's lock."""

    def __init__(self, nodes: List, version: int):
        self.nodes: List = list(nodes)
        self.ids: List[str] = [n.id for n in self.nodes]
        self.row_of: Dict[str, int] = {nid: i
                                       for i, nid in enumerate(self.ids)}
        self.n = len(self.nodes)
        self.cap = max(self.n, 8)
        self.version = version        # node-table raft index synced to
        self.ids_epoch = 0            # bumps on register/deregister only
        self.columns: Dict[object, AttrColumn] = {}
        # mask journal: (raft index, index row) per in-place update in
        # the CURRENT ids_epoch; events with index > row_log_floor are
        # all retained
        self.row_log: List[Tuple[int, int]] = []
        self.row_log_floor = version
        _SERIAL[0] += 1
        self.serial = _SERIAL[0]
        # compiled-mask cache, owned by scheduler/feasible_compiler
        # (living here so a store swap drops it naturally)
        self.mask_cache: Dict[Tuple, dict] = {}
        self.stats = {"column_builds": 0, "delta_syncs": 0,
                      "delta_rows": 0, "row_events": 0, "epoch_bumps": 0}
        self._perm: Optional[Tuple] = None

    # -- columns -------------------------------------------------------
    def column(self, key) -> AttrColumn:
        """The interned column for one target, built lazily (ONE O(N)
        pass, then incremental forever)."""
        col = self.columns.get(key)
        if col is None:
            col = AttrColumn(self.cap)
            for i, node in enumerate(self.nodes):
                col.set_row(i, node, key)
            self.columns[key] = col
            self.stats["column_builds"] += 1
        return col

    def intern_values(self) -> int:
        return sum(len(c.values) for c in self.columns.values())

    # -- row maintenance -----------------------------------------------
    def _grow(self) -> None:
        self.cap *= 2
        for col in self.columns.values():
            codes = np.full(self.cap, -1, dtype=np.int32)
            codes[:self.n] = col.codes[:self.n]
            col.codes = codes

    def _bump_epoch(self, index: int) -> None:
        self.ids_epoch += 1
        self.row_log.clear()
        self.row_log_floor = index
        self._perm = None
        self.stats["epoch_bumps"] += 1

    def apply_upsert(self, index: int, node) -> None:
        r = self.row_of.get(node.id)
        if r is None:
            if self.n == self.cap:
                self._grow()
            r = self.n
            self.n += 1
            self.ids.append(node.id)
            self.nodes.append(node)
            self.row_of[node.id] = r
            for key, col in self.columns.items():
                col.set_row(r, node, key)
            self._bump_epoch(index)
            return
        self.nodes[r] = node
        for key, col in self.columns.items():
            col.set_row(r, node, key)
        self.row_log.append((index, r))
        self.stats["row_events"] += 1
        if len(self.row_log) > ROW_LOG_MAX:
            drop = len(self.row_log) // 2
            self.row_log_floor = self.row_log[drop - 1][0]
            del self.row_log[:drop]

    def apply_delete(self, index: int, node_id: str) -> None:
        r = self.row_of.pop(node_id, None)
        if r is None:
            return
        last = self.n - 1
        if r != last:
            for col in self.columns.values():
                col.codes[r] = col.codes[last]
            self.ids[r] = self.ids[last]
            self.nodes[r] = self.nodes[last]
            self.row_of[self.ids[r]] = r
        self.ids.pop()
        self.nodes.pop()
        self.n = last
        self._bump_epoch(index)

    # -- mask journal --------------------------------------------------
    def rows_since(self, version: int) -> Optional[List[int]]:
        """Index rows updated since `version` within the current
        ids_epoch, or None when the journal no longer reaches back
        (caller rebuilds dense)."""
        if version < self.row_log_floor:
            return None
        return sorted({r for (i, r) in self.row_log if i > version})

    # -- table alignment -----------------------------------------------
    def perm_for(self, table_ids: List[str]):
        """(perm, inv) aligning this index with a store-served
        NodeTable: perm[table_row] = index_row, inv[index_row] =
        table_row. Tables are ALL store nodes sorted by id, so one perm
        per ids_epoch serves every table generation — a pure attribute
        update rebuilds the table but not the permutation. Returns
        (None, None) on any mismatch (caller falls back scalar)."""
        p = self._perm
        if p is not None and p[0] == self.ids_epoch \
                and len(p[1]) == len(table_ids):
            return p[1], p[2]
        if len(table_ids) != self.n:
            return None, None
        row_of = self.row_of
        try:
            perm = np.fromiter((row_of[i] for i in table_ids),
                               dtype=np.int64, count=self.n)
        except KeyError:
            return None, None
        inv = np.empty(self.n, dtype=np.int64)
        inv[perm] = np.arange(self.n, dtype=np.int64)
        self._perm = (self.ids_epoch, perm, inv)
        return perm, inv


class NodeAttrIndexCache:
    """One per StateStore (`store.attr_index`): write-through deltas
    from the node mutation path, lazy first build, and the lock every
    compiled-mask read runs under."""

    def __init__(self, enabled: bool = True, delta_max: int = 8192):
        self.enabled = enabled
        self.delta_max = delta_max
        self.lock = make_lock()
        self._idx: Optional[NodeAttrIndex] = None
        self._deltas: List[Tuple[int, str, object]] = []
        self.stats = {"builds": 0, "drops": 0, "folds": 0,
                      "stale_reads": 0}

    # -- write-through (called under the store lock) -------------------
    def note_upsert(self, index: int, node) -> None:
        if self._idx is None:
            # unlocked early-out (the AllocIndexCache idiom): install
            # happens under the store lock too, so a registration storm
            # before the first columnar read pays zero mutex round-trips
            return
        self._note(index, "up", node)

    def note_delete(self, index: int, node_id: str) -> None:
        if self._idx is None:
            return
        self._note(index, "del", node_id)

    def _note(self, index: int, op: str, payload) -> None:
        with self.lock:
            if self._idx is None:
                return
            if len(self._deltas) >= self.delta_max:
                # nobody is reading: stop hoarding, rebuild on next read
                self._idx = None
                self._deltas.clear()
                self.stats["drops"] += 1
                return
            self._deltas.append((index, op, payload))

    # -- build / sync --------------------------------------------------
    def build_install(self, snapshot) -> None:
        """First columnar read: build the (column-less) row index from
        the snapshot and install it iff the live store still sits at
        the snapshot's node index — checked under the store lock, the
        same close-the-race install AllocIndexCache.get does."""
        store = getattr(snapshot, "_store", None)
        if store is None or not self.enabled:
            return
        target = snapshot.index("nodes")
        idx = NodeAttrIndex(snapshot.nodes(), target)
        with store._lock:
            if store.index("nodes") != target:
                self.stats["stale_reads"] += 1
                return
            with self.lock:
                if self._idx is None:
                    self._idx = idx
                    self._deltas.clear()
                    self.stats["builds"] += 1

    def synced(self, snapshot) -> Optional[NodeAttrIndex]:
        """The index advanced to `snapshot`'s node index, or None when
        unavailable (disabled / not built / snapshot older than the
        synced arrays). CALLER HOLDS self.lock, and keeps holding it
        for every read of the returned index — the global-index analog
        of the alloc index's one-reader-per-job contract."""
        idx = self._idx
        if idx is None or not self.enabled:
            return None
        target = snapshot.index("nodes")
        if idx.version > target:
            self.stats["stale_reads"] += 1
            return None
        d = self._deltas
        i = 0
        while i < len(d) and d[i][0] <= target:
            i += 1
        if i:
            for index, op, payload in d[:i]:
                if op == "del":
                    idx.apply_delete(index, payload)
                else:
                    idx.apply_upsert(index, payload)
            del d[:i]
            idx.stats["delta_syncs"] += 1
            idx.stats["delta_rows"] += i
        idx.version = target
        return idx

    def needs_build(self) -> bool:
        return self.enabled and self._idx is None

    # -- accounting (governor gauges) ----------------------------------
    def gauge_stats(self) -> dict:
        with self.lock:
            idx = self._idx
            out = dict(self.stats)
            out["debt"] = len(self._deltas)
            if idx is None:
                out.update(intern_values=0, columns=0,
                           mask_cache_entries=0, rows=0)
            else:
                out.update(intern_values=idx.intern_values(),
                           columns=len(idx.columns),
                           mask_cache_entries=len(idx.mask_cache),
                           rows=idx.n, ids_epoch=idx.ids_epoch,
                           **{f"idx_{k}": v
                              for k, v in idx.stats.items()})
            return out

    def drop_masks(self) -> dict:
        """Governor reclaim: drop cached masks, KEEP the intern tables
        and code columns — the next eval rebuilds bool[N] from codes
        (one np.take per check), not the attribute walks."""
        with self.lock:
            idx = self._idx
            if idx is None:
                return {"masks_dropped": 0}
            dropped = len(idx.mask_cache)
            idx.mask_cache.clear()
            self.stats["folds"] += 1
        return {"masks_dropped": dropped}
