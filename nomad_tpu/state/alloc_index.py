"""Incremental per-job columnar alloc index — struct-of-arrays over one
job's allocations, advanced on every alloc upsert.

BENCH_r05's 13x kernel-vs-e2e gap sits in the per-eval host phase: the
reconciler walks every existing alloc of the job in Python (status
predicates, name parsing, job-version checks, deep spec diffs) on EVERY
eval, even when nothing changed. This module keeps those facts resident
as numpy columns so the reconciler's partition math (terminal filter,
tainted split, per-tg bucketing, same-version ignore) becomes mask ops
(scheduler/reconcile_columnar.py), the same way ops/tables.py made node
feasibility columnar.

Lifecycle mirrors the resident node table's delta scheme:

  - columns live on the StateStore (`store.alloc_index`), created
    lazily on the first columnar read of a job;
  - every alloc write appends a (raft index, op, payload) delta to the
    job's entry under the store lock; the next read applies pending
    deltas up to its snapshot's alloc-table index (O(changes), not
    O(allocs));
  - a snapshot OLDER than the synced arrays, a wholesale load
    (bulk_load/restore), or a delta log past `delta_max` falls back to
    a dense rebuild from the snapshot — counted in `stats["rebuilds"]`
    and surfaced as the governor's `reconcile.index_rebuilds` gauge;
  - the governor's `reconcile.index_debt` watermark
    (`governor_reconcile_index_debt_high`) folds the whole index back
    to dense rebuild via `fold()` when pending delta debt grows.

Concurrency contract: delta sync mutates an entry's arrays in place,
which is safe because the eval broker enforces one outstanding eval per
job — no two reconcilers read the same job's columns concurrently, and
writers only append deltas (applied under the cache lock).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils.locks import make_lock
from ..models import (
    ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_FAILED, ALLOC_CLIENT_LOST,
    ALLOC_CLIENT_PENDING, ALLOC_CLIENT_RUNNING,
    ALLOC_DESIRED_EVICT, ALLOC_DESIRED_RUN, ALLOC_DESIRED_STOP,
    Allocation,
)

# status codes: client_terminal <=> code >= 2, server_terminal <=> code > 0
CLIENT_CODES = {ALLOC_CLIENT_PENDING: 0, ALLOC_CLIENT_RUNNING: 1,
                ALLOC_CLIENT_COMPLETE: 2, ALLOC_CLIENT_FAILED: 3,
                ALLOC_CLIENT_LOST: 4}
CLIENT_FAILED_CODE = 3
DESIRED_CODES = {ALLOC_DESIRED_RUN: 0, ALLOC_DESIRED_STOP: 1,
                 ALLOC_DESIRED_EVICT: 2}

# -- per-alloc candidate facts (batched columnar preemption) -----------
#
# The preemption matrix gather (scheduler/preemption.py
# _evaluate_columnar) reads one (cpu, mem, disk, mbits) usage row and
# one migrate.max_parallel per candidate alloc. Both are pure functions
# of pinned snapshot objects, so they memoize by identity exactly like
# the node table's usage memo — the gather pays dict hits, not resource
# graph walks, per candidate.

_usage_vec = None

_MP_MEMO: Dict[Tuple[int, str], Tuple[object, int]] = {}
_MP_MEMO_MAX = 8192


def alloc_usage_vec(a) -> Tuple[float, float, float, float]:
    """(cpu_shares, memory_mb, disk_mb, mbits) of one alloc's
    comparable resources — the ops/tables identity-memoized extraction
    (fleets share flyweight resource rows, so this is ~always a dict
    hit), surfaced through the state layer for columnar consumers."""
    global _usage_vec
    if _usage_vec is None:     # lazy: state must not import ops at load
        from ..ops.tables import _alloc_usage
        _usage_vec = _alloc_usage
    return _usage_vec(a)


def alloc_max_parallel(a) -> int:
    """tg.migrate.max_parallel for one alloc (0 when absent), memoized
    per (job identity, task group) — the task-group spec walk is
    invariant for a pinned Job snapshot. Entries pin the Job and
    re-verify identity on hit (the _ENGINE_CACHE idiom)."""
    job = a.job
    if job is None:
        return 0
    key = (id(job), a.task_group)
    hit = _MP_MEMO.get(key)
    if hit is not None and hit[0] is job:
        return hit[1]
    tg = job.lookup_task_group(a.task_group)
    mp = 0
    if tg is not None and tg.migrate is not None:
        mp = tg.migrate.max_parallel
    if len(_MP_MEMO) >= _MP_MEMO_MAX:
        # FIFO eviction; tolerate concurrent-lane races like the
        # ops/tables memos do
        try:
            _MP_MEMO.pop(next(iter(_MP_MEMO)), None)
        except (StopIteration, RuntimeError):
            pass
    _MP_MEMO[key] = (job, mp)
    return mp


_INT_COLS = (
    ("client", np.int8), ("desired", np.int8), ("healthy", np.int8),
    ("tg_code", np.int32), ("name_idx", np.int32),
    ("node_code", np.int32), ("job_code", np.int32),
    ("dep_code", np.int32),
    ("job_version", np.int64), ("job_create", np.int64),
    ("job_mod", np.int64),
)
_BOOL_COLS = ("has_job", "migrate", "force_resched", "resched_flag",
              "has_next")


class JobAllocColumns:
    """Struct-of-arrays over one job's allocs. `allocs`/`ids` are
    positional and exactly row-aligned with every column; deletes
    swap-remove so rows stay dense."""

    __slots__ = tuple(n for n, _ in _INT_COLS) + _BOOL_COLS + (
        "n", "cap", "ids", "allocs", "row_of",
        "tg_names", "tg_of", "node_ids", "node_of",
        "job_objs", "job_of", "dep_ids", "dep_of")

    def __init__(self, cap: int = 16):
        self.n = 0
        self.cap = max(cap, 4)
        for name, dtype in _INT_COLS:
            setattr(self, name, np.zeros(self.cap, dtype=dtype))
        for name in _BOOL_COLS:
            setattr(self, name, np.zeros(self.cap, dtype=bool))
        self.ids: List[str] = []
        self.allocs: List[Allocation] = []
        self.row_of: Dict[str, int] = {}
        self.tg_names: List[str] = []
        self.tg_of: Dict[str, int] = {}
        self.node_ids: List[str] = []
        self.node_of: Dict[str, int] = {}
        self.job_objs: List = []            # pins alloc.job snapshots
        self.job_of: Dict[int, int] = {}    # id(job) -> code
        self.dep_ids: List[str] = []
        self.dep_of: Dict[str, int] = {}

    @classmethod
    def build(cls, allocs: List[Allocation]) -> "JobAllocColumns":
        c = cls(cap=max(len(allocs), 4))
        for a in allocs:
            c.upsert(a)
        return c

    # -- codes ---------------------------------------------------------
    def _code(self, value, values: list, of: dict) -> int:
        code = of.get(value)
        if code is None:
            code = len(values)
            values.append(value)
            of[value] = code
        return code

    # -- row maintenance ----------------------------------------------
    def _grow(self) -> None:
        self.cap *= 2
        for name, _ in _INT_COLS:
            col = getattr(self, name)
            setattr(self, name, np.resize(col, self.cap))
        for name in _BOOL_COLS:
            col = getattr(self, name)
            setattr(self, name, np.resize(col, self.cap))

    def _set_row(self, r: int, a: Allocation) -> None:
        self.client[r] = CLIENT_CODES.get(a.client_status, -1)
        self.desired[r] = DESIRED_CODES.get(a.desired_status, -1)
        self.tg_code[r] = self._code(a.task_group, self.tg_names,
                                     self.tg_of)
        self.name_idx[r] = a.index()
        self.node_code[r] = self._code(a.node_id, self.node_ids,
                                       self.node_of)
        job = a.job
        if job is None:
            self.has_job[r] = False
            self.job_code[r] = -1
            self.job_version[r] = -1
            self.job_create[r] = -1
            self.job_mod[r] = -1
        else:
            self.has_job[r] = True
            code = self.job_of.get(id(job))
            if code is None:
                code = len(self.job_objs)
                self.job_objs.append(job)
                self.job_of[id(job)] = code
            self.job_code[r] = code
            self.job_version[r] = job.version
            self.job_create[r] = job.create_index
            self.job_mod[r] = job.job_modify_index
        dt = a.desired_transition
        self.migrate[r] = bool(dt.migrate)
        self.force_resched[r] = bool(dt.force_reschedule)
        self.resched_flag[r] = bool(dt.reschedule)
        ds = a.deployment_status
        if ds is None or ds.healthy is None:
            self.healthy[r] = 0
        else:
            self.healthy[r] = 1 if ds.healthy else -1
        self.dep_code[r] = (self._code(a.deployment_id, self.dep_ids,
                                       self.dep_of)
                            if a.deployment_id else -1)
        self.has_next[r] = a.next_allocation != ""

    def upsert(self, a: Allocation) -> None:
        r = self.row_of.get(a.id)
        if r is None:
            if self.n == self.cap:
                self._grow()
            r = self.n
            self.n += 1
            self.ids.append(a.id)
            self.allocs.append(a)
            self.row_of[a.id] = r
        else:
            self.allocs[r] = a
        self._set_row(r, a)

    def delete(self, alloc_id: str) -> None:
        r = self.row_of.pop(alloc_id, None)
        if r is None:
            return
        last = self.n - 1
        if r != last:
            for name, _ in _INT_COLS:
                col = getattr(self, name)
                col[r] = col[last]
            for name in _BOOL_COLS:
                col = getattr(self, name)
                col[r] = col[last]
            self.ids[r] = self.ids[last]
            self.allocs[r] = self.allocs[last]
            self.row_of[self.ids[r]] = r
        self.ids.pop()
        self.allocs.pop()
        self.n = last


class _Entry:
    __slots__ = ("cols", "version", "deltas")

    def __init__(self, cols: JobAllocColumns, version: int):
        self.cols = cols
        self.version = version
        self.deltas: List[Tuple[int, str, object]] = []


# entries whose job-object pin list outgrows this rebuild dense: each
# pinned Job snapshot is a dead version the store already pruned
_JOB_PIN_MAX = 128


class AllocIndexCache:
    """Per-(namespace, job) columnar indexes with write-through deltas.
    One per StateStore (`store.alloc_index`); every alloc write path
    notes its change here, next to the changelog."""

    def __init__(self, max_jobs: int = 512, delta_max: int = 4096,
                 enabled: bool = True):
        self.enabled = enabled
        self.max_jobs = max_jobs
        self.delta_max = delta_max
        self._entries: Dict[Tuple[str, str], _Entry] = {}
        self._lock = make_lock()
        self.stats = {"rebuilds": 0, "delta_syncs": 0, "delta_rows": 0,
                      "entry_drops": 0, "folds": 0}

    # -- write-through (called under the store lock) -------------------
    def note_upsert(self, index: int, a: Allocation) -> None:
        if self.enabled:
            self._note((a.namespace, a.job_id), index, "up", a)

    def note_delete(self, index: int, namespace: str, job_id: str,
                    alloc_id: str) -> None:
        if self.enabled:
            self._note((namespace, job_id), index, "del", alloc_id)

    def _note(self, key, index: int, op: str, payload) -> None:
        # unlocked early-out: with no live entries (engine off, or no
        # columnar read yet) a 10k-alloc plan apply must not pay 10k
        # mutex round-trips on the commit path. Safe, not just benign:
        # entry INSTALL happens under the store lock (get()), and every
        # _note caller also holds the store lock, so install and note
        # can never interleave
        if not self._entries:
            return
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return
            if len(e.deltas) >= self.delta_max:
                # a cold entry nobody reads must not hoard deltas; the
                # next read rebuilds dense
                del self._entries[key]
                self.stats["entry_drops"] += 1
                return
            e.deltas.append((index, op, payload))

    def install(self, key: Tuple[str, str], cols: JobAllocColumns,
                version: int) -> None:
        """Install a pre-built entry (restore's eager rebuild — ISSUE
        8 satellite). Caller holds the store lock, same as the install
        in get(), so the unlocked early-out in _note stays safe."""
        if not self.enabled:
            return
        with self._lock:
            while len(self._entries) >= self.max_jobs:
                self._entries.pop(next(iter(self._entries)))
                self.stats["entry_drops"] += 1
            self._entries[key] = _Entry(cols, version)

    def note_bulk_load(self, index: int,
                       groups: Dict[Tuple[str, str], List[Allocation]],
                       had_prior: Dict[Tuple[str, str], bool]) -> None:
        """Wholesale insert of brand-new allocs (store.bulk_load_allocs
        — called under the store lock): keep the index WARM instead of
        invalidating. An existing entry absorbs its job's rows in place
        (bulk loads ride the module's single-reconciling-reader
        contract: nobody reconciles a job mid-seed) and advances to
        `index` so older snapshots fall back to detached dense builds;
        a job with NO prior allocs gets a fresh entry built from
        exactly this batch — the whole job state. A job with prior
        allocs but no live entry stays absent (lazy build on first
        read, as before)."""
        if not self.enabled:
            return
        for key, allocs in groups.items():
            with self._lock:
                e = self._entries.get(key)
                if e is not None:
                    for a in allocs:
                        e.cols.upsert(a)
                    e.version = max(e.version, index)
                elif not had_prior.get(key):
                    while len(self._entries) >= self.max_jobs:
                        self._entries.pop(next(iter(self._entries)))
                        self.stats["entry_drops"] += 1
                    self._entries[key] = _Entry(
                        JobAllocColumns.build(allocs), index)

    # -- reads ---------------------------------------------------------
    def get(self, snapshot, namespace: str,
            job_id: str) -> Optional[JobAllocColumns]:
        """Columns valid at `snapshot`'s alloc-table index, or None
        when the engine is disabled. Pending deltas at or below the
        snapshot index are applied in place (see the module concurrency
        contract); an older-than-synced snapshot gets a detached dense
        build."""
        if not self.enabled:
            return None
        target = snapshot.index("allocs")
        key = (namespace, job_id)
        due = None
        cols = None
        with self._lock:
            e = self._entries.get(key)
            if e is not None and e.version <= target:
                if len(e.cols.job_objs) > _JOB_PIN_MAX:
                    del self._entries[key]   # stale job pins: rebuild
                    self.stats["entry_drops"] += 1
                else:
                    d = e.deltas
                    i = 0
                    while i < len(d) and d[i][0] <= target:
                        i += 1
                    due = d[:i]
                    if i:
                        del d[:i]
                        self.stats["delta_syncs"] += 1
                        self.stats["delta_rows"] += i
                    e.version = target
                    cols = e.cols
        if cols is not None:
            # apply OUTSIDE the cache lock: note_* callers hold the
            # store lock while waiting on it, so a large sync under the
            # lock would stall the raft apply path. Safe per the module
            # contract (one reconciling reader per job), and the due
            # slice is already detached — concurrent writers only
            # append fresh deltas with higher indexes.
            for _idx, op, payload in due:
                if op == "del":
                    cols.delete(payload)
                else:
                    cols.upsert(payload)
            return cols

        cols = JobAllocColumns.build(snapshot.allocs_by_job(namespace,
                                                            job_id))
        with self._lock:
            self.stats["rebuilds"] += 1
        store = getattr(snapshot, "_store", None)
        if store is not None:
            # install only if the live store still sits exactly at this
            # snapshot's alloc index: writes hold store._lock while they
            # note deltas, so checking under it closes the race where a
            # commit between build and install would be lost forever
            with store._lock:
                if store.index("allocs") == target:
                    with self._lock:
                        if key not in self._entries:
                            while len(self._entries) >= self.max_jobs:
                                self._entries.pop(
                                    next(iter(self._entries)))
                                self.stats["entry_drops"] += 1
                            self._entries[key] = _Entry(cols, target)
        return cols

    # -- accounting (governor gauges) ----------------------------------
    def rows(self) -> int:
        with self._lock:
            return sum(e.cols.n for e in self._entries.values())

    def debt(self) -> int:
        with self._lock:
            return sum(len(e.deltas) for e in self._entries.values())

    def entries(self) -> int:
        with self._lock:
            return len(self._entries)

    def fold(self) -> dict:
        """Governor reclaim: drop every entry so the next read per job
        is one dense rebuild — the columnar-index analog of the node
        table's fold-to-rebuild."""
        with self._lock:
            dropped = len(self._entries)
            reclaimed = sum(len(e.deltas) for e in self._entries.values())
            self._entries.clear()
            self.stats["folds"] += 1
        return {"entries_dropped": dropped,
                "delta_reclaimed": reclaimed}
