from .store import StateStore, StateSnapshot

__all__ = ["StateStore", "StateSnapshot"]
