"""MVCC in-memory state store with O(1) immutable snapshots and blocking
watches — the go-memdb equivalent.

Reference semantics: nomad/state/state_store.go (StateStore:64, 21-table
schema at nomad/state/schema.go:36-62, SnapshotMinIndex:186) and the FSM
mutations in nomad/fsm.go. Tables are persistent HAMTs: a write
transaction path-copies the touched tables and atomically publishes a new
root; readers (schedulers) hold their root forever at O(1) cost — this is
what makes optimistic concurrent scheduling cheap.

Secondary indexes (allocs by node/job/eval, evals by job) are nested
HAMTs maintained in the same transaction.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field, replace
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from ..models import (
    Allocation, Deployment, Evaluation, Job, Node, ScalingPolicy,
    SchedulerConfiguration,
    ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_FAILED, ALLOC_CLIENT_LOST,
    ALLOC_CLIENT_RUNNING, ALLOC_CLIENT_PENDING,
    ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT,
    EVAL_STATUS_BLOCKED,
    JOB_STATUS_DEAD, JOB_STATUS_PENDING, JOB_STATUS_RUNNING,
    NODE_SCHED_ELIGIBLE, NODE_SCHED_INELIGIBLE,
)
from ..models.deployment import DeploymentStatusUpdate
from ..utils.hamt import EditContext, Hamt  # noqa: F401 (substrate option)
from ..utils.layermap import LayerMap
from ..utils.locks import make_condition, make_rlock

# Table substrate: LayerMap implements the same persistent-map
# contract as Hamt (O(1) snapshots, transient edit sessions) on
# layered CPython dicts — 10-100x faster on the store's real write
# and scan workloads (see utils/layermap.py).
_Table = LayerMap

LOG = logging.getLogger("nomad_tpu.state")


@dataclass
class JobSummary:
    """Per-TG alloc status counts (structs.go JobSummary)."""
    job_id: str = ""
    namespace: str = "default"
    summary: Dict[str, Dict[str, int]] = field(default_factory=dict)
    children_pending: int = 0
    children_running: int = 0
    children_dead: int = 0
    create_index: int = 0
    modify_index: int = 0


class _Root:
    """One immutable version of the whole database.

    `edit()` opens a transient write transaction (utils/hamt.py
    EditContext): all table writes through the returned root share one
    edit context, so a transaction touching k keys path-copies each trie
    node at most once. `frozen()` seals the transaction before publish —
    published roots are immutable again."""

    __slots__ = ("tables", "indexes", "_ctx")

    def __init__(self, tables: Hamt, indexes: Hamt, _ctx=None):
        self.tables = tables      # name -> Hamt(primary key -> object)
        self.indexes = indexes    # table name -> last modify index
        self._ctx = _ctx

    def table(self, name: str) -> Hamt:
        # always normalize the edit context: a stored table may carry the
        # ctx of the transaction that wrote it, and writing through a
        # stale ctx would mutate published nodes
        t = self.tables.get(name) or _Table()
        return t.with_ctx(self._ctx)

    def with_table(self, name: str, t: Hamt) -> "_Root":
        return _Root(self.tables.set(name, t), self.indexes, self._ctx)

    def with_index(self, name: str, idx: int) -> "_Root":
        return _Root(self.tables, self.indexes.set(name, idx), self._ctx)

    def edit(self) -> "_Root":
        ctx = EditContext()
        return _Root(self.tables.with_ctx(ctx), self.indexes.with_ctx(ctx),
                     ctx)

    def frozen(self) -> "_Root":
        if self._ctx is None:
            return self
        # deep-freeze: the VALUES of `tables` are per-table Hamts that
        # still carry this transaction's EditContext; leaving it attached
        # would pin every trie node the transaction created (via
        # ctx.keepalive) for as long as the table value survives, and
        # force table() to re-wrap on every read
        tables = self.tables.frozen()
        for name, t in tables.items():
            if t._ctx is not None:
                tables = tables.set(name, t.frozen())
        return _Root(tables, self.indexes.frozen())


TABLES = (
    "nodes", "jobs", "job_versions", "evals", "allocs", "deployments",
    "job_summaries", "scheduler_config", "periodic_launches",
    "acl_policies", "acl_tokens", "csi_volumes", "service_registrations",
    "vault_accessors",
    # secondary indexes
    "allocs_by_node", "allocs_by_job", "allocs_by_eval", "evals_by_job",
    "deployments_by_job", "services_by_name", "services_by_alloc",
    "vault_accessors_by_alloc", "vault_accessors_by_token",
)

JOB_TRACKED_VERSIONS = 6  # structs.go JobTrackedVersions


def _client_status_bucket(a: Optional["Allocation"]) -> Optional[str]:
    """JobSummary bucket for an alloc's client status
    (state_store.go updateSummaryWithAlloc)."""
    if a is None:
        return None
    cs = a.client_status
    if cs == ALLOC_CLIENT_PENDING:
        return "starting"
    if cs == ALLOC_CLIENT_RUNNING:
        return "running"
    if cs == ALLOC_CLIENT_COMPLETE:
        return "complete"
    if cs == ALLOC_CLIENT_FAILED:
        return "failed"
    if cs == ALLOC_CLIENT_LOST:
        return "lost"
    return None


class StateSnapshot:
    """A read-only view at one index. Safe to hold across scheduler runs."""

    def __init__(self, root: _Root, store: "StateStore" = None):
        self._root = root
        self._store = store

    def job_alloc_columns(self, namespace: str, job_id: str):
        """Columnar alloc index for one job at this snapshot's alloc
        index (state/alloc_index.py JobAllocColumns), or None when the
        engine is off or the snapshot is detached from a store."""
        if self._store is None:
            return None
        return self._store.alloc_index.get(self, namespace, job_id)

    def node_table(self, build: bool = True):
        """The columnar node table for this snapshot. Snapshots taken
        from a live store share its resident delta-maintained table
        (ops/tables.py NodeTableCache — SURVEY §7.2 step 8: no per-eval
        rebuild); detached snapshots build fresh. `build=False` returns
        None instead of paying a full private build when the resident
        table has already advanced past this snapshot (callers with a
        cheap fallback, e.g. the plan applier's scalar verify)."""
        from ..ops.tables import NodeTable
        if self._store is None:
            return NodeTable.build_all(self) if build else None
        return self._store.table_cache.get(self, build=build)

    # -- index bookkeeping --------------------------------------------
    def index(self, table: str) -> int:
        return self._root.indexes.get(table, 0)

    def latest_index(self) -> int:
        return max([0] + list(self._root.indexes.values()))

    # -- nodes ---------------------------------------------------------
    def csi_volume(self, namespace: str, volume_id: str):
        return self._root.table("csi_volumes").get((namespace, volume_id))

    def node_by_id(self, node_id: str) -> Optional[Node]:
        return self._root.table("nodes").get(node_id)

    def nodes(self) -> List[Node]:
        return list(self._root.table("nodes").values())

    def node_count(self) -> int:
        """O(1) node-table cardinality (the worker's batching heuristic
        reads this per drained batch)."""
        return len(self._root.table("nodes"))

    def node_by_prefix(self, prefix: str) -> List[Node]:
        return [n for n in self.nodes() if n.id.startswith(prefix)]

    # -- jobs ----------------------------------------------------------
    def job_by_id(self, namespace: str, job_id: str) -> Optional[Job]:
        return self._root.table("jobs").get((namespace, job_id))

    def jobs(self, namespace: Optional[str] = None) -> List[Job]:
        out = self._root.table("jobs").values()
        if namespace is None:
            return list(out)
        return [j for j in out if j.namespace == namespace]

    def job_versions(self, namespace: str, job_id: str) -> List[Job]:
        versions = self._root.table("job_versions").get((namespace, job_id))
        if not versions:
            return []
        return sorted(versions.values(), key=lambda j: -j.version)

    def job_by_id_and_version(self, namespace: str, job_id: str,
                              version: int) -> Optional[Job]:
        versions = self._root.table("job_versions").get((namespace, job_id))
        if not versions:
            return None
        return versions.get(version)

    def job_summary(self, namespace: str, job_id: str) -> Optional[JobSummary]:
        return self._root.table("job_summaries").get((namespace, job_id))

    # -- evals ---------------------------------------------------------
    def eval_by_id(self, eval_id: str) -> Optional[Evaluation]:
        return self._root.table("evals").get(eval_id)

    def evals(self) -> List[Evaluation]:
        return list(self._root.table("evals").values())

    def evals_by_job(self, namespace: str, job_id: str) -> List[Evaluation]:
        ids = self._root.table("evals_by_job").get((namespace, job_id))
        if not ids:
            return []
        table = self._root.table("evals")
        return [table[i] for i in ids.keys()]

    # -- allocs --------------------------------------------------------
    def alloc_by_id(self, alloc_id: str) -> Optional[Allocation]:
        return self._root.table("allocs").get(alloc_id)

    def allocs(self) -> List[Allocation]:
        return list(self._root.table("allocs").values())

    def allocs_by_node(self, node_id: str) -> List[Allocation]:
        return self._by_index("allocs_by_node", node_id, "allocs")

    def allocs_by_node_terminal(self, node_id: str,
                                terminal: bool) -> List[Allocation]:
        return [a for a in self.allocs_by_node(node_id)
                if a.terminal_status() == terminal]

    def allocs_by_job(self, namespace: str, job_id: str,
                      anyCreateIndex: bool = True) -> List[Allocation]:
        return self._by_index("allocs_by_job", (namespace, job_id), "allocs")

    def allocs_by_eval(self, eval_id: str) -> List[Allocation]:
        return self._by_index("allocs_by_eval", eval_id, "allocs")

    def allocs_by_deployment(self, deployment_id: str) -> List[Allocation]:
        return [a for a in self.allocs() if a.deployment_id == deployment_id]

    def scheduler_parity_manifest(self) -> Dict[str, List[str]]:
        """Canonical view of scheduling OUTCOMES for cross-cluster
        parity checks (ISSUE 16): per job, the sorted list of live
        alloc names. Node choice and alloc ids are timing- and
        decorrelation-dependent and legitimately differ between
        equivalent clusters; the name set (job × task group × index)
        is what the scheduler promised and must match exactly —
        3-server distributed scheduling must land the same manifest
        as a single server given the same workload."""
        out: Dict[str, List[str]] = {}
        for a in self.allocs():
            if a.terminal_status():
                continue
            out.setdefault(f"{a.namespace}/{a.job_id}", []).append(a.name)
        return {k: sorted(v) for k, v in out.items()}

    def _by_index(self, index_table: str, key, target: str) -> List:
        ids = self._root.table(index_table).get(key)
        if not ids:
            return []
        table = self._root.table(target)
        return [table[i] for i in ids.keys()]

    # -- deployments ---------------------------------------------------
    def deployment_by_id(self, deployment_id: str) -> Optional[Deployment]:
        return self._root.table("deployments").get(deployment_id)

    def deployments(self) -> List[Deployment]:
        return list(self._root.table("deployments").values())

    def deployments_by_job(self, namespace: str, job_id: str) -> List[Deployment]:
        return self._by_index("deployments_by_job", (namespace, job_id),
                              "deployments")

    def latest_deployment_by_job(self, namespace: str,
                                 job_id: str) -> Optional[Deployment]:
        ds = self.deployments_by_job(namespace, job_id)
        if not ds:
            return None
        return max(ds, key=lambda d: d.create_index)

    # -- periodic launches ---------------------------------------------
    def periodic_launch(self, namespace: str, job_id: str) -> Optional[float]:
        """Last launch time for a periodic job (periodic_launch table)."""
        return self._root.table("periodic_launches").get((namespace, job_id))

    def periodic_launches(self) -> Dict[Tuple[str, str], float]:
        return dict(self._root.table("periodic_launches").items())

    # -- children (periodic / dispatch) --------------------------------
    def jobs_by_parent(self, namespace: str, parent_id: str) -> List[Job]:
        return [j for j in self.jobs(namespace) if j.parent_id == parent_id]

    # -- config --------------------------------------------------------
    def scheduler_config(self) -> SchedulerConfiguration:
        return (self._root.table("scheduler_config").get("config")
                or SchedulerConfiguration())

    # -- namespaces (state_store.go UpsertNamespaces:5565) -------------
    def namespaces(self) -> List:
        """All namespaces; "default" exists implicitly (the reference
        seeds it at bootstrap)."""
        from ..models.namespace import DEFAULT_NAMESPACE, Namespace
        t = self._root.table("namespaces")
        out = list(t.values())
        if t.get(DEFAULT_NAMESPACE) is None:
            out.append(Namespace(name=DEFAULT_NAMESPACE,
                                 description="Default shared namespace"))
        out.sort(key=lambda n: n.name)
        return out

    def namespace_by_name(self, name: str):
        from ..models.namespace import DEFAULT_NAMESPACE, Namespace
        got = self._root.table("namespaces").get(name)
        if got is None and name == DEFAULT_NAMESPACE:
            return Namespace(name=DEFAULT_NAMESPACE,
                             description="Default shared namespace")
        return got

    # -- service registry reads (built-in catalog) ---------------------
    def service_registrations(self, namespace: Optional[str] = None
                              ) -> List:
        out = [s for s in
               self._root.table("service_registrations").values()
               if namespace is None or s.namespace == namespace]
        out.sort(key=lambda s: (s.service_name, s.id))
        return out

    def service_by_name(self, namespace: str, name: str) -> List:
        members = self._root.table("services_by_name").get(
            (namespace, name))
        if members is None:
            return []
        t = self._root.table("service_registrations")
        out = [t.get(rid) for rid in members.keys()]
        return sorted((s for s in out if s is not None),
                      key=lambda s: s.id)

    def services_by_alloc(self, alloc_id: str) -> List:
        members = self._root.table("services_by_alloc").get(alloc_id)
        if members is None:
            return []
        t = self._root.table("service_registrations")
        return sorted((s for s in (t.get(rid) for rid in members.keys())
                       if s is not None), key=lambda s: s.id)

    # -- checkpoint (fsm.go Snapshot:1360) -----------------------------
    def dump(self) -> dict:
        """Wire-encode the full database for a snapshot file (LEGACY
        object format — one wire dict per row; the raft InstallSnapshot
        wire keeps using it for cross-version compatibility). Defined on
        the snapshot view so a raft leader can capture an O(1) MVCC root
        under the apply lock and serialize it afterwards without
        blocking writers (raft.py _send_snapshot)."""
        from ..utils.codec import to_wire
        root = self._root
        out = {"indexes": dict(root.indexes.items()), "tables": {}}
        plain = out["tables"]
        plain["nodes"] = [to_wire(n) for n in root.table("nodes").values()]
        plain["evals"] = [to_wire(e) for e in root.table("evals").values()]
        plain["allocs"] = [to_wire(a) for a in root.table("allocs").values()]
        self._dump_small(root, plain)
        return out

    def dump_columnar(self) -> dict:
        """Format-2 snapshot: the three big tables (allocs/evals/nodes)
        as struct-of-arrays (state/columnar.py — numpy buffers framed
        in msgpack, dedup pools for nested values), everything else in
        the legacy wire shape. Encode/decode is O(columns + unique
        nested values) instead of O(objects)."""
        from .columnar import SNAPSHOT_FORMAT, encode_table
        root = self._root
        out = {"format": SNAPSHOT_FORMAT,
               "indexes": dict(root.indexes.items()),
               "tables": {}, "columnar": {}}
        self._dump_small(root, out["tables"])
        cal = out["columnar"]
        cal["nodes"] = encode_table(list(root.table("nodes").values()))
        cal["evals"] = encode_table(list(root.table("evals").values()))
        cal["allocs"] = encode_table(list(root.table("allocs").values()))
        return out

    def _dump_small(self, root: _Root, plain: dict) -> None:
        """Every table EXCEPT the big three — shared by the legacy and
        columnar dump formats."""
        from ..utils.codec import to_wire
        plain["jobs"] = [to_wire(j) for j in root.table("jobs").values()]
        plain["job_versions"] = [
            {"key": list(k), "versions": {str(v): to_wire(j)
                                          for v, j in versions.items()}}
            for k, versions in root.table("job_versions").items()]
        plain["deployments"] = [to_wire(d)
                                for d in root.table("deployments").values()]
        plain["job_summaries"] = [to_wire(s) for s in
                                  root.table("job_summaries").values()]
        cfg = root.table("scheduler_config").get("config")
        plain["scheduler_config"] = to_wire(cfg) if cfg else None
        plain["periodic_launches"] = [
            {"key": list(k), "launch_time": v}
            for k, v in root.table("periodic_launches").items()]
        plain["scaling_events"] = [
            {"key": list(k), "events": v}
            for k, v in root.table("scaling_events").items()]
        plain["scaling_policies"] = [
            to_wire(p) for p in root.table("scaling_policies").values()]
        plain["event_sinks"] = [
            to_wire(s) for s in root.table("event_sinks").values()]
        plain["server_members"] = list(
            root.table("server_members").get("members") or [])
        plain["acl_policies"] = [to_wire(p) for p in
                                 root.table("acl_policies").values()]
        plain["acl_tokens"] = [to_wire(t) for t in
                               root.table("acl_tokens").values()]
        plain["csi_volumes"] = [to_wire(v) for v in
                                root.table("csi_volumes").values()]
        plain["service_registrations"] = [
            to_wire(s) for s in
            root.table("service_registrations").values()]
        plain["namespaces"] = [to_wire(n) for n in
                               root.table("namespaces").values()]
        plain["vault_accessors"] = [to_wire(a) for a in
                                    root.table("vault_accessors").values()]


class StateStore(StateSnapshot):
    """The mutable handle: all writes go through FSM-style apply methods
    that stamp a raft-like index and notify blocked watchers."""

    CHANGELOG_MAX = 200_000

    def __init__(self):
        root = _Root(_Table(), _Table()).edit()
        super().__init__(root)
        self._store = self  # StateStore doubles as its own snapshot view
        # RLock: composite mutations re-enter (e.g. update_deployment_status
        # upserting the rolled-back job via upsert_job)
        self._lock = make_rlock()
        self._watch = make_condition()
        # bounded changelog feeding the resident NodeTable's delta path:
        # (index, kind, key) in index order; entries at or below
        # _change_floor may have been pruned
        self._changes: List[Tuple[int, str, str]] = []
        self._change_indexes: List[int] = []
        self._change_floor = 0
        from ..ops.tables import NodeTableCache
        self.table_cache = NodeTableCache()
        # columnar per-job alloc index (state/alloc_index.py): the
        # reconciler's struct-of-arrays view, advanced write-through by
        # every alloc mutation below
        from .alloc_index import AllocIndexCache
        self.alloc_index = AllocIndexCache()
        # interned node-attribute columns (state/node_attr_index.py):
        # the feasibility compiler's resident code columns, advanced
        # write-through by every node mutation below
        from .node_attr_index import NodeAttrIndexCache
        self.attr_index = NodeAttrIndexCache()
        # decoded alloc columns left behind by a columnar restore for
        # the resident table's vectorized cold build (pop_cold_columns)
        self._cold_columns = None

    # -- changelog -----------------------------------------------------
    def _log_change(self, index: int, kind: str, key: str) -> None:
        self._changes.append((index, kind, key))
        self._change_indexes.append(index)
        if len(self._changes) > self.CHANGELOG_MAX:
            drop = len(self._changes) - self.CHANGELOG_MAX
            self._change_floor = self._changes[drop - 1][0]
            del self._changes[:drop]
            del self._change_indexes[:drop]

    def changes_since(self, from_idx: int,
                      to_idx: int) -> Optional[List[Tuple[str, str]]]:
        """Node/alloc changes with from_idx < index <= to_idx, or None if
        the log no longer reaches back to from_idx (caller rebuilds)."""
        import bisect
        with self._lock:
            if from_idx < self._change_floor:
                return None
            lo = bisect.bisect_right(self._change_indexes, from_idx)
            hi = bisect.bisect_right(self._change_indexes, to_idx)
            return [(k, key) for (_i, k, key) in self._changes[lo:hi]]

    # -- governance accounting / compaction (governor/) ----------------
    def table_stats(self) -> Dict[str, dict]:
        """Per-table size + layer-overlay stats for the governor's
        accounting pass."""
        out: Dict[str, dict] = {}
        for name, t in self._root.tables.items():
            stats = getattr(t, "layer_stats", None)
            out[name] = stats() if stats is not None else {"size": len(t)}
        return out

    def version_debt(self) -> int:
        """Total uncompacted overlay entries (tip writes + tombstones)
        across tables — the store-side version chain the round-5 soak
        showed growing between snapshots. The automatic fold threshold
        is len(base)/8, which on a 2M-row alloc table lets ~250k stale
        overlay entries accumulate before a fold; the governor bounds
        this via compact()."""
        debt = 0
        for t in self._root.tables.values():
            ov = getattr(t, "overlay_len", None)
            if ov is not None:
                debt += ov()
        return debt

    def changelog_len(self) -> int:
        with self._lock:
            return len(self._changes)

    def compact(self, min_tip: int = 1024, force: bool = False) -> dict:
        """Fold every table whose overlay warrants it into its base,
        dropping tombstones (the state-store analog of old-version
        compaction). A fold costs O(len(base)) under the write lock,
        so a table must earn it: overlay >= min_tip AND >= base/32 —
        without the proportional floor a 2M-row table with a 1k
        overlay would copy 2M entries (stalling every plan apply) to
        reclaim almost nothing.

        `force` is the governor's over-watermark escalation: total
        debt breached its bound, so the proportional floor must not
        be allowed to veto every table (debt split across big tables,
        each individually under base/32, would otherwise leave the
        reclaim a permanent no-op). Forced folds go largest-overlay
        first and stop once half the candidate debt is reclaimed, so
        the big offenders pay and the long tail is spared.

        Published snapshots keep reading their own roots untouched.
        Returns fold accounting for the governor's reclaim event."""
        folded = 0
        reclaimed = 0
        with self._lock:
            cands = []
            for t in self._root.tables.values():
                ov = getattr(t, "overlay_len", None)
                if ov is None:
                    continue
                n = ov()
                if n < max(min_tip, 1):
                    continue
                if not force and n * 32 < t.layer_stats()["base"]:
                    continue
                cands.append((n, id(t), t))
            cands.sort(reverse=True)
            target = sum(n for n, _, _ in cands) / 2.0 if force else None
            for n, _, t in cands:
                if target is not None and reclaimed >= target:
                    break
                reclaimed += n
                t.fold()
                folded += 1
        return {"tables_folded": folded, "overlay_reclaimed": reclaimed}

    # -- snapshot / blocking ------------------------------------------
    def snapshot(self) -> StateSnapshot:
        return StateSnapshot(self._root, self)

    def snapshot_min_index(self, index: int, timeout_s: float = 5.0) -> StateSnapshot:
        """Wait until the store has caught up to `index`, then snapshot
        (state_store.go:186 SnapshotMinIndex — the scheduler's raft fence)."""
        deadline = time.monotonic() + timeout_s
        with self._watch:
            while self.latest_index() < index:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"timeout waiting for state at index {index} "
                        f"(have {self.latest_index()})")
                self._watch.wait(remaining)
        return self.snapshot()

    def block_min_index(self, index: int, timeout_s: float) -> bool:
        """Blocking-query support: wait for any write past `index`."""
        deadline = time.monotonic() + timeout_s
        with self._watch:
            while self.latest_index() <= index:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._watch.wait(remaining)
            return True

    def _publish(self, root: _Root) -> None:
        # seal any open edit context: published roots are immutable
        self._root = root.frozen()
        with self._watch:
            self._watch.notify_all()

    # -- helpers -------------------------------------------------------
    @staticmethod
    def _index_add(root: _Root, table: str, key, member) -> _Root:
        t = root.table(table)
        # nested member sets ride the transaction's edit context but are
        # stored frozen so no stale ctx can ever mutate published nodes
        members = (t.get(key) or _Table()).with_ctx(root._ctx)
        return root.with_table(
            table, t.set(key, members.set(member, True).frozen()))

    @staticmethod
    def _index_del(root: _Root, table: str, key, member) -> _Root:
        t = root.table(table)
        members = t.get(key)
        if members is None:
            return root
        members = members.delete(member)
        if len(members) == 0:
            return root.with_table(table, t.delete(key))
        return root.with_table(table, t.set(key, members.frozen()))

    # -- nodes ---------------------------------------------------------
    def upsert_node(self, index: int, node: Node) -> None:
        with self._lock:
            root = self._root.edit()
            existing = root.table("nodes").get(node.id)
            if existing is not None:
                node.create_index = existing.create_index
                # preserve operator-set fields across re-registration
                node.drain = existing.drain
                node.drain_strategy = existing.drain_strategy
                node.scheduling_eligibility = existing.scheduling_eligibility
            else:
                node.create_index = index
            node.modify_index = index
            node.canonicalize()
            if not node.computed_class:
                node.compute_class()
            root = root.with_table("nodes", root.table("nodes").set(node.id, node))
            root = root.with_index("nodes", index)
            self._log_change(index, "node", node.id)
            self.attr_index.note_upsert(index, node)
            self._publish(root)

    def delete_node(self, index: int, node_ids: List[str]) -> None:
        with self._lock:
            root = self._root.edit()
            t = root.table("nodes")
            for nid in node_ids:
                t = t.delete(nid)
            root = root.with_table("nodes", t).with_index("nodes", index)
            for nid in node_ids:
                self._log_change(index, "node", nid)
                self.attr_index.note_delete(index, nid)
            self._publish(root)

    def update_node_status(self, index: int, node_id: str, status: str,
                           updated_at: int = 0) -> None:
        with self._lock:
            self._update_node(index, node_id,
                              status=status, status_updated_at=updated_at)

    def update_node_eligibility(self, index: int, node_id: str,
                                eligibility: str) -> None:
        with self._lock:
            self._update_node(index, node_id, scheduling_eligibility=eligibility)

    def update_node_drain(self, index: int, node_id: str, drain_strategy,
                          mark_eligible: bool = False) -> None:
        with self._lock:
            node = self._root.table("nodes").get(node_id)
            if node is None:
                raise KeyError(f"node {node_id} not found")
            eligibility = node.scheduling_eligibility
            if drain_strategy is not None:
                eligibility = NODE_SCHED_INELIGIBLE
            elif mark_eligible:
                eligibility = NODE_SCHED_ELIGIBLE
            self._update_node(index, node_id,
                              drain=drain_strategy is not None,
                              drain_strategy=drain_strategy,
                              scheduling_eligibility=eligibility)

    def _update_node(self, index: int, node_id: str, **changes) -> None:
        root = self._root.edit()
        node = root.table("nodes").get(node_id)
        if node is None:
            raise KeyError(f"node {node_id} not found")
        node = replace(node, modify_index=index, **changes)
        root = root.with_table("nodes", root.table("nodes").set(node_id, node))
        root = root.with_index("nodes", index)
        self._log_change(index, "node", node_id)
        self.attr_index.note_upsert(index, node)
        self._publish(root)

    # -- jobs ----------------------------------------------------------
    def upsert_job(self, index: int, job: Job) -> None:
        with self._lock:
            root = self._upsert_job_root(self._root.edit(), index, job)
            self._publish(root)

    def upsert_jobs_batch(self, index: int, jobs: List[Job]) -> None:
        """Batched register ingest (ISSUE 19): one committed `ingest_batch`
        entry's job registers on ONE edit root with ONE publish, applied
        in submission order — state-equivalent to sequential upsert_job
        calls at the same index (same-job re-registers on one root still
        see each other's version bumps)."""
        if not jobs:
            return
        with self._lock:
            root = self._root.edit()
            for job in jobs:
                root = self._upsert_job_root(root, index, job)
            self._publish(root)

    def _upsert_job_root(self, root: _Root, index: int, job: Job) -> _Root:
        key = job.namespaced_id()
        existing = root.table("jobs").get(key)
        if existing is not None:
            job.create_index = existing.create_index
            job.job_modify_index = index
            if existing.specchanged(job):
                job.version = existing.version + 1
            else:
                job.version = existing.version
        else:
            job.create_index = index
            job.job_modify_index = index
            job.version = 0
        job.modify_index = index
        if job.status == "":
            job.status = JOB_STATUS_PENDING
        root = root.with_table("jobs", root.table("jobs").set(key, job))
        # version history (pruned to JOB_TRACKED_VERSIONS)
        versions = root.table("job_versions").get(key) or _Table()
        versions = versions.set(job.version, job)
        if len(versions) > JOB_TRACKED_VERSIONS:
            oldest = min(versions.keys())
            versions = versions.delete(oldest)
        root = root.with_table("job_versions",
                               root.table("job_versions").set(key, versions))
        root = self._ensure_job_summary(root, index, job)
        root = self._sync_scaling_policies(root, index, job)
        if job.parent_id:
            root = self._bump_parent_children(
                root, index, (job.namespace, job.parent_id),
                existing.status if existing is not None else None,
                job.status)
        return root.with_index("jobs", index)

    def _sync_scaling_policies(self, root: _Root, index: int,
                               job: Job) -> _Root:
        """Derive scaling policies from the job's task-group scaling
        blocks (state_store.go updateJobScalingPolicies; CRUD surface
        nomad/scaling_endpoint.go:24,90). Policies keep their id across
        re-registrations; groups that drop their scaling block lose
        their policy."""
        key = (job.namespace, job.id)
        members = root.table("scaling_policies_by_job").get(key)
        if members is None and not any(tg.scaling is not None
                                       for tg in job.task_groups):
            return root         # common case: no policies either side
        t = root.table("scaling_policies")      # id -> ScalingPolicy
        changed = False
        live_ids = set()
        for tg in job.task_groups:
            if tg.scaling is None:
                continue
            pid = ScalingPolicy.id_for(job.namespace, job.id, tg.name)
            live_ids.add(pid)
            existing = t.get(pid)
            enabled = tg.scaling.enabled and not job.stop
            if existing is None:
                root = self._index_add(root, "scaling_policies_by_job",
                                       key, pid)
            elif (existing.min, existing.max, existing.policy,
                  existing.enabled) == (tg.scaling.min, tg.scaling.max,
                                        tg.scaling.policy, enabled):
                continue        # unchanged: keep its modify_index
            t = t.set(pid, ScalingPolicy(
                id=pid, namespace=job.namespace,
                target={"Namespace": job.namespace, "Job": job.id,
                        "Group": tg.name},
                min=tg.scaling.min, max=tg.scaling.max,
                policy=dict(tg.scaling.policy),
                enabled=enabled,
                create_index=(existing.create_index
                              if existing is not None else index),
                modify_index=index))
            changed = True
        # stale sweep via the per-job member index — never the whole
        # table (this runs inside every job-register FSM apply)
        for pid in list(members.keys()) if members is not None else []:
            if pid not in live_ids:
                t = t.delete(pid)
                root = self._index_del(root, "scaling_policies_by_job",
                                       key, pid)
                changed = True
        if changed:
            root = root.with_table("scaling_policies", t) \
                       .with_index("scaling_policies", index)
        return root

    # -- scaling policies (nomad/scaling_endpoint.go) ------------------
    def scaling_policies(self, namespace: Optional[str] = None,
                         job_id: Optional[str] = None,
                         policy_type: Optional[str] = None
                         ) -> List[ScalingPolicy]:
        out = []
        for pol in self._root.table("scaling_policies").values():
            if namespace is not None and pol.namespace != namespace:
                continue
            if job_id is not None and pol.target.get("Job") != job_id:
                continue
            if policy_type is not None and pol.type != policy_type:
                continue
            out.append(pol)
        out.sort(key=lambda p: p.id)
        return out

    def scaling_policy_by_id(self, policy_id: str
                             ) -> Optional[ScalingPolicy]:
        return self._root.table("scaling_policies").get(policy_id)

    def scaling_policy_by_target(self, namespace: str, job_id: str,
                                 group: str) -> Optional[ScalingPolicy]:
        return self.scaling_policy_by_id(
            ScalingPolicy.id_for(namespace, job_id, group))

    # -- server membership (nomad/serf.go; the voter set rides the
    # replicated log instead of gossip) --------------------------------
    def set_server_members(self, index: int, members: List[str]) -> None:
        with self._lock:
            root = self._root.edit()
            t = root.table("server_members")
            root = root.with_table(
                "server_members",
                t.set("members", list(dict.fromkeys(members)))) \
                .with_index("server_members", index)
            self._publish(root)

    def server_members(self) -> List[str]:
        return list(self._root.table("server_members")
                    .get("members") or [])

    # -- event sinks (nomad/stream/sink.go; event_sinks table) ---------
    def upsert_event_sink(self, index: int, sink) -> None:
        with self._lock:
            root = self._root.edit()
            t = root.table("event_sinks")
            existing = t.get(sink.id)
            if existing is not None:
                sink.create_index = existing.create_index
                # progress survives reconfiguration
                sink.latest_index = max(sink.latest_index,
                                        existing.latest_index)
            else:
                sink.create_index = index
            sink.modify_index = index
            root = root.with_table("event_sinks", t.set(sink.id, sink)) \
                       .with_index("event_sinks", index)
            self._publish(root)

    def delete_event_sink(self, index: int, sink_id: str) -> None:
        with self._lock:
            root = self._root.edit()
            t = root.table("event_sinks")
            if t.get(sink_id) is None:
                return
            root = root.with_table("event_sinks", t.delete(sink_id)) \
                       .with_index("event_sinks", index)
            self._publish(root)

    def update_event_sink_progress(self, index: int, sink_id: str,
                                   latest: int) -> None:
        with self._lock:
            root = self._root.edit()
            t = root.table("event_sinks")
            sink = t.get(sink_id)
            if sink is None or sink.latest_index >= latest:
                return
            from dataclasses import replace as _replace
            sink = _replace(sink, latest_index=latest, modify_index=index)
            root = root.with_table("event_sinks", t.set(sink_id, sink)) \
                       .with_index("event_sinks", index)
            self._publish(root)

    def event_sinks(self) -> List:
        return sorted(self._root.table("event_sinks").values(),
                      key=lambda s: s.id)

    def event_sink(self, sink_id: str):
        return self._root.table("event_sinks").get(sink_id)

    def delete_job(self, index: int, namespace: str, job_id: str) -> None:
        with self._lock:
            root = self._root.edit()
            key = (namespace, job_id)
            existing = root.table("jobs").get(key)
            if existing is not None and existing.parent_id:
                root = self._bump_parent_children(
                    root, index, (namespace, existing.parent_id),
                    existing.status, None)
            root = root.with_table("jobs", root.table("jobs").delete(key))
            root = root.with_table("periodic_launches",
                                   root.table("periodic_launches").delete(key))
            root = root.with_table("job_versions",
                                   root.table("job_versions").delete(key))
            root = root.with_table("job_summaries",
                                   root.table("job_summaries").delete(key))
            # deregistration drops the job's scaling policies
            # (state_store.go deleteJobScalingPolicies)
            members = root.table("scaling_policies_by_job").get(key)
            if members is not None:
                sp = root.table("scaling_policies")
                for pid in members.keys():
                    sp = sp.delete(pid)
                root = root.with_table("scaling_policies", sp) \
                           .with_table(
                               "scaling_policies_by_job",
                               root.table("scaling_policies_by_job")
                                   .delete(key)) \
                           .with_index("scaling_policies", index)
            root = root.with_index("jobs", index).with_index("job_summaries", index)
            self._publish(root)

    def _ensure_job_summary(self, root: _Root, index: int, job: Job) -> _Root:
        key = job.namespaced_id()
        summaries = root.table("job_summaries")
        existing = summaries.get(key)
        if existing is None:
            s = JobSummary(job_id=job.id, namespace=job.namespace,
                           create_index=index, modify_index=index)
            for tg in job.task_groups:
                s.summary[tg.name] = {}
        else:
            s = existing
            for tg in job.task_groups:
                s.summary.setdefault(tg.name, {})
            s.modify_index = index
        return root.with_table("job_summaries", summaries.set(key, s)) \
                   .with_index("job_summaries", index)

    # -- evals ---------------------------------------------------------
    def upsert_evals(self, index: int, evals: List[Evaluation]) -> None:
        with self._lock:
            root = self._root.edit()
            for e in evals:
                root = self._upsert_eval_impl(root, index, e)
            root = root.with_index("evals", index)
            self._publish(root)

    def upsert_evals_batch(
            self, items: List[Tuple[int, List[Evaluation]]]) -> None:
        """Batched WAL replay (ISSUE 8): N `eval_update` entries' evals
        on ONE edit root with ONE publish, each eval stamped with its
        own entry index — state-equivalent to sequential upsert_evals
        calls."""
        if not items:
            return
        with self._lock:
            root = self._root.edit()
            last = 0
            for index, evals in items:
                for e in evals:
                    root = self._upsert_eval_impl(root, index, e)
                last = index
            root = root.with_index("evals", last)
            self._publish(root)

    def _upsert_eval_impl(self, root: _Root, index: int, e: Evaluation) -> _Root:
        existing = root.table("evals").get(e.id)
        if existing is not None:
            e.create_index = existing.create_index
        else:
            e.create_index = index
        e.modify_index = index
        root = root.with_table("evals", root.table("evals").set(e.id, e))
        root = self._index_add(root, "evals_by_job", (e.namespace, e.job_id), e.id)
        # cancel older blocked evals for the same job (fsm.go applyUpsertEvals
        # -> state_store nested blocked-eval dedup happens broker-side; the
        # store just records)
        return root

    def delete_evals(self, index: int, eval_ids: List[str],
                     alloc_ids: Optional[List[str]] = None) -> None:
        with self._lock:
            root = self._root.edit()
            for eid in eval_ids:
                e = root.table("evals").get(eid)
                if e is None:
                    continue
                root = root.with_table("evals", root.table("evals").delete(eid))
                root = self._index_del(root, "evals_by_job",
                                       (e.namespace, e.job_id), eid)
            for aid in (alloc_ids or []):
                root = self._delete_alloc_impl(root, aid, index)
            root = root.with_index("evals", index).with_index("allocs", index)
            self._publish(root)

    # -- allocs --------------------------------------------------------
    def upsert_allocs(self, index: int, allocs: List[Allocation]) -> None:
        with self._lock:
            root = self._root.edit()
            for a in allocs:
                root = self._upsert_alloc_impl(root, index, a)
            root = root.with_index("allocs", index)
            self._publish(root)

    def bulk_load_allocs(self, index: int, allocs: List[Allocation]) -> None:
        """Replay/restore-grade bulk insert — the C2M seed path and the
        columnar analog of fsm.go's snapshot Restore:1374. Semantics
        match repeated upsert_allocs for brand-new allocs, but the work
        is batched: one transient pass over the alloc table, grouped
        secondary-index updates (one sub-HAMT rebuild per key instead of
        one per member), a single job-summary aggregation, and a
        changelog floor bump so resident node tables rebuild once
        instead of replaying millions of row deltas."""
        with self._lock:
            root = self._root.edit()
            t = root.table("allocs")
            pairs: List[Tuple[str, Allocation]] = []
            by_node: Dict[str, List[str]] = {}
            by_job: Dict[Tuple[str, str], List[str]] = {}
            by_job_objs: Dict[Tuple[str, str], List[Allocation]] = {}
            by_eval: Dict[str, List[str]] = {}
            summary_delta: Dict[Tuple[str, str], Dict[str, Dict[str, int]]] = {}
            for a in allocs:
                a.create_index = index
                a.modify_index = index
                a.alloc_modify_index = index
                pairs.append((a.id, a))
                by_node.setdefault(a.node_id, []).append(a.id)
                by_job.setdefault((a.namespace, a.job_id), []).append(a.id)
                by_job_objs.setdefault((a.namespace, a.job_id),
                                       []).append(a)
                by_eval.setdefault(a.eval_id, []).append(a.id)
                b = _client_status_bucket(a)
                if b is not None:
                    tgs = summary_delta.setdefault((a.namespace, a.job_id), {})
                    counts = tgs.setdefault(a.task_group, {})
                    counts[b] = counts.get(b, 0) + 1
            # captured BEFORE the index update below: a job with no
            # prior allocs can take a fresh columnar-index entry built
            # from exactly this batch (note_bulk_load)
            prior_jobs = {key: root.table("allocs_by_job").get(key)
                          is not None for key in by_job}
            root = root.with_table("allocs", t.update(pairs))
            for name, groups in (("allocs_by_node", by_node),
                                 ("allocs_by_job", by_job),
                                 ("allocs_by_eval", by_eval)):
                it = root.table(name)
                for key, ids in groups.items():
                    sub = (it.get(key) or _Table()).with_ctx(root._ctx)
                    # single-member adds dominate (a 10k batch touches
                    # 10k distinct nodes): set() skips update()'s batch
                    # machinery
                    if len(ids) == 1:
                        sub = sub.set(ids[0], True)
                    else:
                        sub = sub.update([(i, True) for i in ids])
                    it = it.set(key, sub.frozen())
                root = root.with_table(name, it)
            summaries = root.table("job_summaries")
            changed_summaries = False
            for key, tgs in summary_delta.items():
                s: Optional[JobSummary] = summaries.get(key)
                if s is None:
                    continue
                new_sum = dict(s.summary)
                for tg, buckets in tgs.items():
                    counts = dict(new_sum.get(tg, {}))
                    for b, n in buckets.items():
                        counts[b] = counts.get(b, 0) + n
                    new_sum[tg] = counts
                summaries = summaries.set(
                    key, replace(s, summary=new_sum, modify_index=index))
                changed_summaries = True
            if changed_summaries:
                root = root.with_table("job_summaries", summaries) \
                           .with_index("job_summaries", index)
            root = root.with_index("allocs", index)
            # invalidate the RESIDENT TABLE delta path wholesale: one
            # rebuild beats replaying a multi-million-row changelog
            self._changes.clear()
            self._change_indexes.clear()
            self._change_floor = index
            # …but keep the per-job columnar alloc index WARM (ISSUE 8
            # satellite — the old invalidate_all here made the eval
            # after a seed/restore pay a dense rebuild): existing
            # entries absorb the new rows in place, brand-new jobs get
            # a fresh entry built from exactly this batch
            self.alloc_index.note_bulk_load(index, by_job_objs,
                                            prior_jobs)
            self._publish(root)

    def _upsert_alloc_impl(self, root: _Root, index: int, a: Allocation) -> _Root:
        existing: Optional[Allocation] = root.table("allocs").get(a.id)
        if existing is not None:
            a.create_index = existing.create_index
            # A plan's stop/evict stub carries no job/resources: inherit
            # (fsm.go UpsertAllocs keeps existing fields on update)
            if a.job is None:
                a.job = existing.job
            if a.allocated_resources is None:
                a.allocated_resources = existing.allocated_resources
            if not a.name:
                a.name = existing.name
            if not a.node_id:
                a.node_id = existing.node_id
            if not a.job_id:
                a.job_id = existing.job_id
            if not a.task_group:
                a.task_group = existing.task_group
            if not a.eval_id:
                a.eval_id = existing.eval_id
            if a.client_status == ALLOC_CLIENT_PENDING and existing.client_status:
                # server-side updates don't regress client status
                a.client_status = existing.client_status
                a.task_states = existing.task_states or a.task_states
        else:
            a.create_index = index
        a.modify_index = index
        a.alloc_modify_index = index
        root = root.with_table("allocs", root.table("allocs").set(a.id, a))
        if existing is None:
            root = self._index_add(root, "allocs_by_node", a.node_id, a.id)
            root = self._index_add(root, "allocs_by_job",
                                   (a.namespace, a.job_id), a.id)
            root = self._index_add(root, "allocs_by_eval", a.eval_id, a.id)
        elif existing.node_id != a.node_id:
            root = self._index_del(root, "allocs_by_node", existing.node_id, a.id)
            root = self._index_add(root, "allocs_by_node", a.node_id, a.id)
        root = self._update_summary_for_alloc(root, index, existing, a)
        self._log_change(index, "alloc", a.id)
        self.alloc_index.note_upsert(index, a)
        return root

    def _delete_alloc_impl(self, root: _Root, alloc_id: str,
                           index: int) -> _Root:
        a = root.table("allocs").get(alloc_id)
        if a is None:
            return root
        self._log_change(index, "alloc", alloc_id)
        self.alloc_index.note_delete(index, a.namespace, a.job_id,
                                     alloc_id)
        root = root.with_table("allocs", root.table("allocs").delete(alloc_id))
        root = self._index_del(root, "allocs_by_node", a.node_id, alloc_id)
        root = self._index_del(root, "allocs_by_job",
                               (a.namespace, a.job_id), alloc_id)
        root = self._index_del(root, "allocs_by_eval", a.eval_id, alloc_id)
        return root

    def update_allocs_from_client(self, index: int,
                                  allocs: List[Allocation]) -> None:
        """Client pushes task states / client status (node_endpoint.go:1065)."""
        with self._lock:
            root = self._update_allocs_from_client_root(
                self._root.edit(), index, allocs)
            root = root.with_index("allocs", index)
            self._publish(root)

    def update_allocs_from_client_batch(
            self, items: List[Tuple[int, List[Allocation]]]) -> None:
        """Batched `alloc_client_update` writes on ONE edit root with
        ONE publish, each entry stamped with its own index —
        state-equivalent to sequential update_allocs_from_client calls
        (the mutation sequence is identical; only the layer pushes and
        watcher wakes collapse). Born as WAL replay (ISSUE 8), now also
        the live ingest path (ISSUE 19): a coalesced `ingest_batch` run
        of client updates lands through here as one store transaction."""
        if not items:
            return
        with self._lock:
            root = self._root.edit()
            for index, allocs in items:
                root = self._update_allocs_from_client_root(root, index,
                                                            allocs)
                root = root.with_index("allocs", index)
            self._publish(root)

    def _update_allocs_from_client_root(self, root: _Root, index: int,
                                        allocs: List[Allocation]) -> _Root:
        for update in allocs:
            existing = root.table("allocs").get(update.id)
            if existing is None:
                continue
            merged = replace(
                existing,
                client_status=update.client_status,
                client_description=update.client_description,
                task_states=update.task_states or existing.task_states,
                deployment_status=(update.deployment_status
                                   or existing.deployment_status),
                modify_index=index,
                modify_time=update.modify_time or existing.modify_time,
            )
            root = root.with_table("allocs",
                                   root.table("allocs").set(merged.id, merged))
            root = self._update_summary_for_alloc(root, index, existing, merged)
            root = self._maybe_update_deployment_health(root, index, merged)
            self._log_change(index, "alloc", merged.id)
            self.alloc_index.note_upsert(index, merged)
        return root

    def _maybe_update_deployment_health(self, root: _Root, index: int,
                                        alloc: Allocation) -> _Root:
        if not alloc.deployment_id or alloc.deployment_status is None:
            return root
        d: Optional[Deployment] = root.table("deployments").get(alloc.deployment_id)
        if d is None or not d.active():
            return root
        state = d.task_groups.get(alloc.task_group)
        if state is None:
            return root
        # recount healthy/unhealthy from allocs of this deployment
        healthy = unhealthy = 0
        for a in root.table("allocs").values():
            if a.deployment_id != d.id or a.task_group != alloc.task_group:
                continue
            ds = a.deployment_status if a.id != alloc.id else alloc.deployment_status
            if ds is None or ds.healthy is None:
                continue
            if ds.healthy:
                healthy += 1
            else:
                unhealthy += 1
        new_state = replace(state, healthy_allocs=healthy,
                            unhealthy_allocs=unhealthy)
        d = replace(d, task_groups={**d.task_groups,
                                    alloc.task_group: new_state},
                    modify_index=index)
        return root.with_table("deployments",
                               root.table("deployments").set(d.id, d)) \
                   .with_index("deployments", index)

    # -- job summary maintenance --------------------------------------
    def _update_summary_for_alloc(self, root: _Root, index: int,
                                  old: Optional[Allocation],
                                  new: Allocation) -> _Root:
        key = (new.namespace, new.job_id)
        summaries = root.table("job_summaries")
        s: Optional[JobSummary] = summaries.get(key)
        if s is None:
            return root
        tg = new.task_group
        counts = dict(s.summary.get(tg, {}))

        bucket = _client_status_bucket
        ob, nb = bucket(old), bucket(new)
        if ob == nb:
            if old is not None:
                return root
        if ob is not None:
            counts[ob] = max(0, counts.get(ob, 0) - 1)
        if nb is not None:
            counts[nb] = counts.get(nb, 0) + 1
        new_summary = replace(s, summary={**s.summary, tg: counts},
                              modify_index=index)
        return root.with_table("job_summaries", summaries.set(key, new_summary)) \
                   .with_index("job_summaries", index)

    # -- deployments ---------------------------------------------------
    def upsert_deployment(self, index: int, deployment: Deployment) -> None:
        with self._lock:
            root = self._upsert_deployment_impl(self._root, index, deployment)
            self._publish(root)

    def _upsert_deployment_impl(self, root: _Root, index: int,
                                d: Deployment) -> _Root:
        existing = root.table("deployments").get(d.id)
        if existing is not None:
            d.create_index = existing.create_index
        else:
            d.create_index = index
        d.modify_index = index
        root = root.with_table("deployments",
                               root.table("deployments").set(d.id, d))
        if existing is None:
            root = self._index_add(root, "deployments_by_job",
                                   (d.namespace, d.job_id), d.id)
        return root.with_index("deployments", index)

    def update_deployment_status(self, index: int,
                                 update: DeploymentStatusUpdate,
                                 job: Optional[Job] = None,
                                 evals: Optional[List[Evaluation]] = None) -> None:
        with self._lock:
            root = self._root.edit()
            d = root.table("deployments").get(update.deployment_id)
            if d is None:
                raise KeyError(f"deployment {update.deployment_id} not found")
            d = replace(d, status=update.status,
                        status_description=update.status_description,
                        modify_index=index)
            root = root.with_table("deployments",
                                   root.table("deployments").set(d.id, d))
            root = root.with_index("deployments", index)
            if job is not None:
                self._publish(root)
                self.upsert_job(index, job)
                root = self._root.edit()
            for e in (evals or []):
                root = self._upsert_eval_impl(root, index, e)
            if evals:
                root = root.with_index("evals", index)
            self._publish(root)

    # -- plan apply (the commit point) --------------------------------
    def upsert_plan_results(self, index: int, *,
                            allocs_stopped: List[Allocation],
                            allocs_placed: List[Allocation],
                            allocs_preempted: List[Allocation],
                            deployment: Optional[Deployment] = None,
                            deployment_updates: Optional[List[DeploymentStatusUpdate]] = None,
                            evals: Optional[List[Evaluation]] = None) -> None:
        """Apply a verified plan atomically (fsm.go ApplyPlanResults /
        state_store.go UpsertPlanResults)."""
        with self._lock:
            root = self._plan_results_root(
                self._root.edit(), index,
                allocs_stopped=allocs_stopped,
                allocs_placed=allocs_placed,
                allocs_preempted=allocs_preempted,
                deployment=deployment,
                deployment_updates=deployment_updates,
                evals=evals)
            self._publish(root)

    def upsert_plan_group_results(self, index: int,
                                  groups: List[dict]) -> None:
        """Apply a whole plan GROUP as ONE transaction (group-commit
        applier): every group member's writes land on one edit root —
        a single layer push across the alloc/index/summary tables
        instead of N, directly reducing the layer-overlay debt the
        governor's compact() reclaim exists to fold — and publish once,
        so watchers wake once per group."""
        with self._lock:
            root = self._root.edit()
            for g in groups:
                root = self._plan_results_root(
                    root, index,
                    allocs_stopped=g.get("allocs_stopped") or [],
                    allocs_placed=g.get("allocs_placed") or [],
                    allocs_preempted=g.get("allocs_preempted") or [],
                    deployment=g.get("deployment"),
                    deployment_updates=g.get("deployment_updates"),
                    evals=g.get("evals"))
            self._publish(root)

    def _plan_results_root(self, root: _Root, index: int, *,
                           allocs_stopped: List[Allocation],
                           allocs_placed: List[Allocation],
                           allocs_preempted: List[Allocation],
                           deployment: Optional[Deployment] = None,
                           deployment_updates: Optional[List[DeploymentStatusUpdate]] = None,
                           evals: Optional[List[Evaluation]] = None) -> _Root:
        """One plan's writes onto an open edit root (shared by the
        single-plan and group-commit paths; caller holds the lock and
        publishes)."""
        t_allocs = root.table("allocs")
        fresh = [a for a in allocs_placed
                 if t_allocs.get(a.id) is None]
        fresh_ids = {a.id for a in fresh}
        new_placed = [a for a in fresh if a.deployment_id]
        for a in allocs_stopped:
            root = self._upsert_alloc_impl(root, index, a)
        # in-place updates go through the general path; brand-new
        # placements take the bulk path (one index write per key)
        for a in allocs_placed:
            if a.id not in fresh_ids:
                root = self._upsert_alloc_impl(root, index, a)
        root = self._bulk_insert_allocs(root, index, fresh)
        for a in allocs_preempted:
            root = self._upsert_alloc_impl(root, index, a)
        # claim CSI volumes for placements whose task group requests
        # them (csi_hook claim-at-placement; the volume watcher
        # releases claims once allocs turn terminal)
        root = self._claim_csi_for_placements(root, index,
                                              allocs_placed)
        if deployment is not None:
            root = self._upsert_deployment_impl(root, index, deployment)
        for a in new_placed:
            root = self._deployment_account_placement(root, index, a)
        for du in (deployment_updates or []):
            d = root.table("deployments").get(du.deployment_id)
            if d is not None:
                d = replace(d, status=du.status,
                            status_description=du.status_description,
                            modify_index=index)
                root = root.with_table(
                    "deployments", root.table("deployments").set(d.id, d))
        for e in (evals or []):
            root = self._upsert_eval_impl(root, index, e)
        return (root.with_index("allocs", index)
                    .with_index("deployments", index)
                    .with_index("evals", index))

    def _bulk_insert_allocs(self, root: _Root, index: int,
                            allocs: List[Allocation]) -> _Root:
        """Insert allocations known to be ABSENT from the table. Same
        effect as _upsert_alloc_impl per alloc, but secondary-index and
        job-summary writes are grouped per key — a 10k-alloc plan apply
        does ~1 outer write per touched node/job/eval instead of 14
        HAMT writes per alloc."""
        if not allocs:
            return root
        t = root.table("allocs")
        pairs = []
        for a in allocs:
            a.create_index = index
            a.modify_index = index
            a.alloc_modify_index = index
            pairs.append((a.id, a))
            self._log_change(index, "alloc", a.id)
            self.alloc_index.note_upsert(index, a)
        root = root.with_table("allocs", t.update(pairs))

        for table, keyfn in (
                ("allocs_by_node", lambda a: a.node_id),
                ("allocs_by_job", lambda a: (a.namespace, a.job_id)),
                ("allocs_by_eval", lambda a: a.eval_id)):
            groups: Dict = {}
            for a in allocs:
                groups.setdefault(keyfn(a), []).append(a.id)
            tt = root.table(table)
            pairs = []
            for key, ids in groups.items():
                members = tt.get(key)
                if members is None:
                    members = _Table()
                # single-member adds dominate spread-out batches: a
                # frozen set() skips the with_ctx/update/frozen dance
                if len(ids) == 1:
                    members = members.set(ids[0], True)
                else:
                    members = members.with_ctx(root._ctx).update(
                        [(aid, True) for aid in ids]).frozen()
                pairs.append((key, members))
            # ONE outer batch write per index table: per-key .set walks
            # the trie path each time (a 10k-alloc plan touches ~1k
            # nodes)
            root = root.with_table(table, tt.update(pairs))

        # job summaries: aggregate bucket deltas per job
        per_job: Dict = {}
        for a in allocs:
            nb = _client_status_bucket(a)
            if nb is None:
                continue
            deltas = per_job.setdefault((a.namespace, a.job_id), {})
            k = (a.task_group, nb)
            deltas[k] = deltas.get(k, 0) + 1
        if per_job:
            summaries = root.table("job_summaries")
            changed = False
            for key, deltas in per_job.items():
                s: Optional[JobSummary] = summaries.get(key)
                if s is None:
                    continue
                summ = dict(s.summary)
                for (tg, b), cnt in deltas.items():
                    counts = dict(summ.get(tg, {}))
                    counts[b] = counts.get(b, 0) + cnt
                    summ[tg] = counts
                summaries = summaries.set(
                    key, replace(s, summary=summ, modify_index=index))
                changed = True
            if changed:
                root = root.with_table("job_summaries", summaries) \
                           .with_index("job_summaries", index)
        return root

    def update_alloc_desired_transitions(self, index: int,
                                         alloc_ids: List[str],
                                         transition,
                                         evals: Optional[List[Evaluation]] = None) -> None:
        """Set server-desired transitions (state_store.go
        UpdateAllocsDesiredTransitions) — the drainer's migrate flag."""
        with self._lock:
            root = self._root.edit()
            updates = {k: v for k, v in vars(transition).items()
                       if v is not None}
            for aid in alloc_ids:
                a: Optional[Allocation] = root.table("allocs").get(aid)
                if a is None:
                    continue
                a = replace(a, desired_transition=replace(
                    a.desired_transition, **updates), modify_index=index)
                root = root.with_table("allocs",
                                       root.table("allocs").set(aid, a))
                self._log_change(index, "alloc", aid)
                self.alloc_index.note_upsert(index, a)
            for e in (evals or []):
                root = self._upsert_eval_impl(root, index, e)
            root = root.with_index("allocs", index)
            if evals:
                root = root.with_index("evals", index)
            self._publish(root)

    def _deployment_account_placement(self, root: _Root, index: int,
                                      alloc: Allocation) -> _Root:
        """Bump placed counts / canary list on the owning deployment
        (state_store.go updateDeploymentWithAlloc)."""
        d: Optional[Deployment] = root.table("deployments").get(alloc.deployment_id)
        if d is None or not d.active():
            return root
        state = d.task_groups.get(alloc.task_group)
        if state is None:
            return root
        canaries = state.placed_canaries
        if (alloc.deployment_status is not None and alloc.deployment_status.canary
                and alloc.id not in canaries):
            canaries = canaries + [alloc.id]
        new_state = replace(state, placed_allocs=state.placed_allocs + 1,
                            placed_canaries=canaries)
        d = replace(d, task_groups={**d.task_groups,
                                    alloc.task_group: new_state},
                    modify_index=index)
        return root.with_table("deployments",
                               root.table("deployments").set(d.id, d)) \
                   .with_index("deployments", index)

    def update_deployment_promotion(self, index: int, deployment_id: str,
                                    groups: Optional[List[str]] = None,
                                    evals: Optional[List[Evaluation]] = None) -> None:
        """Mark task groups promoted (state_store.go
        UpdateDeploymentPromotion). Validation happens at the RPC layer;
        the FSM apply is unconditional so WAL replay is deterministic."""
        from ..models.deployment import DESC_RUNNING
        with self._lock:
            root = self._root.edit()
            d: Optional[Deployment] = root.table("deployments").get(deployment_id)
            if d is None:
                raise KeyError(f"deployment {deployment_id} not found")
            new_states = dict(d.task_groups)
            for name, state in d.task_groups.items():
                if state.desired_canaries == 0:
                    continue
                if groups and name not in groups:
                    continue
                new_states[name] = replace(state, promoted=True)
            # a paused deployment keeps its pause description; only a
            # running one flips to the plain running text
            desc = (DESC_RUNNING if d.status == "running"
                    else d.status_description)
            d = replace(d, task_groups=new_states,
                        status_description=desc, modify_index=index)
            root = root.with_table("deployments",
                                   root.table("deployments").set(d.id, d))
            for e in (evals or []):
                root = self._upsert_eval_impl(root, index, e)
            root = root.with_index("deployments", index)
            if evals:
                root = root.with_index("evals", index)
            self._publish(root)

    def update_job_stability(self, index: int, namespace: str, job_id: str,
                             version: int, stable: bool) -> None:
        """Flag a job version (un)stable (state_store.go
        UpdateJobStability) — the auto-revert target marker."""
        with self._lock:
            root = self._root.edit()
            key = (namespace, job_id)
            versions = root.table("job_versions").get(key)
            if versions is not None:
                v = versions.get(version)
                if v is not None:
                    v = v.copy()
                    v.stable = stable
                    root = root.with_table(
                        "job_versions",
                        root.table("job_versions").set(key, versions.set(version, v)))
            current: Optional[Job] = root.table("jobs").get(key)
            if current is not None and current.version == version:
                current = current.copy()
                current.stable = stable
                current.modify_index = index
                root = root.with_table("jobs", root.table("jobs").set(key, current))
            root = root.with_index("jobs", index)
            self._publish(root)

    # -- periodic launches ---------------------------------------------
    def upsert_periodic_launch(self, index: int, namespace: str, job_id: str,
                               launch_time: float) -> None:
        with self._lock:
            root = self._root.edit()
            t = root.table("periodic_launches")
            root = root.with_table("periodic_launches",
                                   t.set((namespace, job_id), launch_time))
            root = root.with_index("periodic_launches", index)
            self._publish(root)

    def delete_periodic_launch(self, index: int, namespace: str,
                               job_id: str) -> None:
        with self._lock:
            root = self._root.edit()
            t = root.table("periodic_launches").delete((namespace, job_id))
            root = root.with_table("periodic_launches", t)
            root = root.with_index("periodic_launches", index)
            self._publish(root)

    # -- deployments GC ------------------------------------------------
    def delete_deployments(self, index: int, deployment_ids: List[str]) -> None:
        with self._lock:
            root = self._root.edit()
            for did in deployment_ids:
                d = root.table("deployments").get(did)
                if d is None:
                    continue
                root = root.with_table("deployments",
                                       root.table("deployments").delete(did))
                root = self._index_del(root, "deployments_by_job",
                                       (d.namespace, d.job_id), did)
            root = root.with_index("deployments", index)
            self._publish(root)

    # -- scaling events (state_store.go UpsertScalingEvent) ------------
    JOB_TRACKED_SCALING_EVENTS = 20

    def add_scaling_event(self, index: int, namespace: str, job_id: str,
                          event: dict) -> None:
        with self._lock:
            root = self._root.edit()
            key = (namespace, job_id)
            events = list(root.table("scaling_events").get(key) or [])
            event = dict(event, create_index=index)
            events.insert(0, event)
            del events[self.JOB_TRACKED_SCALING_EVENTS:]
            root = root.with_table(
                "scaling_events",
                root.table("scaling_events").set(key, events))
            root = root.with_index("scaling_events", index)
            self._publish(root)

    def scaling_events(self, namespace: str, job_id: str) -> List[dict]:
        return list(self._root.table("scaling_events")
                    .get((namespace, job_id)) or [])

    # -- scheduler config ---------------------------------------------
    def set_scheduler_config(self, index: int,
                             config: SchedulerConfiguration) -> None:
        with self._lock:
            config.modify_index = index
            root = self._root.with_table(
                "scheduler_config",
                self._root.table("scheduler_config").set("config", config))
            root = root.with_index("scheduler_config", index)
            self._publish(root)

    # -- ACL (state_store.go ACLPolicy/ACLToken tables) ----------------
    def upsert_acl_policies(self, index: int, policies: List) -> None:
        with self._lock:
            root = self._root.edit()
            t = root.table("acl_policies")
            for p in policies:
                existing = t.get(p.name)
                p.create_index = existing.create_index if existing else index
                p.modify_index = index
                t = t.set(p.name, p)
            root = root.with_table("acl_policies", t) \
                       .with_index("acl_policies", index)
            self._publish(root)

    def delete_acl_policies(self, index: int, names: List[str]) -> None:
        with self._lock:
            root = self._root.edit()
            t = root.table("acl_policies")
            for name in names:
                t = t.delete(name)
            root = root.with_table("acl_policies", t) \
                       .with_index("acl_policies", index)
            self._publish(root)

    # -- namespaces (state_store.go:5565) ------------------------------
    def upsert_namespaces(self, index: int, namespaces: List) -> None:
        with self._lock:
            root = self._root.edit()
            t = root.table("namespaces")
            for ns in namespaces:
                existing = t.get(ns.name)
                ns.create_index = existing.create_index if existing \
                    else index
                ns.modify_index = index
                t = t.set(ns.name, ns)
            root = root.with_table("namespaces", t) \
                       .with_index("namespaces", index)
            self._publish(root)

    def delete_namespaces(self, index: int, names: List[str]) -> None:
        with self._lock:
            root = self._root.edit()
            t = root.table("namespaces")
            for name in names:
                t = t.delete(name)
            root = root.with_table("namespaces", t) \
                       .with_index("namespaces", index)
            self._publish(root)

    # -- service registry (built-in catalog; the reference delegates
    # -- to Consul via command/agent/consul/service_client.go) ---------
    def upsert_service_registrations(self, index: int,
                                     services: List) -> None:
        with self._lock:
            root = self._root.edit()
            t = root.table("service_registrations")
            for s in services:
                existing = t.get(s.id)
                # own the row: in-proc transports hand us the client's
                # LIVE objects, and its check threads keep mutating them
                s = replace(s, tags=list(s.tags), checks=dict(s.checks))
                s.create_index = existing.create_index if existing \
                    else index
                s.modify_index = index
                if existing is not None and \
                        (existing.namespace, existing.service_name) != \
                        (s.namespace, s.service_name):
                    root = self._index_del(
                        root, "services_by_name",
                        (existing.namespace, existing.service_name),
                        s.id)
                t = t.set(s.id, s)
                root = self._index_add(root, "services_by_name",
                                       (s.namespace, s.service_name),
                                       s.id)
                root = self._index_add(root, "services_by_alloc",
                                       s.alloc_id, s.id)
            root = root.with_table("service_registrations", t) \
                       .with_index("service_registrations", index)
            self._publish(root)

    def delete_service_registrations(self, index: int,
                                     ids: Optional[List[str]] = None,
                                     alloc_ids: Optional[List[str]] = None
                                     ) -> None:
        """Remove catalog rows by id and/or every row an alloc owns."""
        with self._lock:
            root = self._root.edit()
            t = root.table("service_registrations")
            doomed = list(ids or [])
            for alloc_id in alloc_ids or []:
                members = root.table("services_by_alloc").get(alloc_id)
                if members is not None:
                    doomed.extend(members.keys())
            changed = False
            for rid in doomed:
                s = t.get(rid)
                if s is None:
                    continue
                t = t.delete(rid)
                root = self._index_del(root, "services_by_name",
                                       (s.namespace, s.service_name),
                                       rid)
                root = self._index_del(root, "services_by_alloc",
                                       s.alloc_id, rid)
                changed = True
            if changed:
                root = root.with_table("service_registrations", t) \
                           .with_index("service_registrations", index)
                self._publish(root)

    def acl_policy(self, name: str):
        return self._root.table("acl_policies").get(name)

    def acl_policies(self) -> List:
        return sorted(self._root.table("acl_policies").values(),
                      key=lambda p: p.name)

    def upsert_acl_tokens(self, index: int, tokens: List) -> None:
        with self._lock:
            root = self._root.edit()
            t = root.table("acl_tokens")
            for tok in tokens:
                existing = t.get(tok.accessor_id)
                tok.create_index = existing.create_index if existing \
                    else index
                tok.modify_index = index
                t = t.set(tok.accessor_id, tok)
                root = root.with_table("acl_tokens", t)
                root = self._index_add(root, "acl_tokens_by_secret",
                                       tok.secret_id, tok.accessor_id)
            root = root.with_table("acl_tokens", t) \
                       .with_index("acl_tokens", index)
            self._publish(root)

    def delete_acl_tokens(self, index: int, accessor_ids: List[str]) -> None:
        with self._lock:
            root = self._root.edit()
            t = root.table("acl_tokens")
            for aid in accessor_ids:
                tok = t.get(aid)
                if tok is None:
                    continue
                t = t.delete(aid)
                root = self._index_del(root, "acl_tokens_by_secret",
                                       tok.secret_id, aid)
            root = root.with_table("acl_tokens", t) \
                       .with_index("acl_tokens", index)
            self._publish(root)

    def acl_token_by_accessor(self, accessor_id: str):
        return self._root.table("acl_tokens").get(accessor_id)

    def acl_token_by_secret(self, secret_id: str):
        members = self._root.table("acl_tokens_by_secret").get(secret_id)
        if not members:
            return None
        for aid in members.keys():
            return self._root.table("acl_tokens").get(aid)
        return None

    def acl_tokens(self) -> List:
        return sorted(self._root.table("acl_tokens").values(),
                      key=lambda t: t.accessor_id)

    # -- Vault accessors (state_store.go UpsertVaultAccessor:5743) -----
    def upsert_vault_accessors(self, index: int, accessors: List) -> None:
        with self._lock:
            root = self._root.edit()
            t = root.table("vault_accessors")
            for a in accessors:
                existing = t.get(a.accessor)
                a.create_index = existing.create_index if existing else index
                a.modify_index = index
                t = t.set(a.accessor, a)
                if existing is None:
                    root = self._index_add(root, "vault_accessors_by_alloc",
                                           a.alloc_id, a.accessor)
                    root = self._index_add(root, "vault_accessors_by_token",
                                           a.token, a.accessor)
            root = root.with_table("vault_accessors", t) \
                       .with_index("vault_accessors", index)
            self._publish(root)

    def delete_vault_accessors(self, index: int,
                               accessor_ids: List[str]) -> None:
        with self._lock:
            root = self._root.edit()
            t = root.table("vault_accessors")
            for aid in accessor_ids:
                a = t.get(aid)
                if a is None:
                    continue
                t = t.delete(aid)
                root = self._index_del(root, "vault_accessors_by_alloc",
                                       a.alloc_id, aid)
                root = self._index_del(root, "vault_accessors_by_token",
                                       a.token, aid)
            root = root.with_table("vault_accessors", t) \
                       .with_index("vault_accessors", index)
            self._publish(root)

    def vault_accessor(self, accessor: str):
        return self._root.table("vault_accessors").get(accessor)

    def vault_accessors(self) -> List:
        return sorted(self._root.table("vault_accessors").values(),
                      key=lambda a: a.accessor)

    def vault_accessors_by_alloc(self, alloc_id: str) -> List:
        """Leases minted for one allocation (state_store.go
        VaultTokenAccessorsByAlloc) — the terminal-alloc revocation
        hot path must not scan the whole lease table."""
        return self._by_index("vault_accessors_by_alloc", alloc_id,
                              "vault_accessors")

    def vault_accessor_by_token(self, token: str):
        ids = self._root.table("vault_accessors_by_token").get(token)
        if not ids:
            return None
        t = self._root.table("vault_accessors")
        for aid in ids.keys():
            return t.get(aid)
        return None

    # -- CSI volumes (state_store.go CSIVolume*) -----------------------
    def upsert_csi_volumes(self, index: int, volumes: List) -> None:
        with self._lock:
            root = self._root.edit()
            t = root.table("csi_volumes")
            for v in volumes:
                existing = t.get((v.namespace, v.id))
                v.create_index = existing.create_index if existing else index
                v.modify_index = index
                t = t.set((v.namespace, v.id), v)
            root = root.with_table("csi_volumes", t) \
                       .with_index("csi_volumes", index)
            self._publish(root)

    def delete_csi_volume(self, index: int, namespace: str,
                          volume_id: str) -> None:
        with self._lock:
            root = self._root.edit()
            t = root.table("csi_volumes").delete((namespace, volume_id))
            root = root.with_table("csi_volumes", t) \
                       .with_index("csi_volumes", index)
            self._publish(root)

    def csi_volume(self, namespace: str, volume_id: str):
        return self._root.table("csi_volumes").get((namespace, volume_id))

    def csi_volumes(self, namespace: Optional[str] = None) -> List:
        vols = list(self._root.table("csi_volumes").values())
        if namespace is not None:
            vols = [v for v in vols if v.namespace == namespace]
        return sorted(vols, key=lambda v: (v.namespace, v.id))

    def _claim_csi_for_placements(self, root: _Root, index: int,
                                  allocs_placed) -> _Root:
        from dataclasses import replace as _replace
        for a in allocs_placed:
            job = a.job or root.table("jobs").get((a.namespace, a.job_id))
            tg = job.lookup_task_group(a.task_group) if job else None
            if tg is None or not tg.volumes:
                continue
            for req in tg.volumes.values():
                if getattr(req, "type", "host") != "csi":
                    continue
                t = root.table("csi_volumes")
                v = t.get((a.namespace, req.source))
                if v is None:
                    continue
                # re-check capacity PER placement against the claims
                # already applied in this batch: a count>1 group (or two
                # groups in one plan) must not exceed a single-writer
                # access mode (csi.go WriteFreeClaims:385 is per-claim)
                read_only = bool(req.read_only)
                if not v.claimable(read_only) and \
                        a.id not in v.write_allocs and \
                        a.id not in v.read_allocs:
                    LOG.warning(
                        "csi claim for alloc %s on volume %s/%s exceeds "
                        "access mode %s; skipping claim", a.id,
                        a.namespace, req.source, v.access_mode)
                    continue
                v = _replace(v, read_allocs=dict(v.read_allocs),
                             write_allocs=dict(v.write_allocs),
                             modify_index=index)
                v.claim(a.id, a.node_id, read_only)
                root = root.with_table(
                    "csi_volumes", t.set((a.namespace, req.source), v))
                root = root.with_index("csi_volumes", index)
        return root

    def csi_volume_claim(self, index: int, namespace: str, volume_id: str,
                         alloc_id: str, node_id: str,
                         read_only: bool) -> None:
        from dataclasses import replace as _replace
        with self._lock:
            root = self._root.edit()
            v = root.table("csi_volumes").get((namespace, volume_id))
            if v is None:
                raise KeyError(f"volume {volume_id} not found")
            v = _replace(v, read_allocs=dict(v.read_allocs),
                         write_allocs=dict(v.write_allocs),
                         modify_index=index)
            v.claim(alloc_id, node_id, read_only)
            root = root.with_table(
                "csi_volumes",
                root.table("csi_volumes").set((namespace, volume_id), v))
            root = root.with_index("csi_volumes", index)
            self._publish(root)

    def csi_volume_release(self, index: int, namespace: str,
                           volume_id: str, alloc_id: str) -> None:
        from dataclasses import replace as _replace
        with self._lock:
            root = self._root.edit()
            v = root.table("csi_volumes").get((namespace, volume_id))
            if v is None:
                return
            v = _replace(v, read_allocs=dict(v.read_allocs),
                         write_allocs=dict(v.write_allocs),
                         modify_index=index)
            if not v.release(alloc_id):
                return
            root = root.with_table(
                "csi_volumes",
                root.table("csi_volumes").set((namespace, volume_id), v))
            root = root.with_index("csi_volumes", index)
            self._publish(root)

    # -- checkpoint / restore (fsm.go Snapshot:1360 / Restore:1374) ----
    def restore(self, data: dict) -> None:
        """Rebuild the database from a dump. Replaces all state. Both
        formats restore here: legacy object snapshots (format 1 — one
        wire dict per row) and columnar format-2 snapshots
        (state/columnar.py struct-of-arrays).

        The big three tables land through the same grouped bulk-index
        path a plan apply uses (one sub-table build per key instead of
        one HAMT write per row), the per-job columnar alloc index is
        rebuilt EAGERLY from the loaded rows — the pre-r12 wholesale
        invalidate made the first eval after recovery pay a dense
        O(allocs) rebuild inside its latency budget — and a columnar
        snapshot leaves its decoded alloc columns on `_cold_columns`
        for the resident NodeTable's vectorized cold build
        (ops/tables.py NodeTable.build_from_columns via
        pop_cold_columns)."""
        from ..models import SchedulerConfiguration
        from ..utils.codec import from_wire
        fmt = int(data.get("format", 1))
        tables = data.get("tables", {})
        cold = None
        if fmt >= 2:
            from .columnar import cold_alloc_columns, decode_table
            cal = data.get("columnar", {})
            dec_allocs = decode_table(Allocation, cal.get("allocs"))
            nodes = decode_table(Node, cal.get("nodes")).objs
            evals = decode_table(Evaluation, cal.get("evals")).objs
            allocs = dec_allocs.objs
            cold = cold_alloc_columns(dec_allocs)
        else:
            nodes = [from_wire(Node, w) for w in tables.get("nodes", [])]
            evals = [from_wire(Evaluation, w)
                     for w in tables.get("evals", [])]
            allocs = [from_wire(Allocation, w)
                      for w in tables.get("allocs", [])]
        with self._lock:
            # invalidate the changelog AND the resident table cache:
            # restore replaces state wholesale, so a cached table at the
            # same numeric index would silently serve pre-restore rows
            self._changes.clear()
            self._change_indexes.clear()
            self._change_floor = max(
                [0] + [int(i) for i in data.get("indexes", {}).values()])
            from ..ops.tables import NodeTableCache
            self.table_cache = NodeTableCache()
            from .alloc_index import AllocIndexCache
            old_ai = self.alloc_index
            self.alloc_index = AllocIndexCache(
                max_jobs=old_ai.max_jobs, delta_max=old_ai.delta_max,
                enabled=old_ai.enabled)
            from .node_attr_index import NodeAttrIndexCache
            self.attr_index = NodeAttrIndexCache(
                enabled=self.attr_index.enabled,
                delta_max=self.attr_index.delta_max)
            root = _Root(_Table(), _Table()).edit()
            if nodes:
                root = root.with_table(
                    "nodes", root.table("nodes").update(
                        [(n.id, n) for n in nodes]))

            t = root.table("jobs")
            for w in tables.get("jobs", []):
                job = from_wire(Job, w)
                t = t.set(job.namespaced_id(), job)
            root = root.with_table("jobs", t)

            t = root.table("job_versions")
            for entry in tables.get("job_versions", []):
                key = tuple(entry["key"])
                versions = _Table()
                for v, w in entry["versions"].items():
                    versions = versions.set(int(v), from_wire(Job, w))
                t = t.set(key, versions)
            root = root.with_table("job_versions", t)

            root = self._bulk_install_evals(root, evals)
            root = self._bulk_install_allocs(root, allocs)

            t = root.table("deployments")
            for w in data["tables"].get("deployments", []):
                d = from_wire(Deployment, w)
                t = t.set(d.id, d)
                root = root.with_table("deployments", t)
                root = self._index_add(root, "deployments_by_job",
                                       (d.namespace, d.job_id), d.id)
                t = root.table("deployments")

            t = root.table("job_summaries")
            for w in data["tables"].get("job_summaries", []):
                s = from_wire(JobSummary, w)
                t = t.set((s.namespace, s.job_id), s)
            root = root.with_table("job_summaries", t)

            t = root.table("periodic_launches")
            for entry in data["tables"].get("periodic_launches", []):
                t = t.set(tuple(entry["key"]), entry["launch_time"])
            root = root.with_table("periodic_launches", t)

            t = root.table("scaling_policies")
            for w in data["tables"].get("scaling_policies", []):
                p = from_wire(ScalingPolicy, w)
                t = t.set(p.id, p)
                root = root.with_table("scaling_policies", t)
                root = self._index_add(
                    root, "scaling_policies_by_job",
                    (p.target.get("Namespace", p.namespace),
                     p.target.get("Job", "")), p.id)
                t = root.table("scaling_policies")
            root = root.with_table("scaling_policies", t)

            members = data["tables"].get("server_members") or []
            if members:
                root = root.with_table(
                    "server_members",
                    root.table("server_members").set("members",
                                                     list(members)))

            from ..server.event_sink import EventSink
            t = root.table("event_sinks")
            for w in data["tables"].get("event_sinks", []):
                s = from_wire(EventSink, w)
                t = t.set(s.id, s)
            root = root.with_table("event_sinks", t)

            t = root.table("scaling_events")
            for entry in data["tables"].get("scaling_events", []):
                t = t.set(tuple(entry["key"]), list(entry["events"]))
            root = root.with_table("scaling_events", t)

            cfg = data["tables"].get("scheduler_config")
            if cfg:
                root = root.with_table(
                    "scheduler_config",
                    root.table("scheduler_config").set(
                        "config", from_wire(SchedulerConfiguration, cfg)))

            from ..models.csi import CSIVolume
            t = root.table("csi_volumes")
            for w in data["tables"].get("csi_volumes", []):
                v = from_wire(CSIVolume, w)
                t = t.set((v.namespace, v.id), v)
            root = root.with_table("csi_volumes", t)

            from ..models.namespace import Namespace
            t = root.table("namespaces")
            for w in data["tables"].get("namespaces", []):
                ns = from_wire(Namespace, w)
                t = t.set(ns.name, ns)
            root = root.with_table("namespaces", t)

            from ..server.vault import VaultAccessor
            t = root.table("vault_accessors")
            for w in data["tables"].get("vault_accessors", []):
                a = from_wire(VaultAccessor, w)
                t = t.set(a.accessor, a)
                root = self._index_add(root, "vault_accessors_by_alloc",
                                       a.alloc_id, a.accessor)
                root = self._index_add(root, "vault_accessors_by_token",
                                       a.token, a.accessor)
            root = root.with_table("vault_accessors", t)

            from ..models.services import ServiceRegistration
            t = root.table("service_registrations")
            for w in data["tables"].get("service_registrations", []):
                s = from_wire(ServiceRegistration, w)
                t = t.set(s.id, s)
                root = root.with_table("service_registrations", t)
                root = self._index_add(root, "services_by_name",
                                       (s.namespace, s.service_name),
                                       s.id)
                root = self._index_add(root, "services_by_alloc",
                                       s.alloc_id, s.id)
                t = root.table("service_registrations")
            root = root.with_table("service_registrations", t)

            from ..acl import AclPolicy, AclToken
            t = root.table("acl_policies")
            for w in data["tables"].get("acl_policies", []):
                p = from_wire(AclPolicy, w)
                t = t.set(p.name, p)
            root = root.with_table("acl_policies", t)
            t = root.table("acl_tokens")
            for w in data["tables"].get("acl_tokens", []):
                tok = from_wire(AclToken, w)
                t = t.set(tok.accessor_id, tok)
                root = root.with_table("acl_tokens", t)
                root = self._index_add(root, "acl_tokens_by_secret",
                                       tok.secret_id, tok.accessor_id)
                t = root.table("acl_tokens")

            for table, index in data.get("indexes", {}).items():
                root = root.with_index(table, index)
            self._publish(root)
            # eager per-job columnar index: the eval that follows
            # recovery reads warm columns, zero dense rebuilds
            if allocs:
                self._prime_alloc_index(allocs, self.index("allocs"))
            self._cold_columns = cold

    def _bulk_install_evals(self, root: _Root, evals: List[Evaluation]
                            ) -> _Root:
        """Restore-grade bulk insert: one outer batch write per table,
        one sub-table build per (namespace, job) — same nested-map
        shape `_index_add` produces row by row."""
        if not evals:
            return root
        root = root.with_table(
            "evals",
            root.table("evals").update([(e.id, e) for e in evals]))
        groups: Dict[Tuple[str, str], List[str]] = {}
        for e in evals:
            groups.setdefault((e.namespace, e.job_id), []).append(e.id)
        t = root.table("evals_by_job")
        pairs = []
        for key, ids in groups.items():
            members = (t.get(key) or _Table()).with_ctx(root._ctx)
            pairs.append((key, members.update(
                [(i, True) for i in ids]).frozen()))
        return root.with_table("evals_by_job", t.update(pairs))

    def _bulk_install_allocs(self, root: _Root,
                             allocs: List[Allocation]) -> _Root:
        """Restore-grade alloc insert: grouped secondary-index builds
        (by node / job / eval) instead of three HAMT writes per row."""
        if not allocs:
            return root
        root = root.with_table(
            "allocs",
            root.table("allocs").update([(a.id, a) for a in allocs]))
        for table, keyfn in (
                ("allocs_by_node", lambda a: a.node_id),
                ("allocs_by_job", lambda a: (a.namespace, a.job_id)),
                ("allocs_by_eval", lambda a: a.eval_id)):
            groups: Dict = {}
            for a in allocs:
                groups.setdefault(keyfn(a), []).append(a.id)
            t = root.table(table)
            pairs = []
            for key, ids in groups.items():
                members = (t.get(key) or _Table()).with_ctx(root._ctx)
                pairs.append((key, members.update(
                    [(i, True) for i in ids]).frozen()))
            root = root.with_table(table, t.update(pairs))
        return root

    def _prime_alloc_index(self, allocs: List[Allocation],
                           index: int) -> None:
        """Rebuild the per-job columnar alloc index eagerly from
        freshly loaded rows (ISSUE 8 satellite: restore used to
        invalidate wholesale, so the eval after recovery paid a dense
        O(allocs) rebuild — `reconcile.index_rebuilds` must stay 0
        after a restore). Bounded by the cache's max_jobs, largest
        jobs first: the entries most expensive to rebuild are the ones
        kept warm."""
        ai = self.alloc_index
        if not ai.enabled:
            return
        from .alloc_index import JobAllocColumns
        groups: Dict[Tuple[str, str], List[Allocation]] = {}
        for a in allocs:
            groups.setdefault((a.namespace, a.job_id), []).append(a)
        keys = sorted(groups, key=lambda k: -len(groups[k]))
        for key in keys[:ai.max_jobs]:
            ai.install(key, JobAllocColumns.build(groups[key]), index)

    def pop_cold_columns(self):
        """One-shot handoff of the last restore's decoded alloc columns
        to the resident-table prime (server/core.py cold-start
        pipeline; None after a legacy-format restore)."""
        cold = getattr(self, "_cold_columns", None)
        self._cold_columns = None
        return cold

    # -- job status reconciliation (fsm setJobStatus analog) ----------
    def set_job_status(self, index: int, namespace: str, job_id: str,
                       status: str, description: str = "") -> None:
        with self._lock:
            root = self._root.edit()
            key = (namespace, job_id)
            job = root.table("jobs").get(key)
            if job is None:
                return
            old_status = job.status
            job = replace(job, status=status, status_description=description,
                          modify_index=index)
            root = root.with_table("jobs", root.table("jobs").set(key, job))
            root = root.with_index("jobs", index)
            if job.parent_id and old_status != status:
                root = self._bump_parent_children(
                    root, index, (namespace, job.parent_id), old_status, status)
            self._publish(root)

    def derive_job_status(self, namespace: str, job_id: str) -> Optional[str]:
        """Compute what a job's status should be from its allocs + evals
        (state_store.go getJobStatus): stop -> dead; any non-terminal
        alloc -> running; any non-terminal eval -> pending; periodic /
        parameterized parents idle at running; else dead once it has
        history, pending when brand new."""
        job = self.job_by_id(namespace, job_id)
        if job is None:
            return None
        if job.stop:
            return JOB_STATUS_DEAD
        allocs = self.allocs_by_job(namespace, job_id)
        for a in allocs:
            if not a.terminal_status():
                return JOB_STATUS_RUNNING
        evals = self.evals_by_job(namespace, job_id)
        has_eval = False
        for e in evals:
            if e.job_id != job_id:
                continue
            has_eval = True
            if not e.terminal_status():
                return JOB_STATUS_PENDING
        if (job.periodic is not None and job.periodic.enabled) or \
                (job.parameterized_job is not None and not job.dispatched):
            return JOB_STATUS_RUNNING
        if allocs or has_eval:
            return JOB_STATUS_DEAD
        return JOB_STATUS_PENDING

    def reconcile_job_status(self, index: int, namespace: str,
                             job_id: str) -> None:
        want = self.derive_job_status(namespace, job_id)
        job = self.job_by_id(namespace, job_id)
        if want is None or job is None or job.status == want:
            return
        self.set_job_status(index, namespace, job_id, want)

    @staticmethod
    def _children_bucket(status: str) -> Optional[str]:
        return {JOB_STATUS_PENDING: "children_pending",
                JOB_STATUS_RUNNING: "children_running",
                JOB_STATUS_DEAD: "children_dead"}.get(status)

    def _bump_parent_children(self, root: _Root, index: int, parent_key,
                              old_status: Optional[str],
                              new_status: Optional[str]) -> _Root:
        """Maintain the parent JobSummary children counters
        (state_store.go setJobSummary children accounting)."""
        summaries = root.table("job_summaries")
        s: Optional[JobSummary] = summaries.get(parent_key)
        if s is None:
            return root
        ob = self._children_bucket(old_status) if old_status else None
        nb = self._children_bucket(new_status) if new_status else None
        if ob == nb:
            return root
        changes = {}
        if ob is not None:
            changes[ob] = max(0, getattr(s, ob) - 1)
        if nb is not None:
            changes[nb] = getattr(s, nb) + 1
        s = replace(s, modify_index=index, **changes)
        return root.with_table("job_summaries", summaries.set(parent_key, s)) \
                   .with_index("job_summaries", index)
