"""Columnar snapshot codec: struct-of-arrays encoding for the store's
big tables (allocs / evals / nodes).

The legacy snapshot format is one wire dict per object — at C2M scale
(2M allocs) restore pays a msgpack decode of 2M small maps plus a
recursive `from_wire` per object, and BENCH_r05 measured the follow-on
dense table build at 20.47 s. This codec turns each table into columns:

  - scalar fields (str/bool/float/None) become ONE msgpack list per
    field — decoded by the msgpack C extension in a single pass;
  - int fields become raw little-endian numpy buffers framed as msgpack
    bin (`np.frombuffer` on decode — no per-value boxing until
    `.tolist()`);
  - nested fields (dataclasses, dicts, lists) become an int32 code
    column into a per-field POOL of unique wire values. Uniqueness is
    identity-first (objects shared before the snapshot stay shared
    after — the C2M seed's flyweight resources row) and then
    content-keyed, so a fleet of equal-but-distinct sub-objects
    (every alloc's DesiredTransition) collapses to ONE `from_wire`
    instead of N.

Decode materializes rows without the recursive `from_wire` walk:
`cls.__new__` + one `__dict__.update` per row from the zipped columns.
This is safe for every model here — none defines `__post_init__`,
`InitVar`, or `__slots__` — and all restored field values went through
the same wire codec the legacy path uses, so round-trip parity with the
object snapshot is testable field for field
(tests/test_cold_start.py).

Sharing contract: pooled sub-objects may be SHARED across rows after a
restore. The store already treats stored objects as immutable
(mutations go through `dataclasses.replace`), and the C2M seed shares
one resources flyweight across millions of allocs by construction, so
this introduces no new hazard class. `task_states` is exempted
(NO_SHARE_FIELDS): client-side task runners mutate those dicts in
place on live objects.
"""

from __future__ import annotations

import dataclasses
import types
import typing
from typing import (Any, Dict, List, Optional, Tuple, get_args, get_origin,
                    get_type_hints)

import numpy as np

from ..utils.codec import from_wire, to_wire

# snapshot file format version: 1 = legacy per-object wire dicts (no
# "format" key), 2 = columnar struct-of-arrays with this codec
SNAPSHOT_FORMAT = 2

# sentinel codes for the two overwhelmingly common "nested" values:
# decoded as a FRESH container per row (mutable-default safety — a
# shared empty dict across 2M allocs would alias task_states)
_EMPTY_DICT = -2
_EMPTY_LIST = -3

# pooled fields that must never share decoded instances across rows
NO_SHARE_FIELDS = frozenset({"task_states"})

_HINTS_CACHE: Dict[type, dict] = {}


def _hints(cls: type) -> dict:
    h = _HINTS_CACHE.get(cls)
    if h is None:
        h = get_type_hints(cls)
        _HINTS_CACHE[cls] = h
    return h


def _freeze(w: Any):
    """Hashable content key for a wire value (dict-order independent).
    NaN floats never compare equal — they simply never dedup."""
    if isinstance(w, dict):
        return tuple(sorted((k, _freeze(v)) for k, v in w.items()))
    if isinstance(w, list):
        return tuple(_freeze(v) for v in w)
    return w


class DecodedTable:
    """Materialized rows plus the raw columns a cold table build can
    feed from (ops/tables.py NodeTable.build_from_columns)."""

    __slots__ = ("objs", "columns", "codes", "pools")

    def __init__(self, objs: List, columns: Dict[str, list],
                 codes: Dict[str, np.ndarray],
                 pools: Dict[str, list]):
        self.objs = objs
        self.columns = columns      # field -> row-aligned value list
        self.codes = codes          # pooled field -> int32 code array
        self.pools = pools          # pooled field -> decoded objects


def encode_table(objs: List) -> dict:
    """Struct-of-arrays encode of one homogeneous object table."""
    n = len(objs)
    if n == 0:
        return {"n": 0, "fields": {}}
    cls = type(objs[0])
    out_fields: Dict[str, dict] = {}
    for f in dataclasses.fields(cls):
        name = f.name
        vals = [getattr(o, name) for o in objs]
        all_int = True
        scalar = True
        for v in vals:
            t = type(v)
            if t is int:
                continue
            all_int = False
            if t is str or t is float or t is bool or v is None:
                continue
            scalar = False
            break
        if all_int:
            col = np.fromiter(vals, np.int64, n)
            out_fields[name] = {"k": "i8", "d": col.tobytes()}
        elif scalar:
            out_fields[name] = {"k": "v", "v": vals}
        else:
            out_fields[name] = _encode_pooled(name, vals)
    return {"n": n, "fields": out_fields}


def _encode_pooled(name: str, vals: list) -> dict:
    pool: List[Any] = []
    codes = np.empty(len(vals), np.int32)
    by_id: Dict[int, int] = {}
    by_key: Dict[Any, int] = {}
    share = name not in NO_SHARE_FIELDS
    for i, v in enumerate(vals):
        if v is None:
            codes[i] = -1
            continue
        tv = type(v)
        if tv is dict and not v:
            codes[i] = _EMPTY_DICT
            continue
        if tv is list and not v:
            codes[i] = _EMPTY_LIST
            continue
        c = by_id.get(id(v))
        if c is None:
            w = to_wire(v)
            if share:
                key = _freeze(w)
                c = by_key.get(key)
                if c is None:
                    c = len(pool)
                    pool.append(w)
                    by_key[key] = c
            else:
                c = len(pool)
                pool.append(w)
            # `vals` pins every object alive for the whole encode, so
            # id() cannot be recycled under the memo
            by_id[id(v)] = c
        codes[i] = c
    return {"k": "p", "c": codes.tobytes(), "p": pool}


def decode_table(cls: type, enc: Optional[dict]) -> DecodedTable:
    """Decode one table: columns first, then one fast materialization
    pass (no recursive from_wire per row — only per unique pool
    entry)."""
    if not enc or not enc.get("n"):
        return DecodedTable([], {}, {}, {})
    n = int(enc["n"])
    hints = _hints(cls)
    columns: Dict[str, list] = {}
    codes_out: Dict[str, np.ndarray] = {}
    pools_out: Dict[str, list] = {}
    for name, c in enc["fields"].items():
        kind = c["k"]
        if kind == "i8":
            columns[name] = np.frombuffer(c["d"], np.int64).tolist()
        elif kind == "v":
            columns[name] = list(c["v"])
        else:
            hint = hints.get(name, Any)
            pool = [from_wire(hint, w) for w in c["p"]]
            codes = np.frombuffer(c["c"], np.int32)
            col: list = [None] * n
            for i, cd in enumerate(codes.tolist()):
                if cd >= 0:
                    col[i] = pool[cd]
                elif cd == _EMPTY_DICT:
                    col[i] = {}
                elif cd == _EMPTY_LIST:
                    col[i] = []
            columns[name] = col
            codes_out[name] = codes
            pools_out[name] = pool

    # fields the dataclass grew AFTER this snapshot was written get
    # their declared defaults (factories called per row)
    names = list(columns.keys())
    colvals = [columns[nm] for nm in names]
    missing: List[Tuple[str, Any, Any]] = []
    for f in dataclasses.fields(cls):
        if f.name in columns:
            continue
        factory = f.default_factory \
            if f.default_factory is not dataclasses.MISSING else None
        default = f.default if f.default is not dataclasses.MISSING \
            else None
        missing.append((f.name, default, factory))

    objs: List = []
    append = objs.append
    new = cls.__new__
    for row in zip(*colvals):
        o = new(cls)
        d = o.__dict__
        d.update(zip(names, row))
        for nm, default, factory in missing:
            d[nm] = factory() if factory is not None else default
        append(o)
    return DecodedTable(objs, columns, codes_out, pools_out)


# -- bulk ingest decode (ISSUE 19) ----------------------------------

class WirePool:
    """Content-keyed decode memo for bulk write bodies: N identical
    nested stanzas across one request batch materialize as ONE shared
    instance instead of N (the snapshot pool idea, applied at
    admission). Safe only for leaf stanza types the write path never
    mutates per row after decode — canonicalize on a shared,
    content-identical instance is deterministic and converges, but
    row-specific mutation targets (constraint lists `_implied_constraints`
    appends to, client-mutated `task_states`) must never pool."""

    __slots__ = ("memo", "hits", "misses")

    def __init__(self):
        self.memo: Dict[Any, Any] = {}
        self.hits = 0
        self.misses = 0


# leaf dataclass types whose decoded instances may be shared across the
# rows of one bulk decode; resolved lazily to dodge the models import
# cycle (models import utils.codec which columnar sits beside)
_POOL_LEAFS: Optional[tuple] = None


def _pool_leafs() -> tuple:
    global _POOL_LEAFS
    if _POOL_LEAFS is None:
        from ..models.resources import Resources
        _POOL_LEAFS = (Resources,)
    return _POOL_LEAFS


def from_wire_pooled(cls: Any, data: Any, pool: WirePool) -> Any:
    """`from_wire` twin for bulk ingest: same dispatch, but whitelisted
    leaf dataclasses memoize by content key so a thousand-job register
    body with one resources shape pays ONE materialization."""
    if data is None:
        return None
    if isinstance(data, dict) and len(data) == 1 and "__b64__" in data:
        return from_wire(cls, data)
    if isinstance(cls, type) and dataclasses.is_dataclass(cls):
        if not isinstance(data, dict):
            return from_wire(cls, data)
        if cls in _pool_leafs():
            key = (cls, _freeze(data))
            hit = pool.memo.get(key)
            if hit is not None:
                pool.hits += 1
                return hit
            obj = from_wire(cls, data)
            pool.memo[key] = obj
            pool.misses += 1
            return obj
        # interior dataclass: recurse per field so nested leaves pool
        hints = _hints(cls)
        names = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: from_wire_pooled(hints.get(k, Any), v, pool)
                  for k, v in data.items() if k in names}
        return cls(**kwargs)
    origin = get_origin(cls)
    if origin in (typing.Union, types.UnionType):
        args = [a for a in get_args(cls) if a is not type(None)]
        return from_wire_pooled(args[0], data, pool) if args else data
    if origin in (list, tuple, set, frozenset) and isinstance(data, list):
        args = get_args(cls)
        elem = args[0] if args else Any
        seq = [from_wire_pooled(elem, v, pool) for v in data]
        return seq if origin is list else origin(seq)
    if origin is dict and isinstance(data, dict):
        args = get_args(cls)
        vt = args[1] if len(args) == 2 else Any
        return {k: from_wire_pooled(vt, v, pool) for k, v in data.items()}
    return from_wire(cls, data)


class ColdAllocColumns:
    """The restore-side feed for the vectorized cold NodeTable build:
    row-aligned alloc objects plus the columns the scatter aggregation
    needs (node ids, liveness, resources pool codes)."""

    __slots__ = ("allocs", "node_ids", "live", "res_codes", "res_pool")

    def __init__(self, allocs: List, node_ids: List[str],
                 live: np.ndarray, res_codes: Optional[np.ndarray],
                 res_pool: List):
        self.allocs = allocs
        self.node_ids = node_ids
        self.live = live
        self.res_codes = res_codes      # None => every row uses pool[-]
        self.res_pool = res_pool


def cold_alloc_columns(dec: DecodedTable) -> Optional[ColdAllocColumns]:
    """Build the cold-build feed from a decoded alloc table, or None
    when the decode lacks the needed columns (legacy restore)."""
    if not dec.objs:
        return None
    from ..models.alloc import (ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_FAILED,
                                ALLOC_CLIENT_LOST, ALLOC_DESIRED_EVICT,
                                ALLOC_DESIRED_STOP)
    terminal_desired = {ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT}
    terminal_client = {ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_FAILED,
                       ALLOC_CLIENT_LOST}
    node_ids = dec.columns.get("node_id")
    desired = dec.columns.get("desired_status")
    client = dec.columns.get("client_status")
    if node_ids is None or desired is None or client is None:
        return None
    n = len(dec.objs)
    live = np.fromiter(
        (d not in terminal_desired and c not in terminal_client
         for d, c in zip(desired, client)), bool, n)
    return ColdAllocColumns(dec.objs, node_ids, live,
                            dec.codes.get("allocated_resources"),
                            dec.pools.get("allocated_resources", []))
