"""Concurrency lint passes: the static half of ISSUE 14.

The original `lock-discipline` pass saw exactly one call deep, so a
blocking call two helpers down — or a lock-order inversion routed
through a helper — was invisible (the r16 `_SHARED_SHARDED` race and
the r17 half-updated heartbeat sample both slipped through exactly
this gap). Three passes now share a project-wide call graph:

  lock-discipline  (interprocedural) `with <lock>` nesting edges PLUS
                   edges discovered by chasing calls made under a held
                   lock through the call graph (depth ``DEPTH``): a
                   helper that acquires a lock, called while another
                   is held, orders those locks. Cross-file cycle
                   detection and blocking-call/dispatch-under-lock run
                   on the expanded graph, with the offending call
                   chain named in the finding.
  shared-state     attributes mutated non-atomically BOTH from code
                   reachable from a `threading.Thread` target and from
                   request/eval paths must share a lock.
                   `# nomad-lint: guarded-by[<lock attr>]` on the
                   attribute's init line declares intent: every
                   non-init mutation must then hold THAT lock. Plain
                   rebinding (`self.x = v`) is a GIL-atomic publish
                   and stays out of the heuristic; AugAssign,
                   subscript stores, and mutator method calls are the
                   read-modify-write shapes that race.
  raw-lock         `threading.Lock/RLock/Condition()` may only be
                   constructed in `utils/locks.py` (and the
                   instrumentation itself) — the factory is what lets
                   `NOMAD_TPU_RACE=1` swap in the runtime shims.

All three report through ctx.finding(), so inline
`# nomad-lint: allow[rule]` suppressions are honored uniformly.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .engine import FileContext, Finding, Project, Rule, attr_chain, \
    call_name

# lock-name heuristics shared with the original pass
_LOCK_SUFFIXES = ("_l", "_lock", "lock", "_cv", "_mu", "_mutex",
                  "_watch", "_cond")

# direct calls that block or dispatch while a lock is held
_DISPATCH_CALLS = ("jax.device_put", "jax.device_get", "time.sleep")
_DISPATCH_SUFFIXES = (".block_until_ready", ".select_many", ".result",
                      ".urlopen")

# call-graph chase depth from a lock-holding call site (tentpole:
# "depth >= 3" — a helper chain of three frames is still seen)
DEPTH = 4

GUARDED_BY_RE = re.compile(
    r"#\s*nomad-lint:\s*guarded-by\[([A-Za-z0-9_.]+)\]")
_COMMENT_ONLY_RE = re.compile(r"^\s*#")


def _is_lock_name(chain: str) -> bool:
    last = chain.split(".")[-1]
    return any(last == s or last.endswith(s) for s in _LOCK_SUFFIXES)


def _is_dispatch_name(name: str) -> bool:
    if name in _DISPATCH_CALLS:
        return True
    return any(name.endswith(s) for s in _DISPATCH_SUFFIXES)


# ---------------------------------------------------------------------
# function summaries + call graph

class _FnInfo:
    """One analyzed function/method: its lock structure and call
    sites, enough for the cross-file passes to chase."""

    __slots__ = ("path", "cls", "name", "node", "ctx", "acquires",
                 "calls", "held_sites", "direct_dispatch")

    def __init__(self, path: str, cls: Optional[str], name: str,
                 node, ctx: FileContext):
        self.path = path
        self.cls = cls
        self.name = name
        self.node = node
        self.ctx = ctx
        self.acquires: Set[str] = set()       # lock ids `with`-taken
        # (held lock ids at site, callee ref or dispatch name, node,
        #  lock ids explicitly .release()d before this site — the
        #  "release the cv around the dispatch" idiom is understood,
        #  not suppressed)
        self.held_sites: List[Tuple[Tuple[str, ...], object,
                                    ast.AST, frozenset]] = []
        self.calls: List[Tuple[object, ast.AST, frozenset]] = []
        # (dispatch name, lock ids released before it) or None
        self.direct_dispatch: Optional[Tuple[str, frozenset]] = None

    @property
    def qualname(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name


def _lock_id(chain: str, cls: Optional[str], path: str) -> str:
    attr = chain.split(".", 1)[1] if "." in chain else chain
    owner = cls if cls is not None and chain.startswith("self.") \
        else path
    return f"{owner}.{attr}"


def _callee_ref(name: str, cls: Optional[str]):
    """Resolvable callee reference for a call-name, or None when the
    target is too dynamic to chase. `self.foo()` resolves by class
    name across files (matching the lock-id convention); bare `foo()`
    resolves to a module-level function in the same file."""
    if name.startswith("self.") and "." not in name[5:]:
        if cls is not None:
            return ("method", cls, name[5:])
        return None
    if "." not in name:
        return ("func", name)
    return None


class _CallGraph:
    """Project-wide index of function summaries, built by the rules'
    check_file passes and queried in finish()."""

    def __init__(self):
        self.fns: List[_FnInfo] = []
        self.by_method: Dict[Tuple[str, str], List[_FnInfo]] = {}
        self.by_func: Dict[Tuple[str, str], List[_FnInfo]] = {}

    def add(self, fn: _FnInfo, resolvable: bool = True) -> None:
        """Nested defs register unresolvable (their bare name is a
        local binding, not a module-level callee) but their own lock
        nesting and dispatch sites still contribute findings."""
        self.fns.append(fn)
        if not resolvable:
            return
        if fn.cls is not None:
            self.by_method.setdefault((fn.cls, fn.name), []).append(fn)
        else:
            self.by_func.setdefault((fn.path, fn.name), []).append(fn)

    def resolve(self, caller: _FnInfo, ref) -> List[_FnInfo]:
        if ref is None:
            return []
        if ref[0] == "method":
            return self.by_method.get((ref[1], ref[2]), [])
        return self.by_func.get((caller.path, ref[1]), [])

    # -- transitive queries (depth-limited, memoized) ------------------
    def reach_locks(self, fn: _FnInfo, depth: int = DEPTH,
                    _memo=None) -> Dict[str, str]:
        """{lock id acquired in fn or its callees within depth: call
        chain that reaches it}."""
        if _memo is None:
            _memo = {}
        key = (id(fn), depth)
        if key in _memo:
            return _memo[key]
        out: Dict[str, str] = {lk: fn.qualname for lk in fn.acquires}
        _memo[key] = out                     # cycle guard
        if depth > 0:
            for ref, _node, _released in fn.calls:
                for callee in self.resolve(fn, ref):
                    for lk, chain in self.reach_locks(
                            callee, depth - 1, _memo).items():
                        out.setdefault(lk, f"{fn.qualname} -> {chain}")
        return out

    def reach_dispatch(self, fn: _FnInfo, depth: int = DEPTH,
                       _memo=None
                       ) -> Optional[Tuple[str, str, frozenset]]:
        """(dispatch call name, chain, lock ids released on the way)
        when fn or a callee within depth performs a device dispatch /
        blocking call. Released locks accumulate along the chain so a
        caller can tell a genuine hold from the release-around-
        dispatch idiom."""
        if _memo is None:
            _memo = {}
        key = (id(fn), depth)
        if key in _memo:
            return _memo[key]
        _memo[key] = None                    # cycle guard
        if fn.direct_dispatch is not None:
            name, released = fn.direct_dispatch
            out = (name, fn.qualname, released)
            _memo[key] = out
            return out
        if depth > 0:
            for ref, _node, released in fn.calls:
                for callee in self.resolve(fn, ref):
                    hit = self.reach_dispatch(callee, depth - 1, _memo)
                    if hit is not None:
                        out = (hit[0], f"{fn.qualname} -> {hit[1]}",
                               released | hit[2])
                        _memo[key] = out
                        return out
        return None


def _summarize_file(ctx: FileContext, graph: _CallGraph) -> None:
    """Walk every top-level function / class method once, recording
    lock structure and call sites into the graph."""
    def walk_fn(fn_node, cls: Optional[str]) -> None:
        info = _FnInfo(ctx.path, cls, fn_node.name, fn_node, ctx)
        held: List[str] = []
        released: Set[str] = set()      # explicit .release() so far

        def visit(node) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn_node:
                return                  # nested defs walk separately
            if isinstance(node, ast.With):
                # each item joins `held` BEFORE the next is examined:
                # `with a, b:` orders a -> b exactly like nested withs
                # (the one-statement inversion is the same deadlock)
                count = 0
                for item in node.items:
                    chain = attr_chain(item.context_expr)
                    if chain and _is_lock_name(chain):
                        lk = _lock_id(chain, cls, ctx.path)
                        info.acquires.add(lk)
                        if held:
                            info.held_sites.append(
                                (tuple(held), ("lock", lk), node,
                                 frozenset(released)))
                        held.append(lk)
                        count += 1
                for child in node.body:
                    visit(child)
                for _ in range(count):
                    held.pop()
                return
            if isinstance(node, ast.Call):
                name = call_name(node) or ""
                base, _, last = name.rpartition(".")
                if last == "release" and base and _is_lock_name(base):
                    released.add(_lock_id(base, cls, ctx.path))
                elif last == "acquire" and base and \
                        _is_lock_name(base):
                    released.discard(_lock_id(base, cls, ctx.path))
                elif _is_dispatch_name(name):
                    if info.direct_dispatch is None:
                        info.direct_dispatch = (name,
                                                frozenset(released))
                    if held:
                        info.held_sites.append(
                            (tuple(held), ("dispatch", name), node,
                             frozenset(released)))
                else:
                    ref = _callee_ref(name, cls)
                    if ref is not None:
                        info.calls.append((ref, node,
                                           frozenset(released)))
                        if held:
                            info.held_sites.append(
                                (tuple(held), ("call", ref, name),
                                 node, frozenset(released)))
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in fn_node.body:
            visit(stmt)
        graph.add(info, resolvable=resolvable)

    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            parent = getattr(node, "_lint_parent", None)
            if isinstance(parent, ast.ClassDef):
                resolvable = True
                walk_fn(node, parent.name)
            elif isinstance(parent, ast.Module):
                resolvable = True
                walk_fn(node, None)
            else:
                # nested def (thread closures, local helpers): still
                # analyzed for its own lock structure, but its bare
                # name never resolves as a callee
                resolvable = False
                cls = ctx.enclosing_class(node)
                walk_fn(node, cls.name if cls is not None else None)


# ---------------------------------------------------------------------
class LockRule(Rule):
    """Pass 4 (interprocedural): lock order + lock scope. Builds the
    lock-acquisition graph from `with <lock>:` nesting AND from calls
    made under a held lock, chased through the project call graph
    (depth DEPTH) — `with self._l: self._refresh()` where _refresh's
    helper's helper acquires another lock or dispatches is now
    visible. Lock identity = Class.attr (or module.attr), so `self._l`
    across methods and files is one node. Flags cycles (the AB/BA
    deadlock shape) and device dispatch / blocking waits reached while
    a lock is held, naming the call chain."""

    name = "lock-discipline"
    doc = ("no lock cycles; no dispatch/blocking call under a lock "
           "(interprocedural)")

    def __init__(self, depth: int = DEPTH):
        self.depth = depth
        self._graph = _CallGraph()
        self._summarized: Set[str] = set()

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.path not in self._summarized:
            self._summarized.add(ctx.path)
            _summarize_file(ctx, self._graph)
        return ()

    def finish(self, project: Project) -> Iterable[Finding]:
        # lock-order edges: (src, dst) -> (ctx, node) of first sighting
        edges: Dict[str, Dict[str, Tuple[FileContext, ast.AST]]] = {}
        lock_memo: dict = {}
        dispatch_memo: dict = {}

        def add_edge(src: str, dst: str, ctx: FileContext,
                     node) -> None:
            if src == dst:
                return
            dsts = edges.setdefault(src, {})
            if dst not in dsts:
                dsts[dst] = (ctx, node)

        for fn in self._graph.fns:
            for held, what, node, released in fn.held_sites:
                still_held = [l for l in held if l not in released]
                if what[0] == "lock":
                    for outer in still_held:
                        add_edge(outer, what[1], fn.ctx, node)
                elif what[0] == "dispatch":
                    if not still_held:
                        continue        # release-around-dispatch
                    yield fn.ctx.finding(
                        self.name, node,
                        f"`{what[1]}` under lock {still_held[-1]}: "
                        f"device dispatch / blocking call while "
                        f"holding a lock serializes every other "
                        f"acquirer behind the device round trip")
                else:                       # ("call", ref, name)
                    _tag, ref, cname = what
                    reported = False
                    for callee in self._graph.resolve(fn, ref):
                        hit = self._graph.reach_dispatch(
                            callee, self.depth - 1, dispatch_memo)
                        if hit is not None and not reported:
                            gone = released | hit[2]
                            live = [l for l in held if l not in gone]
                            if live:
                                reported = True
                                yield fn.ctx.finding(
                                    self.name, node,
                                    f"`{cname}()` under lock "
                                    f"{live[-1]} reaches `{hit[0]}` "
                                    f"(via {hit[1]}): device dispatch"
                                    f" / blocking call while holding "
                                    f"a lock")
                        for lk, chain in self._graph.reach_locks(
                                callee, self.depth - 1,
                                lock_memo).items():
                            for outer in still_held:
                                add_edge(outer, lk, fn.ctx, node)

        yield from self._cycles(edges)

    def _cycles(self, edges) -> Iterable[Finding]:
        seen_cycles: Set[frozenset] = set()
        for start in sorted(edges):
            path: List[str] = []
            on_path: Set[str] = set()
            visited: Set[str] = set()

            def dfs(node: str) -> Optional[List[str]]:
                if node in on_path:
                    return path[path.index(node):] + [node]
                if node in visited:
                    return None
                visited.add(node)
                on_path.add(node)
                path.append(node)
                for nxt in sorted(edges.get(node, {})):
                    cyc = dfs(nxt)
                    if cyc is not None:
                        return cyc
                path.pop()
                on_path.discard(node)
                return None

            cyc = dfs(start)
            if cyc is None:
                continue
            key = frozenset(cyc)
            if key in seen_cycles:
                continue
            seen_cycles.add(key)
            a, b = cyc[0], cyc[1]
            ctx, node = edges[a][b]
            yield ctx.finding(
                self.name, node,
                f"lock-order cycle: {' -> '.join(cyc)} — two threads "
                f"taking these in opposite order deadlock")


# ---------------------------------------------------------------------
class SharedStateRule(Rule):
    """Pass 6: shared mutable state. For every class that owns a
    `threading.Thread` target, attributes mutated NON-ATOMICALLY both
    from thread-reachable code and from other (request/eval) methods
    must share a lock. `# nomad-lint: guarded-by[<lock attr>]` on the
    attribute's initialization line declares the guarding lock; all
    non-__init__ mutations must then hold it. Plain attribute
    rebinding is a GIL-atomic publish and is exempt from the
    heuristic pairing (but NOT from a declared guarded-by)."""

    name = "shared-state"
    doc = ("thread-shared attrs need a common lock; guarded-by[...] "
           "declares and enforces intent")

    # lifecycle methods whose mutations happen-before/after the thread
    LIFECYCLE = ("__init__", "__post_init__")

    MUTATOR_METHODS = {
        "append", "extend", "insert", "remove", "clear", "update",
        "setdefault", "popitem", "appendleft", "add", "discard",
        "rotate",
    }

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        guarded = self._guarded_decls(ctx)
        for node in ctx.tree.body:
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(ctx, node, guarded)

    # -- guarded-by declarations ---------------------------------------
    @staticmethod
    def _guarded_decls(ctx: FileContext) -> Dict[int, str]:
        """{1-based line: lock attr} — a comment-only guarded-by line
        covers the next line (same convention as allow[])."""
        out: Dict[int, str] = {}
        for i, raw in enumerate(ctx.lines, start=1):
            m = GUARDED_BY_RE.search(raw)
            if not m:
                continue
            lock = m.group(1)
            if lock.startswith("self."):
                lock = lock[5:]
            out[i] = lock
            if _COMMENT_ONLY_RE.match(raw):
                out[i + 1] = lock
        return out

    # -- per-class analysis --------------------------------------------
    def _check_class(self, ctx: FileContext, cls: ast.ClassDef,
                     guarded_lines: Dict[int, str]
                     ) -> Iterable[Finding]:
        methods = {n.name: n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        if not methods:
            return
        summaries = {name: self._summarize_method(node)
                     for name, node in methods.items()}

        thread_targets = set()
        for s in summaries.values():
            thread_targets |= s["thread_targets"]
        guarded_attrs: Dict[str, str] = {}
        lockish_attrs: Set[str] = set()
        for s in summaries.values():
            for attr, line in s["inits"]:
                if line in guarded_lines:
                    guarded_attrs[attr] = guarded_lines[line]
            lockish_attrs |= s["lock_attrs"]

        if not thread_targets and not guarded_attrs:
            return

        # thread-reachable closure over the intra-class call graph
        reachable = set(t for t in thread_targets if t in methods)
        frontier = list(reachable)
        while frontier:
            m = frontier.pop()
            for callee in summaries[m]["calls"]:
                if callee in methods and callee not in reachable:
                    reachable.add(callee)
                    frontier.append(callee)

        entry_held = self._entry_held(methods, summaries,
                                      thread_targets)

        # collect mutation sites per attr with effective held sets
        sites: Dict[str, List[dict]] = {}
        for mname, s in summaries.items():
            base = entry_held.get(mname, frozenset())
            for attr, node, held, atomic in s["mutations"]:
                sites.setdefault(attr, []).append({
                    "method": mname, "node": node,
                    "held": frozenset(held) | base,
                    "atomic": atomic,
                    "lifecycle": mname in self.LIFECYCLE,
                })

        # 1) declared guarded-by attrs: every non-lifecycle mutation
        #    must hold the declared lock
        for attr, lock in sorted(guarded_attrs.items()):
            for site in sites.get(attr, []):
                if site["lifecycle"]:
                    continue
                if lock not in site["held"]:
                    held_txt = ", ".join(sorted(site["held"])) \
                        or "no lock"
                    yield ctx.finding(
                        self.name, site["node"],
                        f"{cls.name}.{attr} is declared guarded-by"
                        f"[{lock}] but this mutation in "
                        f"`{site['method']}` holds {held_txt}")

        # 2) heuristic: undeclared attrs mutated non-atomically from
        #    both sides of the thread boundary need a common lock
        if not reachable:
            return
        for attr, slist in sorted(sites.items()):
            if attr in guarded_attrs or attr in lockish_attrs \
                    or _is_lock_name(attr):
                continue
            live = [s for s in slist
                    if not s["lifecycle"] and not s["atomic"]]
            th = [s for s in live if s["method"] in reachable]
            rq = [s for s in live if s["method"] not in reachable]
            if not th or not rq:
                continue
            common = frozenset.intersection(
                *[s["held"] for s in live])
            if common:
                continue
            worst = min(live, key=lambda s: len(s["held"]))
            held_txt = ", ".join(sorted(worst["held"])) or "no lock"
            yield ctx.finding(
                self.name, worst["node"],
                f"{cls.name}.{attr} is mutated from thread-reachable "
                f"`{'/'.join(sorted({s['method'] for s in th}))}` and "
                f"from `{'/'.join(sorted({s['method'] for s in rq}))}`"
                f" with no common lock (this site holds {held_txt}) — "
                f"take one lock on both sides or declare "
                f"`# nomad-lint: guarded-by[<lock>]` on the attr's "
                f"init line")

    # -- method summaries ----------------------------------------------
    def _summarize_method(self, fn) -> dict:
        out = {"thread_targets": set(), "calls": set(),
               "mutations": [],     # (attr, node, held set, atomic)
               "inits": [],         # (attr, lineno) for Assign targets
               "lock_attrs": set(),
               "call_sites": []}    # (callee, held set)
        held: List[str] = []

        def self_attr(node) -> Optional[str]:
            if isinstance(node, ast.Attribute) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == "self":
                return node.attr
            return None

        def thread_target(call: ast.Call) -> None:
            name = call_name(call) or ""
            if not name.endswith("Thread") and \
                    not name.endswith("Timer"):
                return
            # Thread's target and Timer's function arrive by keyword OR
            # positionally (both sit at arg index 1, after group /
            # interval) — every in-tree Timer passes its callback
            # positionally
            cands = [kw.value for kw in call.keywords
                     if kw.arg in ("target", "function")]
            if len(call.args) > 1:
                cands.append(call.args[1])
            for tgt in cands:
                if isinstance(tgt, ast.Lambda):
                    for sub in ast.walk(tgt.body):
                        if isinstance(sub, ast.Call):
                            cn = call_name(sub) or ""
                            if cn.startswith("self."):
                                out["thread_targets"].add(cn[5:])
                    continue
                chain = attr_chain(tgt) or ""
                if chain.startswith("self."):
                    out["thread_targets"].add(chain[5:])

        def visit(node) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                return
            if isinstance(node, ast.With):
                locks = []
                for item in node.items:
                    chain = attr_chain(item.context_expr)
                    if chain and chain.startswith("self.") and \
                            _is_lock_name(chain):
                        locks.append(chain[5:])
                held.extend(locks)
                for child in node.body:
                    visit(child)
                for _ in locks:
                    held.pop()
                return
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    attr = self_attr(t)
                    if attr is not None:
                        out["inits"].append((attr, node.lineno))
                        out["mutations"].append(
                            (attr, node, tuple(held), True))
                        v = node.value
                        if isinstance(v, ast.Call):
                            vn = call_name(v) or ""
                            if vn.split(".")[-1] in (
                                    "Lock", "RLock", "Condition",
                                    "make_lock", "make_rlock",
                                    "make_condition"):
                                out["lock_attrs"].add(attr)
                    elif isinstance(t, ast.Subscript):
                        attr = self_attr(t.value)
                        if attr is not None:
                            out["mutations"].append(
                                (attr, node, tuple(held), False))
            elif isinstance(node, ast.AnnAssign) and \
                    node.value is not None:
                attr = self_attr(node.target)
                if attr is not None:
                    out["inits"].append((attr, node.lineno))
                    out["mutations"].append(
                        (attr, node, tuple(held), True))
            elif isinstance(node, ast.AugAssign):
                attr = self_attr(node.target)
                if attr is not None:
                    out["mutations"].append(
                        (attr, node, tuple(held), False))
                elif isinstance(node.target, ast.Subscript):
                    attr = self_attr(node.target.value)
                    if attr is not None:
                        out["mutations"].append(
                            (attr, node, tuple(held), False))
            elif isinstance(node, (ast.Delete,)):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        attr = self_attr(t.value)
                        if attr is not None:
                            out["mutations"].append(
                                (attr, node, tuple(held), False))
            elif isinstance(node, ast.Call):
                thread_target(node)
                name = call_name(node) or ""
                if name.startswith("self."):
                    rest = name[5:]
                    parts = rest.split(".")
                    if len(parts) == 1:
                        out["calls"].add(parts[0])
                        out["call_sites"].append(
                            (parts[0], tuple(held)))
                    elif len(parts) == 2 and \
                            parts[1] in self.MUTATOR_METHODS:
                        out["mutations"].append(
                            (parts[0], node, tuple(held), False))
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in fn.body:
            visit(stmt)
        return out

    # -- entry-held dataflow -------------------------------------------
    def _entry_held(self, methods, summaries,
                    thread_targets) -> Dict[str, frozenset]:
        """Locks PROVABLY held on entry to each method: the
        intersection over every intra-class call site of (locks held
        at the site + the caller's own entry-held set). Public
        methods and thread entries are outside entry points with
        nothing held; private helpers called only under a lock
        inherit it —
        `with self._l: self._store()` credits _store's mutations."""
        call_sites: Dict[str, List[Tuple[str, frozenset]]] = {}
        for caller, s in summaries.items():
            for callee, held in s["call_sites"]:
                if callee in methods:
                    call_sites.setdefault(callee, []).append(
                        (caller, frozenset(held)))

        entry: Dict[str, frozenset] = {}
        all_locks = frozenset()
        for s in summaries.values():
            for _attr, _node, held, _atomic in s["mutations"]:
                all_locks |= frozenset(held)
            for _callee, held in s["call_sites"]:
                all_locks |= frozenset(held)
        for name in methods:
            is_entry = (name in thread_targets
                        or not name.startswith("_")
                        or name not in call_sites)
            entry[name] = frozenset() if is_entry else all_locks
        for _ in range(len(methods) + 1):
            changed = False
            for name in methods:
                if not entry[name]:
                    continue
                sites = call_sites.get(name, ())
                new = frozenset.intersection(*[
                    held | entry[caller] for caller, held in sites]) \
                    if sites else frozenset()
                new &= entry[name]
                if new != entry[name]:
                    entry[name] = new
                    changed = True
            if not changed:
                break
        return entry


# ---------------------------------------------------------------------
class RawLockRule(Rule):
    """Pass 7: lock construction goes through the factory. A raw
    `threading.Lock()` outside `utils/locks.py` is invisible to the
    `NOMAD_TPU_RACE=1` shims — the whole runtime sanitizer hinges on
    every mutex being born in one place."""

    name = "raw-lock"
    doc = "threading.Lock/RLock/Condition only via utils/locks.py"

    FACTORY = "nomad_tpu/utils/locks.py"
    ALLOWED = (FACTORY, "nomad_tpu/analysis/race.py")
    PRIMITIVES = ("Lock", "RLock", "Condition")

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.path in self.ALLOWED or \
                not ctx.path.startswith("nomad_tpu/"):
            return
        aliases = {"threading"}
        direct: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "threading":
                        aliases.add(a.asname or "threading")
            elif isinstance(node, ast.ImportFrom):
                if node.module == "threading":
                    for a in node.names:
                        if a.name in self.PRIMITIVES:
                            direct.add(a.asname or a.name)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node) or ""
            hit = None
            if "." in name:
                base, _, last = name.rpartition(".")
                if base in aliases and last in self.PRIMITIVES:
                    hit = last
            elif name in direct:
                hit = name
            if hit:
                factory = {"Lock": "make_lock", "RLock": "make_rlock",
                           "Condition": "make_condition"}[hit]
                yield ctx.finding(
                    self.name, node,
                    f"raw threading.{hit}() — construct through "
                    f"utils/locks.{factory}() so NOMAD_TPU_RACE=1 "
                    f"can instrument it")
