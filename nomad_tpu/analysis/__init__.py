"""TPU-hygiene static analysis + runtime sanitizers.

Static: `python -m nomad_tpu.analysis [paths]` / `nomad-tpu dev lint`
runs seven AST passes (engine.py, passes.py, concurrency.py) enforcing
the steady-state invariants — host-sync discipline, jit hygiene, dtype
discipline, interprocedural lock order/scope, thread-shared state
guarding, factory-only lock construction, surface drift — with inline
`# nomad-lint: allow[rule]` suppressions and non-zero exit on
findings.

Runtime: `NOMAD_TPU_SANITIZE=1` (sanitizer.py) adds NaN/Inf and
out-of-bounds-row guards at the placement and scatter-delta kernel
boundaries, and the always-on trace-signature counter feeds the
`nomad.lint.recompiles` governor gauge. `NOMAD_TPU_RACE=1` (race.py,
via the utils/locks.py factory) swaps every lock for instrumented
shims: acquisition-order deadlock detection, hold/contention
accounting behind the governor's `lock.*` gauges, and
guarded-structure mutation checks.
"""

from .engine import FileContext, Finding, Project, Rule, run
from .concurrency import LockRule, RawLockRule, SharedStateRule
from .passes import (DtypeRule, HostSyncRule, JitHygieneRule,
                     SurfaceDriftRule, default_rules)
from . import race, sanitizer

__all__ = [
    "FileContext", "Finding", "Project", "Rule", "run",
    "HostSyncRule", "JitHygieneRule", "DtypeRule", "LockRule",
    "SharedStateRule", "RawLockRule", "SurfaceDriftRule",
    "default_rules", "race", "sanitizer",
]
