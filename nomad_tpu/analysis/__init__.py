"""TPU-hygiene static analysis + runtime sanitizer.

Static: `python -m nomad_tpu.analysis [paths]` / `nomad-tpu dev lint`
runs five AST passes (engine.py, passes.py) enforcing the steady-state
invariants — host-sync discipline, jit hygiene, dtype discipline,
lock order/scope, surface drift — with inline
`# nomad-lint: allow[rule]` suppressions and non-zero exit on
findings.

Runtime: `NOMAD_TPU_SANITIZE=1` (sanitizer.py) adds NaN/Inf and
out-of-bounds-row guards at the placement and scatter-delta kernel
boundaries, and the always-on trace-signature counter feeds the
`nomad.lint.recompiles` governor gauge.
"""

from .engine import FileContext, Finding, Project, Rule, run
from .passes import (DtypeRule, HostSyncRule, JitHygieneRule, LockRule,
                     SurfaceDriftRule, default_rules)
from . import sanitizer

__all__ = [
    "FileContext", "Finding", "Project", "Rule", "run",
    "HostSyncRule", "JitHygieneRule", "DtypeRule", "LockRule",
    "SurfaceDriftRule", "default_rules", "sanitizer",
]
