"""Runtime deadlock & race sanitizer (`NOMAD_TPU_RACE=1`).

The static concurrency passes (analysis/concurrency.py) prove lock
DISCIPLINE at review time; this module watches the lock TRAFFIC of a
live process. When the env switch is armed, `utils/locks.py` — the one
construction point the raw-lock lint rule enforces — hands out
instrumented shims instead of raw `threading` primitives:

  order graph     every first-time (held, acquired) lock pair becomes
                  an edge in a process-global acquisition-order graph,
                  keyed by CONSTRUCTION SITE (all instances born at
                  eval_broker.py:97 are one node, the lockdep
                  convention). A new edge that closes a cycle is a
                  potential-deadlock finding carrying BOTH stacks: the
                  one that just took the locks in this order and the
                  recorded stack of the reversed edge.
  hold/contention every acquire records wait-time when it contended;
                  every release records hold-time. Holds beyond
                  `race_lock_hold_warn_ms` keep a worst-K exemplar
                  (stack at release — the code that sat on the lock),
                  surfaced as `lock.*` governor gauges and the `locks`
                  block of /v1/operator/governor.
  guarded structs `guard(obj, lock, name)` wraps a dict/list so every
                  mutating method checks the declaring lock is held by
                  the current thread — a lock-free mutation of a
                  structure the code PROMISED to guard is a finding
                  with the mutating stack (the dynamic half of the
                  static pass's `# nomad-lint: guarded-by[...]`).

Findings are deliberately few in kind (lock-order cycle, self
deadlock, unguarded mutation) and zero in a healthy tree: the race
ratchet (tests/test_race_ratchet.py) replays the concurrency-heavy
suites under `NOMAD_TPU_RACE=1` and asserts no unsuppressed finding
survives. `NOMAD_TPU_RACE_REPORT=<path>` dumps findings + stats as
JSON at interpreter exit so that subprocess ratchet can read them.

This module uses raw `threading` primitives by design (it IS the
instrumentation) and is allowlisted by the raw-lock rule.
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading
import time
import traceback
import weakref
from collections import deque
from typing import Dict, List, Optional, Tuple

ENV = "NOMAD_TPU_RACE"
REPORT_ENV = "NOMAD_TPU_RACE_REPORT"

STACK_LIMIT = 14        # frames kept per captured stack


def enabled() -> bool:
    """Read live (the sanitizer.enabled idiom) — but note the shims
    only exist for locks CONSTRUCTED while this was true; flipping the
    env mid-process instruments nothing retroactively. Delegates to
    the factory's predicate so the two can never disagree."""
    from ..utils.locks import _race_on
    return _race_on()


def _stack(skip: int = 2) -> str:
    try:
        frames = traceback.format_stack(sys._getframe(skip),
                                        limit=STACK_LIMIT)
    except ValueError:          # shallower than skip
        frames = traceback.format_stack(limit=STACK_LIMIT)
    return "".join(frames)


# Known-benign lock-order cycles, keyed by frozenset of construction-
# site names, each with a justification (audited like the static
# passes' allow[] comments). Findings matching an entry are recorded
# suppressed — the ratchet asserts on UNsuppressed findings only.
SUPPRESSED_CYCLES: Dict[frozenset, str] = {
}


class RaceMonitor:
    """Process-global bookkeeping behind the shims. Per-thread state
    (the held-lock stack, the seen-edge cache) lives in a
    threading.local so the steady-state acquire path never takes the
    monitor's own lock; the global structures (order graph, findings,
    exemplars) are touched only on first-time edges, warn-threshold
    holds, and findings — all rare by construction."""

    def __init__(self):
        self._l = threading.Lock()
        self._tls = threading.local()
        # order graph: src name -> {dst name: (stack, thread name)}
        self._graph: Dict[str, Dict[str, Tuple[str, str]]] = {}
        self._findings: List[dict] = []
        self._finding_keys: set = set()
        self._cycles_seen: set = set()
        self._exemplars: List[dict] = []    # worst-K holds, desc
        self._locks: "weakref.WeakSet" = weakref.WeakSet()
        # counters folded in from GC'd locks (per-connection wlocks,
        # per-drain lane locks, per-election tallies): the lock.*
        # gauges are sums over live locks PLUS these, so they stay
        # monotone when short-lived locks die — a delta-based rate
        # over the telemetry ring must never go negative
        self._dead_totals: Dict[str, dict] = {}
        # lock-free __del__ inbox, drained on every lock registration
        # (the churn that fills it also drains it) and on every gauge
        # read; bounded as a backstop for a process that somehow stops
        # constructing locks but keeps collecting them
        self._dead_q: deque = deque(maxlen=65536)
        self._report_hooked = False
        # knobs (ServerConfig.race_* via configure(); defaults match)
        self.hold_warn_ms: float = 50.0
        self.exemplar_slots: int = 8
        self.max_findings: int = 256
        self.suppressed_cycles: Dict[frozenset, str] = \
            dict(SUPPRESSED_CYCLES)

    # -- configuration -------------------------------------------------
    def configure(self, hold_warn_ms: Optional[float] = None,
                  exemplar_slots: Optional[int] = None,
                  max_findings: Optional[int] = None) -> None:
        if hold_warn_ms is not None:
            self.hold_warn_ms = float(hold_warn_ms)
        if exemplar_slots is not None:
            self.exemplar_slots = int(exemplar_slots)
        if max_findings is not None:
            self.max_findings = int(max_findings)

    # -- per-thread state ----------------------------------------------
    def _tl(self):
        tl = self._tls
        try:
            tl.held
        except AttributeError:
            tl.held = []                # InstrumentedLock stack
            tl.seen_edges = set()       # (src name, dst name) cache
        return tl

    def _note_edges(self, lock: "InstrumentedLock", ident: int,
                    held: list, tls) -> None:
        """Nested-acquire bookkeeping (held non-empty — the rarer
        case, so the flat-acquire fast path in InstrumentedLock never
        pays this call): prune entries a foreign thread released out
        from under us, then record first-time order edges."""
        stale = False
        for l in held:
            if l._owner != ident:
                stale = True
                break
        if stale:
            held[:] = [l for l in held if l._owner == ident]
        seen = tls.seen_edges
        for outer in held:
            if outer is lock:
                continue
            pair = (outer.name, lock.name)
            if pair in seen:
                continue
            seen.add(pair)
            self._add_edge(pair, outer, lock)

    # -- registration --------------------------------------------------
    def register_lock(self, lock: "InstrumentedLock") -> None:
        with self._l:
            self._drain_dead()
            self._locks.add(lock)
        self.ensure_report_hook()

    def fold_dead_lock(self, name: str, acquires: int, contended: int,
                       wait_s: float, hold_s: float, max_hold_ms: float,
                       hold_warns: int) -> None:
        """Called from InstrumentedLock.__del__ — which GC can fire on
        ANY thread at ANY allocation, including while THIS monitor's
        lock is held by the same thread. So the __del__ path must be
        lock-free: append to an atomic deque; readers drain it into
        _dead_totals under the lock."""
        self._dead_q.append((name, acquires, contended, wait_s,
                             hold_s, max_hold_ms, hold_warns))

    def _drain_dead(self) -> None:
        """Fold queued dead-lock counters (caller holds self._l)."""
        while True:
            try:
                (name, acquires, contended, wait_s, hold_s,
                 max_hold_ms, hold_warns) = self._dead_q.popleft()
            except IndexError:
                return
            row = self._dead_totals.setdefault(name, {
                "instances": 0, "acquires": 0, "contended": 0,
                "wait_ms": 0.0, "hold_ms": 0.0, "max_hold_ms": 0.0,
                "hold_warns": 0})
            row["instances"] += 1
            row["acquires"] += acquires
            row["contended"] += contended
            row["wait_ms"] += wait_s * 1000.0
            row["hold_ms"] += hold_s * 1000.0
            row["max_hold_ms"] = max(row["max_hold_ms"], max_hold_ms)
            row["hold_warns"] += hold_warns

    def ensure_report_hook(self) -> None:
        if self._report_hooked or not os.environ.get(REPORT_ENV):
            return
        with self._l:
            if self._report_hooked:
                return
            self._report_hooked = True
        atexit.register(self._write_report)

    # -- acquire/release hooks (the condition sleep/wake path; the
    # lock fast path inlines equivalent bookkeeping) -------------------
    def on_acquired(self, lock: "InstrumentedLock",
                    reacquire: bool = False) -> None:
        tl = self._tl()
        held = tl.held
        if held:
            self._note_edges(lock, threading.get_ident(), held, tl)
        held.append(lock)

    def on_released(self, lock: "InstrumentedLock",
                    hold_s: float) -> None:
        tl = self._tl()
        try:
            tl.held.remove(lock)
        except ValueError:
            pass                        # cross-thread release
        hold_ms = hold_s * 1000.0
        if hold_ms >= self.hold_warn_ms:
            lock.hold_warns += 1
            self._note_exemplar(lock, hold_ms)

    def note_self_deadlock(self, lock: "InstrumentedLock") -> None:
        """A non-reentrant lock re-acquired by its owner thread: the
        raw primitive would hang here forever. Record the finding with
        the stack BEFORE we block exactly like the raw lock would."""
        self._finding({
            "kind": "self-deadlock",
            "lock": lock.name,
            "thread": threading.current_thread().name,
            "stack": _stack(3),
        }, key=("self", lock.name))

    def note_unguarded_mutation(self, name: str, lock_name: str,
                                op: str) -> None:
        self._finding({
            "kind": "unguarded-mutation",
            "structure": name,
            "lock": lock_name,
            "op": op,
            "thread": threading.current_thread().name,
            "stack": _stack(4),
        }, key=("mut", name, op))

    # -- order graph ---------------------------------------------------
    def _add_edge(self, pair: Tuple[str, str],
                  outer: "InstrumentedLock",
                  inner: "InstrumentedLock") -> None:
        src, dst = pair
        stack = _stack(4)
        tname = threading.current_thread().name
        with self._l:
            dsts = self._graph.setdefault(src, {})
            if dst not in dsts:
                dsts[dst] = (stack, tname)
            cycle = self._find_cycle(dst, src)
        if src == dst:
            # same construction site, different instances, nested:
            # peer locks with no global order — the classic
            # unordered-neighbor deadlock
            self._cycle_finding([src, dst], stack, tname,
                                note="same-site peer instances nested")
            return
        if cycle is not None:
            self._cycle_finding([src] + cycle, stack, tname)

    def _find_cycle(self, start: str, goal: str
                    ) -> Optional[List[str]]:
        """Path start -> ... -> goal in the order graph (caller holds
        self._l). Returns the node list or None."""
        if start == goal:
            return [start]
        seen = {start}
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in self._graph.get(node, {}):
                if nxt == goal:
                    return path + [goal]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def _cycle_finding(self, cycle: List[str], stack: str,
                       tname: str, note: str = "") -> None:
        key = frozenset(cycle)
        with self._l:
            if key in self._cycles_seen:
                return
            self._cycles_seen.add(key)
            suppressed_why = self.suppressed_cycles.get(key)
            other = {}
            for a, b in zip(cycle[1:], cycle[2:] + cycle[:1]):
                info = self._graph.get(a, {}).get(b)
                if info is not None:
                    other[f"{a} -> {b}"] = {"stack": info[0],
                                            "thread": info[1]}
        self._finding({
            "kind": "lock-order-cycle",
            "cycle": cycle,
            "note": note,
            "thread": tname,
            "stack": stack,
            "other_stacks": other,
            "suppressed_why": suppressed_why,
        }, key=("cycle", key), suppressed=suppressed_why is not None)

    # -- findings / exemplars ------------------------------------------
    def _finding(self, payload: dict, key=None,
                 suppressed: bool = False) -> None:
        payload.setdefault("t", time.time())
        payload["suppressed"] = suppressed
        with self._l:
            if key is not None:
                if key in self._finding_keys:
                    return
                self._finding_keys.add(key)
            if len(self._findings) < self.max_findings:
                self._findings.append(payload)

    def _note_exemplar(self, lock: "InstrumentedLock",
                       hold_ms: float) -> None:
        ex = {"lock": lock.name, "hold_ms": round(hold_ms, 3),
              "thread": threading.current_thread().name,
              "t": time.time(), "stack": _stack(4)}
        with self._l:
            self._exemplars.append(ex)
            self._exemplars.sort(key=lambda e: -e["hold_ms"])
            del self._exemplars[self.exemplar_slots:]

    # -- reads ---------------------------------------------------------
    def findings(self, include_suppressed: bool = True) -> List[dict]:
        with self._l:
            out = list(self._findings)
        if not include_suppressed:
            out = [f for f in out if not f.get("suppressed")]
        return out

    def unsuppressed_count(self) -> int:
        return len(self.findings(include_suppressed=False))

    def tracked_locks(self) -> int:
        with self._l:
            return len(self._locks)

    def edge_count(self) -> int:
        with self._l:
            return sum(len(d) for d in self._graph.values())

    def _lock_rows(self) -> List[dict]:
        with self._l:
            self._drain_dead()
            locks = list(self._locks)
            dead = {name: dict(row)
                    for name, row in self._dead_totals.items()}
        agg: Dict[str, dict] = {}
        for name, row in dead.items():
            agg[name] = dict(row, name=name)
        for lk in locks:
            row = agg.setdefault(lk.name, {
                "name": lk.name, "instances": 0, "acquires": 0,
                "contended": 0, "wait_ms": 0.0, "hold_ms": 0.0,
                "max_hold_ms": 0.0, "hold_warns": 0})
            row["instances"] += 1
            row["acquires"] += lk.acquires
            row["contended"] += lk.contended
            row["wait_ms"] += lk.wait_s * 1000.0
            row["hold_ms"] += lk.hold_s * 1000.0
            row["max_hold_ms"] = max(row["max_hold_ms"],
                                     lk.max_hold_ms)
            row["hold_warns"] += lk.hold_warns
        rows = sorted(agg.values(),
                      key=lambda r: (-r["contended"], -r["hold_ms"]))
        for r in rows:
            for k in ("wait_ms", "hold_ms", "max_hold_ms"):
                r[k] = round(r[k], 3)
        return rows

    def contended_total(self) -> int:
        with self._l:
            self._drain_dead()
            locks = list(self._locks)
            dead = sum(r["contended"]
                       for r in self._dead_totals.values())
        return dead + sum(lk.contended for lk in locks)

    def hold_warns_total(self) -> int:
        with self._l:
            self._drain_dead()
            locks = list(self._locks)
            dead = sum(r["hold_warns"]
                       for r in self._dead_totals.values())
        return dead + sum(lk.hold_warns for lk in locks)

    def status_snapshot(self, top: int = 12,
                        stacks: bool = False) -> dict:
        """The `locks` block of /v1/operator/governor: aggregate
        per-site stats (worst contention first), the worst-holder
        exemplars, and finding counts. `stacks=True` (the exit-report
        dump) keeps each exemplar's full release-site stack."""
        if not enabled():
            return {"enabled": False}
        with self._l:
            exemplars = [dict(e) for e in self._exemplars]
        for e in exemplars:
            # the operator surface gets only the top frame as the
            # holder hint; the report dump keeps the whole stack
            frames = [ln for ln in e.get("stack", "").splitlines()
                      if ln.strip().startswith("File")]
            e["holder"] = frames[-1].strip() if frames else ""
            if not stacks:
                e.pop("stack", None)
        findings = self.findings()
        return {
            "enabled": True,
            "tracked": self.tracked_locks(),
            "order_edges": self.edge_count(),
            "hold_warn_ms": self.hold_warn_ms,
            "locks": self._lock_rows()[:top],
            "worst_holders": exemplars,
            "findings": len(findings),
            "findings_unsuppressed": len(
                [f for f in findings if not f.get("suppressed")]),
        }

    def reset(self) -> None:
        with self._l:
            self._graph.clear()
            self._findings.clear()
            self._finding_keys.clear()
            self._cycles_seen.clear()
            self._exemplars.clear()
            self._dead_totals.clear()
            self._dead_q.clear()
        # per-thread caches: only this thread's is reachable; stale
        # seen-edge caches in other threads just skip re-recording
        tl = self._tl()
        tl.held = []
        tl.seen_edges = set()

    # -- exit report ---------------------------------------------------
    def _write_report(self) -> None:
        path = os.environ.get(REPORT_ENV)
        if not path:
            return
        try:
            payload = {"findings": self.findings(),
                       "stats": self.status_snapshot(top=50,
                                                     stacks=True)}
            with open(path, "w") as f:
                json.dump(payload, f, indent=2, default=str)
        except Exception:       # pragma: no cover — exit best effort
            pass


monitor = RaceMonitor()

# hot-path binds: the shim's acquire/release run on every lock op in
# the process, so attribute-chain lookups (time.perf_counter,
# threading.get_ident, monitor._tls) are bound once here
_perf = time.perf_counter
_get_ident = threading.get_ident
_TLS = monitor._tls


def configure(hold_warn_ms: Optional[float] = None,
              exemplar_slots: Optional[int] = None,
              max_findings: Optional[int] = None) -> None:
    """ServerConfig.race_* wiring (the preemption.configure idiom —
    the shims are process-global, the server just tunes them)."""
    monitor.configure(hold_warn_ms=hold_warn_ms,
                      exemplar_slots=exemplar_slots,
                      max_findings=max_findings)


# ---------------------------------------------------------------------
class InstrumentedLock:
    """Drop-in for threading.Lock/RLock with order-graph, contention,
    and hold-time bookkeeping. The fast path adds two perf_counter
    reads and a thread-local list append per acquire/release pair —
    the paired overhead smoke holds it under 5% e2e."""

    __slots__ = ("_inner", "_rlock", "name", "_owner", "_depth",
                 "_acq_t", "acquires", "contended", "wait_s",
                 "hold_s", "max_hold_ms", "hold_warns", "__weakref__")

    def __init__(self, name: str, rlock: bool = False):
        self._inner = threading.RLock() if rlock else threading.Lock()
        self._rlock = rlock
        self.name = name
        self._owner: Optional[int] = None
        self._depth = 0
        self._acq_t = 0.0
        self.acquires = 0
        self.contended = 0
        self.wait_s = 0.0
        self.hold_s = 0.0
        self.max_hold_ms = 0.0
        self.hold_warns = 0
        monitor.register_lock(self)

    # -- core protocol -------------------------------------------------
    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        ident = _get_ident()
        if self._owner == ident:
            if self._rlock:
                got = self._inner.acquire(blocking, timeout)
                if got:
                    self._depth += 1
                return got
            # a plain Lock BLOCKING-re-acquired by its owner: raw
            # threading hangs here forever — record the finding, then
            # behave exactly like the raw primitive. A non-blocking
            # probe of an owned lock is legal polling
            # (threading.Condition._is_owned) and stays silent
            if blocking:
                monitor.note_self_deadlock(self)
        got = self._inner.acquire(False)
        contended = False
        if not got:
            if not blocking:
                self.contended += 1
                return False
            contended = True
            t0 = _perf()
            got = self._inner.acquire(True, timeout)
            if not got:
                self.contended += 1
                return False
        now = _perf()
        self._owner = ident
        self._depth = 1
        self._acq_t = now
        self.acquires += 1
        if contended:
            self.contended += 1
            self.wait_s += now - t0
        # inlined monitor bookkeeping — the flat acquire (nothing else
        # held, the overwhelmingly common shape) pays only a
        # thread-local read and a list append; see the overhead smoke
        try:
            held = _TLS.held
        except AttributeError:
            held = _TLS.held = []
            _TLS.seen_edges = set()
        if held:
            monitor._note_edges(self, ident, held, _TLS)
        held.append(self)
        return True

    __enter__ = acquire         # raw threading.Lock.__enter__ IS
                                # acquire (returns True) — same here,
                                # and it saves a call layer per `with`

    def release(self) -> None:
        if self._rlock and self._depth > 1:
            self._depth -= 1
            self._inner.release()
            return
        hold = _perf() - self._acq_t
        self._depth = 0
        self._owner = None
        self.hold_s += hold
        hold_ms = hold * 1000.0
        if hold_ms > self.max_hold_ms:
            self.max_hold_ms = hold_ms
        try:
            held = _TLS.held
        except AttributeError:
            held = _TLS.held = []
            _TLS.seen_edges = set()
        if held and held[-1] is self:
            held.pop()
        else:
            try:
                held.remove(self)
            except ValueError:
                pass                    # cross-thread release
        if hold_ms >= monitor.hold_warn_ms:
            self.hold_warns += 1
            monitor._note_exemplar(self, hold_ms)
        self._inner.release()

    def __exit__(self, t, v, tb) -> None:
        self.release()

    def locked(self) -> bool:
        inner_locked = getattr(self._inner, "locked", None)
        if inner_locked is not None:
            return inner_locked()
        return self._owner is not None

    def held_by_current(self) -> bool:
        return self._owner == threading.get_ident()

    # -- condition-wait bookkeeping ------------------------------------
    def _sleep_save(self) -> int:
        """Called by InstrumentedCondition.wait with the lock held:
        the inner Condition is about to fully release the inner lock,
        so close out this hold episode and remember the recursion
        depth for the wake."""
        depth = self._depth
        hold = time.perf_counter() - self._acq_t
        self.hold_s += hold
        hold_ms = hold * 1000.0
        if hold_ms > self.max_hold_ms:
            self.max_hold_ms = hold_ms
        self._depth = 0
        self._owner = None
        monitor.on_released(self, hold)
        return depth

    def _wake_restore(self, depth: int) -> None:
        self._owner = threading.get_ident()
        self._depth = depth
        self._acq_t = time.perf_counter()
        monitor.on_acquired(self, reacquire=True)

    def __del__(self):
        # preserve the counters of a dying lock (per-connection /
        # per-drain / per-election scopes) so the aggregate lock.*
        # gauges stay monotone; best-effort at interpreter shutdown
        try:
            if self.acquires or self.contended or self.hold_warns:
                monitor.fold_dead_lock(
                    self.name, self.acquires, self.contended,
                    self.wait_s, self.hold_s, self.max_hold_ms,
                    self.hold_warns)
        except Exception:       # pragma: no cover — shutdown races
            pass

    def __repr__(self) -> str:           # pragma: no cover — debug aid
        kind = "rlock" if self._rlock else "lock"
        return f"<Instrumented{kind} {self.name} owner={self._owner}>"


class InstrumentedCondition:
    """Drop-in for threading.Condition sharing an InstrumentedLock's
    bookkeeping: wait() closes the hold episode (the lock is NOT held
    while sleeping) and reopens it on wake, so hold-time gauges and
    the order graph both see through the sleep."""

    __slots__ = ("_ilock", "_cond", "__weakref__")

    def __init__(self, lock: Optional[InstrumentedLock] = None,
                 name: str = "condition"):
        if lock is None:
            lock = InstrumentedLock(name, rlock=True)
        self._ilock = lock
        self._cond = threading.Condition(lock._inner)

    @property
    def name(self) -> str:
        return self._ilock.name

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        return self._ilock.acquire(blocking, timeout)

    def release(self) -> None:
        self._ilock.release()

    def __enter__(self):
        self._ilock.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self._ilock.release()

    def held_by_current(self) -> bool:
        return self._ilock.held_by_current()

    def wait(self, timeout: Optional[float] = None) -> bool:
        if not self._ilock.held_by_current():
            raise RuntimeError("cannot wait on un-acquired lock")
        depth = self._ilock._sleep_save()
        try:
            return self._cond.wait(timeout)
        finally:
            self._ilock._wake_restore(depth)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        endtime = None
        result = predicate()
        while not result:
            if timeout is not None:
                if endtime is None:
                    endtime = time.monotonic() + timeout
                waittime = endtime - time.monotonic()
                if waittime <= 0:
                    break
                self.wait(waittime)
            else:
                self.wait()
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()

    def __repr__(self) -> str:           # pragma: no cover — debug aid
        return f"<InstrumentedCondition {self._ilock.name}>"


# ---------------------------------------------------------------------
# guarded structures: the dynamic half of `guarded-by[...]`

def _unwrap_lock(lock):
    if isinstance(lock, InstrumentedCondition):
        return lock._ilock
    return lock


class _GuardedMixin:
    # plain attributes (not __slots__): dict/list subclasses carry a
    # __dict__ anyway
    def _g_init(self, lock, name):
        self._g_lock = _unwrap_lock(lock)
        self._g_name = name

    def _g_check(self, op: str) -> None:
        lk = getattr(self, "_g_lock", None)
        if isinstance(lk, InstrumentedLock) and not lk.held_by_current():
            monitor.note_unguarded_mutation(self._g_name, lk.name, op)


def _guarding(op):
    def wrap(method):
        def checked(self, *a, **kw):
            self._g_check(op)
            return method(self, *a, **kw)
        checked.__name__ = op
        return checked
    return wrap


class GuardedDict(dict, _GuardedMixin):
    __setitem__ = _guarding("__setitem__")(dict.__setitem__)
    __delitem__ = _guarding("__delitem__")(dict.__delitem__)
    pop = _guarding("pop")(dict.pop)
    popitem = _guarding("popitem")(dict.popitem)
    clear = _guarding("clear")(dict.clear)
    update = _guarding("update")(dict.update)
    setdefault = _guarding("setdefault")(dict.setdefault)


class GuardedList(list, _GuardedMixin):
    __setitem__ = _guarding("__setitem__")(list.__setitem__)
    __delitem__ = _guarding("__delitem__")(list.__delitem__)
    append = _guarding("append")(list.append)
    extend = _guarding("extend")(list.extend)
    insert = _guarding("insert")(list.insert)
    pop = _guarding("pop")(list.pop)
    remove = _guarding("remove")(list.remove)
    clear = _guarding("clear")(list.clear)
    sort = _guarding("sort")(list.sort)


def guard(obj, lock, name: str):
    """Register `obj` (dict or list) as guarded by `lock`. A no-op
    passthrough when the sanitizer is off; when on, returns a checking
    wrapper that records a finding on any mutation performed without
    the lock held by the mutating thread."""
    if not enabled():
        return obj
    monitor.ensure_report_hook()
    if isinstance(obj, dict):
        g = GuardedDict(obj)
    elif isinstance(obj, list):
        g = GuardedList(obj)
    else:
        return obj
    g._g_init(lock, name)
    return g
