"""AST lint engine: the mechanical half of the TPU-hygiene contract.

The reference Nomad leans on `go vet` + the race detector to keep a
heavily threaded orchestrator honest. This rebuild's equivalents are
invariants, not types — "no host sync in the steady-state eval loop",
"no unkeyed jit recompiles", "no lock held across device dispatch" —
so they need a checker tuned to THIS codebase rather than a generic
linter. The engine here is deliberately small:

  - `Project` walks a tree (or an injected {path: source} map, which
    is how the rule fixtures test known-bad snippets), parses each
    file once, and hands a `FileContext` to every registered rule.
  - A rule is a class with a `name`, a `check_file(ctx)` generator
    for per-file AST passes, and an optional `finish(project)` for
    cross-file passes (lock graphs, surface drift).
  - Findings are plain records; `python -m nomad_tpu.analysis` renders
    them for humans or as JSON and exits non-zero when any survive.

Suppressions: `# nomad-lint: allow[rule-a,rule-b] <justification>` on
a line suppresses those rules' findings for that line; on a line of
its own it covers the next code line. Suppressed findings are still
counted (the clean-tree test asserts on UNsuppressed findings only),
so `--show-suppressed` keeps the escape hatches auditable.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

SUPPRESS_RE = re.compile(
    r"#\s*nomad-lint:\s*allow\[([A-Za-z0-9_,\- ]+)\]")
# a line that is only indentation + comment: its allow[] covers the
# next line (the finding site), since long calls rarely leave room
COMMENT_ONLY_RE = re.compile(r"^\s*#")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str                    # repo-relative posix path
    line: int
    col: int
    message: str
    suppressed: bool = False

    def render(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.rule}] {self.message}{tag}")

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class FileContext:
    """One parsed source file: tree with parent links, raw lines, and
    the per-line suppression map rules consult via `finding()`."""

    def __init__(self, path: str, source: str):
        self.path = path.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            self.parse_error = e
            return
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._lint_parent = node       # type: ignore[attr-defined]
        self.suppressions = parse_suppressions(self.lines)

    # -- helpers rules lean on ----------------------------------------
    def finding(self, rule: str, node_or_line, message: str) -> Finding:
        if isinstance(node_or_line, int):
            line, col = node_or_line, 0
        else:
            line = getattr(node_or_line, "lineno", 0)
            col = getattr(node_or_line, "col_offset", 0)
        allowed = self.suppressions.get(line, frozenset())
        return Finding(rule=rule, path=self.path, line=line, col=col,
                       message=message,
                       suppressed=(rule in allowed or "*" in allowed))

    def enclosing_function(self, node) -> Optional[ast.AST]:
        cur = getattr(node, "_lint_parent", None)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = getattr(cur, "_lint_parent", None)
        return None

    def enclosing_class(self, node) -> Optional[ast.ClassDef]:
        cur = getattr(node, "_lint_parent", None)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            cur = getattr(cur, "_lint_parent", None)
        return None


def parse_suppressions(lines: Sequence[str]) -> Dict[int, frozenset]:
    """{1-based line: frozenset(rule names)} — a comment-only allow[]
    line also covers the next line."""
    out: Dict[int, frozenset] = {}
    for i, raw in enumerate(lines, start=1):
        m = SUPPRESS_RE.search(raw)
        if not m:
            continue
        rules = frozenset(r.strip() for r in m.group(1).split(",")
                          if r.strip())
        out[i] = out.get(i, frozenset()) | rules
        if COMMENT_ONLY_RE.match(raw):
            out[i + 1] = out.get(i + 1, frozenset()) | rules
    return out


def attr_chain(node) -> Optional[str]:
    """Dotted name of an expression: `jax.device_get` ->
    "jax.device_get", `self._l` -> "self._l"; None for anything with a
    non-name base (calls, subscripts)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> Optional[str]:
    return attr_chain(node.func)


def decorator_names(fn) -> List[str]:
    out = []
    for dec in getattr(fn, "decorator_list", []):
        if isinstance(dec, ast.Call):
            dec = dec.func
        name = attr_chain(dec)
        if name:
            out.append(name)
    return out


class Rule:
    """Base lint pass. `name` is the suppression key; `doc` is the
    one-liner `--list` prints."""

    name = "rule"
    doc = ""

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def finish(self, project: "Project") -> Iterable[Finding]:
        return ()


class Project:
    """A lintable tree. `files` injects {relpath: source} directly (the
    fixture tests); otherwise sources are read from `root`."""

    def __init__(self, root: str = ".",
                 files: Optional[Dict[str, str]] = None):
        self.root = root
        self._files = files
        self.contexts: Dict[str, FileContext] = {}
        self.extra_text: Dict[str, str] = {}   # non-python (STATUS.md)

    # -- file discovery -----------------------------------------------
    def _walk_python(self, paths: Sequence[str]) -> List[Tuple[str, str]]:
        out = []
        for p in paths:
            full = os.path.join(self.root, p)
            if os.path.isfile(full):
                out.append((p.replace(os.sep, "/"), full))
                continue
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"
                               and not d.startswith(".")]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        fp = os.path.join(dirpath, fn)
                        rel = os.path.relpath(fp, self.root)
                        out.append((rel.replace(os.sep, "/"), fp))
        return out

    def load(self, paths: Sequence[str]) -> None:
        if self._files is not None:
            for rel, src in self._files.items():
                rel = rel.replace(os.sep, "/")
                if rel.endswith(".py"):
                    self.contexts[rel] = FileContext(rel, src)
                else:
                    self.extra_text[rel] = src
            return
        for rel, full in self._walk_python(paths):
            if rel in self.contexts:
                continue
            try:
                with open(full, encoding="utf-8") as f:
                    src = f.read()
            except OSError:
                continue
            self.contexts[rel] = FileContext(rel, src)

    def text(self, relpath: str) -> Optional[str]:
        """Raw text of a repo file (python or not); fixture-injected
        maps answer from memory, disk projects read lazily."""
        relpath = relpath.replace(os.sep, "/")
        if relpath in self.extra_text:
            return self.extra_text[relpath]
        ctx = self.contexts.get(relpath)
        if ctx is not None:
            return ctx.source
        if self._files is not None:
            return None
        full = os.path.join(self.root, relpath)
        try:
            with open(full, encoding="utf-8") as f:
                return f.read()
        except OSError:
            return None

    def glob_texts(self, reldir: str, suffix: str = ".py"
                   ) -> Dict[str, str]:
        """{relpath: text} for every file under `reldir` (loaded
        contexts + injected texts + disk)."""
        reldir = reldir.rstrip("/") + "/"
        out = {p: c.source for p, c in self.contexts.items()
               if p.startswith(reldir) and p.endswith(suffix)}
        for p, t in self.extra_text.items():
            if p.startswith(reldir) and p.endswith(suffix):
                out[p] = t
        if self._files is None:
            full = os.path.join(self.root, reldir)
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"]
                for fn in filenames:
                    if not fn.endswith(suffix):
                        continue
                    rel = os.path.relpath(os.path.join(dirpath, fn),
                                          self.root).replace(os.sep, "/")
                    if rel not in out:
                        t = self.text(rel)
                        if t is not None:
                            out[rel] = t
        return out

    # -- the run -------------------------------------------------------
    def analyze(self, rules: Sequence[Rule]) -> List[Finding]:
        findings: List[Finding] = []
        for ctx in self.contexts.values():
            if ctx.tree is None:
                findings.append(Finding(
                    rule="parse", path=ctx.path,
                    line=ctx.parse_error.lineno or 0, col=0,
                    message=f"syntax error: {ctx.parse_error.msg}"))
                continue
            for rule in rules:
                findings.extend(rule.check_file(ctx))
        for rule in rules:
            findings.extend(rule.finish(self))
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        return findings


def run(paths: Sequence[str], root: str = ".",
        rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
    """Load + analyze; the programmatic entry the CLI/tests share."""
    from .passes import default_rules
    project = Project(root=root)
    project.load(paths)
    return project.analyze(list(rules) if rules is not None
                           else default_rules())
