"""The five TPU-hygiene passes, tuned to this codebase.

Each pass enforces an invariant PRs 1-2 established but nothing
verified mechanically (CHANGES.md, STATUS §2.6):

  host-sync        zero host syncs in the steady-state eval loop —
                   device pulls only through the attribution fences
  jit-hygiene      no unkeyed recompile sources: config params must be
                   static, closures under jit must be cached
  dtype-discipline no 64-bit dtype literals in ops/ kernels (x64 is
                   disabled — they silently downcast on device), pad
                   widths only from the bucketing helpers
  lock-discipline  lock-acquisition graph must be acyclic, and no lock
                   may be held across device dispatch / blocking waits
                   (interprocedural — concurrency.py, call graph
                   depth >= 3 sees through helpers)
  shared-state     attrs mutated across the thread boundary need a
                   common lock; guarded-by[...] declares intent
  raw-lock         locks are born in utils/locks.py so the
                   NOMAD_TPU_RACE=1 shims can instrument them
  surface-drift    every HTTP route needs a CLI/test reference; every
                   ServerConfig.governor_*/plan_group_* knob must
                   appear in STATUS.md

Rules report THROUGH ctx.finding(), so inline
`# nomad-lint: allow[rule]` suppressions are honored uniformly.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .concurrency import LockRule, RawLockRule, SharedStateRule
from .engine import (FileContext, Finding, Project, Rule, attr_chain,
                     call_name, decorator_names)

# modules whose steady-state hot paths the host-sync / lock passes
# police; everything outside (cli, bench, api edges) is host-side by
# design
HOT_PREFIXES = ("nomad_tpu/ops/", "nomad_tpu/server/",
                "nomad_tpu/scheduler/", "nomad_tpu/state/",
                "nomad_tpu/parallel/", "nomad_tpu/utils/")


def _in_hot_path(path: str) -> bool:
    return any(path.startswith(p) for p in HOT_PREFIXES)


def _module_names(tree: ast.Module) -> Set[str]:
    """Names bound at module level (defs, classes, imports, assigns) —
    the closure checks treat these as NOT free."""
    out: Set[str] = set()
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            out.add(node.name)
        elif isinstance(node, ast.Import):
            out.update(a.asname or a.name.split(".")[0]
                       for a in node.names)
        elif isinstance(node, ast.ImportFrom):
            out.update(a.asname or a.name for a in node.names)
        elif isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _bound_names(fn) -> Set[str]:
    """Parameters + names assigned anywhere inside `fn` (incl. nested
    comprehension targets) — the complement of its free variables."""
    args = fn.args
    names = {a.arg for a in (args.posonlyargs + args.args
                             + args.kwonlyargs)}
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) and isinstance(node.ctx,
                                                         ast.Store):
                names.add(node.id)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                names.add(node.name)
            elif isinstance(node, ast.Import):
                names.update(a.asname or a.name.split(".")[0]
                             for a in node.names)
            elif isinstance(node, ast.ImportFrom):
                names.update(a.asname or a.name for a in node.names)
    return names


def _free_names(fn, module_level: Set[str]) -> Set[str]:
    import builtins
    bound = _bound_names(fn)
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    free: Set[str] = set()
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) and isinstance(node.ctx,
                                                         ast.Load):
                n = node.id
                if n not in bound and n not in module_level \
                        and not hasattr(builtins, n):
                    free.add(n)
    return free


# ---------------------------------------------------------------------
class HostSyncRule(Rule):
    """Pass 1: host-sync discipline. `jax.device_get`, `.item()`,
    `.block_until_ready()`, and `np.asarray`/`float()` over jax values
    are forbidden in the steady-state modules outside the whitelisted
    attribution fences (utils/stages.py and ops/select.py's
    `_stage_get` d2h helper) — each one is a blocking device round
    trip that BENCH_r05 showed dominating the e2e gap."""

    name = "host-sync"
    doc = "no host syncs outside the attribution fences"

    FENCE_MODULES = ("nomad_tpu/utils/stages.py",)
    FENCE_FUNCS = {("nomad_tpu/ops/select.py", "_stage_get")}

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if not _in_hot_path(ctx.path) or ctx.path in self.FENCE_MODULES:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = ctx.enclosing_function(node)
            if fn is not None and (ctx.path, fn.name) in self.FENCE_FUNCS:
                continue
            name = call_name(node) or ""
            msg = None
            if name.endswith("device_get"):
                msg = ("host sync: jax.device_get blocks on the device"
                       " — route result pulls through the d2h fence "
                       "(ops/select._stage_get) or fence this site")
            elif name.endswith(".block_until_ready"):
                msg = ("host sync: block_until_ready stalls the host "
                       "on device completion outside a fence")
            elif name.endswith(".item") and not node.args \
                    and not node.keywords:
                msg = (".item() is a scalar host pull (one device "
                       "round trip per call)")
            elif name in ("np.asarray", "np.array", "numpy.asarray",
                          "numpy.array") and node.args:
                inner = node.args[0]
                iname = call_name(inner) if isinstance(inner, ast.Call) \
                    else None
                if iname and (iname.startswith("jnp.")
                              or iname.startswith("jax.")):
                    msg = (f"np.asarray over `{iname}` forces a host "
                           f"sync on the device value")
            elif name == "float" and node.args \
                    and isinstance(node.args[0], ast.Call):
                iname = call_name(node.args[0]) or ""
                if iname.startswith("jnp.") or iname.startswith("jax."):
                    msg = (f"float() over `{iname}` is a scalar host "
                           f"pull")
            if msg:
                yield ctx.finding(self.name, node, msg)


# ---------------------------------------------------------------------
class JitHygieneRule(Rule):
    """Pass 2: jit hygiene. A `jax.jit` call site must key its
    non-array config through `static_argnums`/`static_argnames`, and a
    closure jitted inside a plain function is reconstructed per call —
    jax caches by function object identity, so every construction
    compiles anew (the recompile-storm source the trace counter in
    analysis/sanitizer.py measures at runtime)."""

    name = "jit-hygiene"
    doc = "static_argnums for config params; no uncached jit closures"

    CACHING_DECORATORS = ("lru_cache", "cache", "functools.lru_cache",
                          "functools.cache")

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        module_level = _module_names(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            target, statics = self._jit_target(node)
            if target is None:
                continue
            yield from self._check_site(ctx, node, target, statics,
                                        module_level)

    def _jit_target(self, node: ast.Call
                    ) -> Tuple[Optional[ast.AST], bool]:
        """(jitted expression, statics-given) for a jax.jit call site;
        (None, False) when `node` is not one. Handles direct
        `jax.jit(fn, ...)` and `partial(jax.jit, ...)` — the partial's
        kwargs count as the statics."""
        name = call_name(node) or ""
        statics = any(kw.arg in ("static_argnums", "static_argnames")
                      for kw in node.keywords)
        if name.endswith("jax.jit") or name == "jit":
            return (node.args[0] if node.args else None), statics
        if name.endswith("partial") and node.args:
            first = attr_chain(node.args[0]) or ""
            if first.endswith("jax.jit") or first == "jit":
                # partial(jax.jit, static_argnames=...)(fn): the outer
                # call applies it; the wrapped fn is checked where the
                # partial is invoked — too dynamic to chase, so only
                # verify the partial carries statics OR targets a fn
                # with none needed. Treated as statics-given when the
                # partial has them.
                return None, statics
        return None, False

    def _check_site(self, ctx: FileContext, node: ast.Call, target,
                    statics: bool, module_level: Set[str]
                    ) -> Iterable[Finding]:
        # a jit applied through a partial-with-statics wrapper
        # ( _select_scan = partial(jax.jit, static_argnames=...)(fn) )
        # arrives here with statics=True via the outer call's keywords
        parent = getattr(node, "_lint_parent", None)
        if isinstance(parent, ast.Call):
            pname = call_name(parent) or ""
            if pname.endswith("partial"):
                return
        enclosing = ctx.enclosing_function(node)
        cached = enclosing is not None and any(
            d in self.CACHING_DECORATORS
            for d in decorator_names(enclosing))

        # look through jax.vmap(fn, ...) wrappers
        inner = target
        if isinstance(inner, ast.Call) and \
                (call_name(inner) or "").endswith("vmap") and inner.args:
            inner = inner.args[0]

        if isinstance(inner, ast.Lambda):
            if enclosing is not None and not cached:
                yield ctx.finding(
                    self.name, node,
                    "jax.jit over a lambda constructed per call — jax "
                    "caches by function identity, so every invocation "
                    "of the enclosing function recompiles; hoist to "
                    "module level or cache the wrapper")
            return
        if not isinstance(inner, ast.Name):
            return
        fndef = self._resolve(ctx, node, inner.id)
        if fndef is None:
            return
        if fndef.args.kwonlyargs and not statics:
            names = ", ".join(a.arg for a in fndef.args.kwonlyargs)
            yield ctx.finding(
                self.name, node,
                f"jitted `{fndef.name}` takes keyword-only config "
                f"params ({names}) but the jit call passes no "
                f"static_argnums/static_argnames — every distinct "
                f"value retraces with a poisoned cache key")
        if enclosing is not None and not cached:
            free = _free_names(fndef, module_level)
            local_defs = {n.name for n in ast.walk(enclosing)
                          if isinstance(n, (ast.FunctionDef,
                                            ast.AsyncFunctionDef))}
            if free and fndef.name in local_defs:
                yield ctx.finding(
                    self.name, node,
                    f"jax.jit over closure `{fndef.name}` (captures "
                    f"{', '.join(sorted(free))}) inside an uncached "
                    f"function — each call builds a fresh callable "
                    f"and recompiles; memoize the wrapper "
                    f"(lru_cache) or hoist the closure")

    @staticmethod
    def _resolve(ctx: FileContext, node, name: str):
        """Nearest FunctionDef named `name`: enclosing scopes first,
        then module level."""
        cur = ctx.enclosing_function(node)
        while cur is not None:
            for stmt in ast.walk(cur):
                if isinstance(stmt, ast.FunctionDef) and \
                        stmt.name == name:
                    return stmt
            cur = ctx.enclosing_function(cur)
        for stmt in ctx.tree.body:
            if isinstance(stmt, ast.FunctionDef) and stmt.name == name:
                return stmt
        return None


# ---------------------------------------------------------------------
class DtypeRule(Rule):
    """Pass 3: dtype discipline in `ops/` kernel modules. x64 is
    disabled (tests/conftest.py pins JAX_ENABLE_X64=0), so a 64-bit
    dtype literal reaching a device array silently downcasts — the
    value the author wrote is not the value the kernel sees. Pad
    widths must come from the bucketing helpers, or every novel shape
    is a fresh XLA compile."""

    name = "dtype-discipline"
    doc = "no float64/int64 literals in ops/; pad widths from buckets"

    SCOPE = ("nomad_tpu/ops/",)
    BAD_ATTRS = {"np.float64", "np.int64", "numpy.float64",
                 "numpy.int64", "jnp.float64", "jnp.int64",
                 "jax.numpy.float64", "jax.numpy.int64"}
    BUCKET_HELPERS = ("_pad_n", "_bucket_k", "_bucket_rows", "_kway_w")

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if not any(ctx.path.startswith(p) for p in self.SCOPE):
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute):
                chain = attr_chain(node)
                if chain in self.BAD_ATTRS:
                    yield ctx.finding(
                        self.name, node,
                        f"64-bit dtype literal `{chain}` in a kernel "
                        f"module — x64 is disabled, device use "
                        f"silently downcasts; use the 32-bit dtype")
            elif isinstance(node, ast.Constant) and \
                    node.value in ("float64", "int64"):
                yield ctx.finding(
                    self.name, node,
                    f"64-bit dtype string {node.value!r} in a kernel "
                    f"module — x64 is disabled; use the 32-bit dtype")
            elif isinstance(node, ast.Call):
                name = call_name(node) or ""
                if name.endswith(".pad") and len(node.args) >= 2:
                    width = node.args[1]
                    if not self._uses_bucket(width):
                        yield ctx.finding(
                            self.name, node,
                            "pad width is not derived from the "
                            "bucketing table (_pad_n/_bucket_k/"
                            "_bucket_rows) — ad-hoc pad shapes "
                            "multiply XLA compile-cache entries")

    def _uses_bucket(self, expr) -> bool:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                name = (call_name(node) or "").split(".")[-1]
                if name in self.BUCKET_HELPERS:
                    return True
        return False


# ---------------------------------------------------------------------
class SurfaceDriftRule(Rule):
    """Pass 5: surface drift. The HTTP route table, the CLI, and
    STATUS.md drift apart silently as the surface grows (ROADMAP: CLI
    long tail, RPC surface). Two contracts: every `/v1/...` route in
    api/http.py must be referenced by a CLI command, the typed client,
    or a test; every `ServerConfig.governor_*` / `plan_group_*` knob
    must appear in STATUS.md so operators can find it."""

    name = "surface-drift"
    doc = ("routes need CLI/test references; governor/persistence "
           "knobs in STATUS.md")

    # ServerConfig/ClientConfig knob families that must appear in the
    # STATUS.md knob table (operators find them there; the table is
    # the contract). stats_ covers BOTH config classes (ISSUE 13: the
    # client sampler's knobs live on ClientConfig, the rollup
    # staleness knob on ServerConfig).
    KNOB_PREFIXES = ("governor_", "plan_group_", "reconcile_",
                     "gateway_", "snapshot_", "wal_", "trace_",
                     "preempt_", "telemetry_", "mesh_", "stats_",
                     "race_", "chaos_", "follower_", "feas_",
                     "ingest_")

    # which config dataclasses carry operator knobs
    CONFIG_CLASSES = ("ServerConfig", "ClientConfig")

    def __init__(self,
                 http_path: str = "nomad_tpu/api/http.py",
                 reference_dirs: Sequence[str] = ("nomad_tpu/cli",
                                                 "tests"),
                 reference_files: Sequence[str] = (
                     "nomad_tpu/api/client.py",),
                 config_path: str = "nomad_tpu/server/core.py",
                 client_config_path: str = "nomad_tpu/client/agent.py",
                 status_path: str = "STATUS.md"):
        self.http_path = http_path
        self.reference_dirs = tuple(reference_dirs)
        self.reference_files = tuple(reference_files)
        self.config_path = config_path
        self.client_config_path = client_config_path
        self.status_path = status_path

    def finish(self, project: Project) -> Iterable[Finding]:
        yield from self._check_routes(project)
        yield from self._check_knobs(project)

    # -- routes --------------------------------------------------------
    def _check_routes(self, project: Project) -> Iterable[Finding]:
        ctx = project.contexts.get(self.http_path)
        if ctx is None or ctx.tree is None:
            return
        pools = self._reference_pools(project)
        for line, route in self._routes(ctx):
            segments = [s for s in route.split("*") if len(s) > 1]
            if not segments:
                continue
            if not any(all(seg in text for seg in segments)
                       for text in pools):
                yield ctx.finding(
                    self.name, line,
                    f"route {route!r} has no CLI command, client "
                    f"method, or test referencing it — dead or "
                    f"untested surface")

    def _routes(self, ctx: FileContext) -> List[Tuple[int, str]]:
        """(line, normalized route) pairs: capture groups -> `*`."""
        out = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Compare):
                for comp in node.comparators:
                    if isinstance(comp, ast.Constant) and \
                            isinstance(comp.value, str) and \
                            comp.value.startswith("/v1/"):
                        if isinstance(node.ops[0], (ast.Eq, ast.NotEq)):
                            out.append((node.lineno, comp.value))
            elif isinstance(node, ast.Call):
                name = call_name(node) or ""
                if name.endswith("re.match") and node.args and \
                        isinstance(node.args[0], ast.Constant) and \
                        isinstance(node.args[0].value, str):
                    pat = node.args[0].value
                    if pat.startswith("^/v1/"):
                        out.append((node.lineno,
                                    self._normalize(pat)))
        return out

    @staticmethod
    def _normalize(pattern: str) -> str:
        pat = pattern.lstrip("^").rstrip("$")
        pat = re.sub(r"\((?:[^()]|\([^()]*\))*\)", "*", pat)
        return pat.replace("\\", "")

    def _reference_pools(self, project: Project) -> List[str]:
        pools = []
        for d in self.reference_dirs:
            pools.extend(project.glob_texts(d).values())
        for f in self.reference_files:
            t = project.text(f)
            if t is not None:
                pools.append(t)
        return pools

    # -- operator knobs ------------------------------------------------
    def _check_knobs(self, project: Project) -> Iterable[Finding]:
        status = project.text(self.status_path) or ""
        seen_paths = set()
        for path in (self.config_path, self.client_config_path):
            if not path or path in seen_paths:
                continue
            seen_paths.add(path)
            ctx = project.contexts.get(path)
            if ctx is None or ctx.tree is None:
                continue
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.ClassDef) or \
                        node.name not in self.CONFIG_CLASSES:
                    continue
                for stmt in node.body:
                    target = None
                    if isinstance(stmt, ast.AnnAssign) and \
                            isinstance(stmt.target, ast.Name):
                        target = stmt.target.id
                    elif isinstance(stmt, ast.Assign) and \
                            isinstance(stmt.targets[0], ast.Name):
                        target = stmt.targets[0].id
                    if target and target.startswith(self.KNOB_PREFIXES) \
                            and target not in status:
                        yield ctx.finding(
                            self.name, stmt,
                            f"{node.name}.{target} is not documented "
                            f"in {self.status_path} — operators can't "
                            f"find the knob")


def default_rules() -> List[Rule]:
    return [HostSyncRule(), JitHygieneRule(), DtypeRule(), LockRule(),
            SharedStateRule(), RawLockRule(), SurfaceDriftRule()]
