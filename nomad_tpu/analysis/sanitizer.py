"""Opt-in runtime sanitizer for the placement and scatter-delta
kernels (`NOMAD_TPU_SANITIZE=1`).

The static passes prove call-site discipline; this module checks the
VALUES. Checkify-style guards run host-side at the kernel boundary —
where the arrays are still (or again) numpy — so the device never pays
for them and the checks hold even when the dispatch itself is async:

  check_finite    NaN/Inf screens on the columns a dispatch ships
                  (capacity/used/ask) and the scores it returns — a
                  NaN in `used` silently wins every argmax
  check_rows      out-of-bounds row guards on the scatter-delta and
                  overlay index vectors — `.at[rows]` DROPS
                  out-of-range rows on TPU instead of raising, which
                  is exactly the silent corruption mode

Always-on (the cost is a set lookup): a per-kernel distinct
trace-signature counter. Every dispatch arm reports its compile key
(kernel name, shape bucket, statics); a NEW signature means XLA traced
and compiled. The total is exported as the `nomad.lint.recompiles`
metric gauge and registered as the governor's `lint.recompiles` gauge,
so a recompile storm (the failure mode the jit-hygiene pass guards
statically) shows up in `/v1/operator/governor` as a climbing number
instead of a mystery p99.
"""

from __future__ import annotations

import os
from typing import Dict, Tuple

import numpy as np
from ..utils.locks import make_lock

ENV = "NOMAD_TPU_SANITIZE"


def enabled() -> bool:
    """Read live (not cached) so tests and operators can toggle the
    env var without a restart; one getenv per guarded kernel entry."""
    return os.environ.get(ENV, "") not in ("", "0", "off", "no")


class SanitizerError(RuntimeError):
    """A value-level invariant violation caught at a kernel boundary."""


def check_finite(tag: str, **arrays) -> None:
    """Raise when any float array carries NaN/Inf. Non-float and
    non-numpy values are skipped — device arrays are checked at the
    host boundaries where they have been pulled anyway."""
    for name, a in arrays.items():
        if a is None or not isinstance(a, np.ndarray):
            continue
        if a.dtype.kind != "f":
            continue
        if not np.isfinite(a).all():
            bad = int((~np.isfinite(a)).sum())
            raise SanitizerError(
                f"sanitizer[{tag}]: {name} carries {bad} non-finite "
                f"value(s) — a NaN/Inf here silently corrupts every "
                f"downstream argmax")


def check_rows(tag: str, rows, n: int) -> None:
    """Raise when a scatter/overlay row-index vector leaves [0, n).
    On TPU `.at[rows]` drops out-of-range rows silently, so this is
    the only place the bug is visible."""
    idx = np.asarray(rows)
    if idx.size == 0:
        return
    lo = int(idx.min())
    hi = int(idx.max())
    if lo < 0 or hi >= n:
        raise SanitizerError(
            f"sanitizer[{tag}]: row indices [{lo}, {hi}] fall outside "
            f"the table's [0, {n}) — the device scatter would drop "
            f"them silently")


class TraceCounter:
    """Compile events per kernel. `note()` is the dispatch-side hook;
    it returns True when the signature is new since the last
    invalidation (== a trace + compile happened). The exported total
    is a MONOTONE cumulative compile count, not len(seen): after the
    governor's `clear_kernel_caches` reclaim (which must call
    `invalidate()`), warm shapes re-trace and each one moves the gauge
    again — a cache-thrash storm stays visible instead of hiding
    behind already-seen keys."""

    def __init__(self):
        self._l = make_lock()
        self._seen: Dict[str, set] = {}
        self._total = 0

    def note(self, kernel: str, signature: Tuple) -> bool:
        from ..utils import metrics
        with self._l:
            sigs = self._seen.setdefault(kernel, set())
            if signature in sigs:
                return False
            sigs.add(signature)
            self._total += 1
            # publish under the lock: metrics has its own independent
            # lock (no ordering cycle), and publishing outside would
            # let two concurrent notes land out of order and make the
            # "monotone by construction" gauge transiently regress
            metrics.set_gauge("nomad.lint.recompiles", self._total)
        return True

    def count(self) -> int:
        """Cumulative compile events (monotone; the gauge value)."""
        with self._l:
            return self._total

    def per_kernel(self) -> Dict[str, int]:
        """Distinct signatures since the last invalidation."""
        with self._l:
            return {k: len(v) for k, v in sorted(self._seen.items())}

    def invalidate(self) -> None:
        """The compiled caches were dropped: forget seen signatures so
        re-traces count as fresh compiles, keep the cumulative total."""
        with self._l:
            self._seen.clear()

    def reset(self) -> None:
        with self._l:
            self._seen.clear()
            self._total = 0


# process-wide: every kernel arm (workers, gateways, benches) reports
# into the same counter the governor gauge reads
traces = TraceCounter()
