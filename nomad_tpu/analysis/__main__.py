"""`python -m nomad_tpu.analysis [paths...]` — run the TPU-hygiene
passes and exit non-zero when unsuppressed findings remain. Also the
body of `nomad-tpu dev lint` and the `nomad-tpu-lint` console entry.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional


def _resolve(paths: List[str]):
    """(root, repo-relative paths) for the engine. The scope prefixes
    the passes match on ("nomad_tpu/ops/", ...) are repo-relative, so
    paths must be normalized against the repo root — NOT the cwd — or
    an invocation from outside the repo silently scopes every
    path-gated pass to nothing and reports a false clean."""
    # __file__ = <repo>/nomad_tpu/analysis/__main__.py
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    if not paths:
        return repo, ["nomad_tpu"]
    abspaths = [os.path.abspath(p) for p in paths]
    if all(ap == repo or ap.startswith(repo + os.sep)
           for ap in abspaths):
        return repo, [os.path.relpath(ap, repo) or "."
                      for ap in abspaths]
    # linting a tree that is not this repo: cwd-relative as given
    return ".", paths


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="nomad-tpu-lint",
        description="TPU-hygiene linter: host-sync / jit / dtype / "
                    "lock / surface-drift passes")
    p.add_argument("paths", nargs="*",
                   help="files or directories (default: the "
                        "nomad_tpu package)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable findings on stdout")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also print findings silenced by "
                        "`# nomad-lint: allow[...]`")
    p.add_argument("--list", action="store_true", dest="list_rules",
                   help="list the passes and exit")
    args = p.parse_args(argv)

    from .passes import default_rules
    rules = default_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.name:18s} {r.doc}")
        return 0

    from .engine import run
    root, paths = _resolve(args.paths)
    findings = run(paths, root=root, rules=rules)
    active = [f for f in findings if not f.suppressed]
    shown = findings if args.show_suppressed else active

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_dict() for f in shown],
            "total": len(active),
            "suppressed": len(findings) - len(active),
        }, indent=2))
    else:
        for f in shown:
            print(f.render())
        print(f"{len(active)} finding(s), "
              f"{len(findings) - len(active)} suppressed")
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
