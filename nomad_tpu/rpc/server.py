"""Server-side RPC endpoint registry and TCP listener.

The method table mirrors the reference's endpoint structs
(nomad/server.go:264 `endpoints`, node_endpoint.go, job_endpoint.go):
each method declares how to decode its typed arguments and runs against
the Server object. Long-poll methods (Node.GetClientAllocs) block
server-side on the state store's watch condition exactly like blocking
queries over go-memdb watch channels (node_endpoint.go:926).

Concurrency model: one handler thread per in-flight request; responses
are written under a per-connection lock and matched by seq on the
client — the functional equivalent of net/rpc over yamux streams.
"""

from __future__ import annotations

import logging
import socket
import socketserver
import threading
from typing import Any, Dict, List, Optional

from ..models import Allocation, Node
from ..utils.codec import from_wire, to_wire
from .codec import FrameCodec, RpcRefused
from ..utils.locks import make_lock

LOG = logging.getLogger("nomad_tpu.rpc")


def _get_client_allocs(server, args: Dict) -> Dict:
    node_id = args["node_id"]
    min_index = int(args.get("min_index", 0))
    max_wait_s = float(args.get("max_wait_s", 30.0))
    store = server.store
    if min_index > 0:
        store.block_min_index(min_index, timeout_s=max_wait_s)
    snap = store.snapshot()
    allocs = snap.allocs_by_node(node_id)
    return {"allocs": [to_wire(a) for a in allocs],
            "index": snap.latest_index()}


# -- per-domain endpoint registries (ISSUE 19 satellite) --------------
# The reference registers one endpoint struct per domain
# (nomad/server.go:264 `endpoints`: Node, Job, Alloc, Eval, Plan,
# ClientStats, ...); the flat 16-verb dict this grew from made adding a
# batch verb a diff in the middle of an unrelated list. Each registry
# below returns its domain's verbs and declares its own write set;
# build_method_table composes them. Two domains register elsewhere by
# construction: Eval.* / Plan.* (the distributed scheduler plane's
# follower verbs, follower_sched.rpc_handlers) and Raft.* (raft shim)
# merge into RpcServer.methods at Server.attach_raft — same
# registration discipline, later binding. ClientStats rides
# Node.Heartbeat's `stats` argument rather than its own verb.


def node_methods(server) -> Dict[str, Any]:
    def node_register(args):
        node = from_wire(Node, args["node"])
        server.register_node(node)
        return {"heartbeat_ttl_s": server.config.heartbeat_ttl_s}

    def node_update_status(args):
        server.update_node_status(args["node_id"], args["status"])
        return {}

    def node_heartbeat(args):
        return {"ttl_s": server.heartbeat(args["node_id"],
                                          stats=args.get("stats"))}

    def node_update_alloc(args):
        allocs = [from_wire(Allocation, a) for a in args["allocs"]]
        server.update_alloc_status_from_client(allocs)
        return {}

    def node_update_alloc_batch(args):
        # bulk ingest verb (ISSUE 19): N clients' update groups in one
        # call, decoded through the dedup pool (a fleet pushing one
        # task-state shape materializes it once) and landed as one
        # coalesced raft entry by the ingest gateway
        from ..state.columnar import WirePool, from_wire_pooled
        pool = WirePool()
        groups = [[from_wire_pooled(Allocation, a, pool) for a in g]
                  for g in args.get("updates") or []]
        server.update_alloc_status_from_client_batch(groups)
        return {"groups": len(groups),
                "pool_hits": pool.hits}

    def node_get_client_allocs(args):
        return _get_client_allocs(server, args)

    def node_derive_vault_token(args):
        return {"tokens": server.derive_vault_token(
            args["alloc_id"], list(args.get("tasks") or []))}

    def node_renew_vault_token(args):
        return {"lease_s": server.renew_vault_token(
            args["accessor"], args["token"])}

    return {
        "Node.Register": node_register,
        "Node.UpdateStatus": node_update_status,
        "Node.Heartbeat": node_heartbeat,
        "Node.UpdateAlloc": node_update_alloc,
        "Node.UpdateAllocBatch": node_update_alloc_batch,
        "Node.GetClientAllocs": node_get_client_allocs,
        "Node.DeriveVaultToken": node_derive_vault_token,
        "Node.RenewVaultToken": node_renew_vault_token,
    }


NODE_WRITE_METHODS = frozenset({
    "Node.Register", "Node.UpdateStatus", "Node.Heartbeat",
    "Node.UpdateAlloc", "Node.UpdateAllocBatch",
    "Node.DeriveVaultToken", "Node.RenewVaultToken"})


def status_methods(server) -> Dict[str, Any]:
    def status_ping(_args):
        return {"status": "ok", "leader": True,
                "index": server.store.latest_index()}

    return {"Status.Ping": status_ping}


def server_methods(server) -> Dict[str, Any]:
    def server_join(args):
        return {"members": server.join_member(args["addr"])}

    def server_leave(args):
        return {"members": server.leave_member(args["addr"])}

    def server_members(_args):
        return {"members": server.store.server_members()}

    def server_indirect_ping(args):
        # SWIM ping-req: probe `target` on behalf of another member
        swim = getattr(server, "swim", None)
        if swim is None:
            return {"ok": False}
        return {"ok": swim.probe_for_peer(args["target"])}

    def server_report_failed(args):
        return {"removed": server.handle_peer_failure_report(
            args["addr"], reporter=args.get("reporter", ""))}

    return {
        "Server.Join": server_join,
        "Server.Leave": server_leave,
        "Server.Members": server_members,
        "Server.IndirectPing": server_indirect_ping,
        "Server.ReportFailed": server_report_failed,
    }


SERVER_WRITE_METHODS = frozenset({"Server.Join", "Server.Leave"})


def alloc_methods(server) -> Dict[str, Any]:
    def alloc_get(args):
        from .transport import _alloc_with_node
        return _alloc_with_node(server, args["alloc_id"])

    return {"Alloc.GetAlloc": alloc_get}


def service_methods(server) -> Dict[str, Any]:
    def service_update(args):
        from ..models.services import ServiceRegistration
        upserts = [from_wire(ServiceRegistration, s)
                   for s in args.get("upserts") or []]
        server.update_service_registrations(
            upserts=upserts,
            delete_alloc_ids=args.get("delete_alloc_ids"),
            delete_ids=args.get("delete_ids"))
        return {}

    return {"Service.Update": service_update}


SERVICE_WRITE_METHODS = frozenset({"Service.Update"})


def csi_methods(server) -> Dict[str, Any]:
    def csi_volume_get(args):
        v = server.store.csi_volume(args.get("namespace", "default"),
                                    args["volume_id"])
        return {"volume": v.stub() if v is not None else None}

    return {"CSIVolume.Get": csi_volume_get}


DOMAIN_REGISTRIES = (node_methods, status_methods, server_methods,
                     alloc_methods, service_methods, csi_methods)


def build_method_table(server) -> Dict[str, Any]:
    """method name -> callable(args dict) -> wire-safe result,
    composed from the per-domain registries above."""
    methods: Dict[str, Any] = {}
    for registry in DOMAIN_REGISTRIES:
        methods.update(registry(server))
    return methods


# client-facing writes that must run on the leader (rpc.go forward()),
# composed from each domain's declared write set
WRITE_METHODS = (NODE_WRITE_METHODS | SERVER_WRITE_METHODS
                 | SERVICE_WRITE_METHODS)


class RpcServer:
    """Threaded TCP RPC listener. Bound to a Server instance by
    default; a custom method table makes it a generic RPC endpoint
    (the plugin boundary reuses it, plugins/base.py)."""

    def __init__(self, server=None, host: str = "127.0.0.1", port: int = 0,
                 methods: Optional[Dict[str, Any]] = None):
        self.server = server
        self.methods = methods if methods is not None \
            else build_method_table(server)
        self.raft = None                   # set by Server.attach_raft
        rpc = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                rpc._serve_conn(self.request)

        class Listener(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._listener = Listener((host, port), Handler)
        self.host, self.port = self._listener.server_address
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._listener.serve_forever, daemon=True,
            name="rpc-listener")
        self._thread.start()
        LOG.info("rpc listening on %s:%d", self.host, self.port)

    def shutdown(self) -> None:
        self._listener.shutdown()
        self._listener.server_close()

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    # -- per-connection serving ---------------------------------------
    def _serve_conn(self, sock: socket.socket) -> None:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        codec = FrameCodec(sock)
        wlock = make_lock()
        try:
            while True:
                frame = codec.read_frame()
                if frame is None:
                    return
                seq, method, args = frame
                t = threading.Thread(
                    target=self._dispatch, daemon=True,
                    args=(codec, wlock, seq, method, args),
                    name=f"rpc-{method}")
                t.start()
        except (ConnectionError, OSError):
            return

    def _dispatch(self, codec: FrameCodec, wlock: threading.Lock,
                  seq: int, method: str, args: Dict) -> None:
        err: Optional[str] = None
        result: Any = None
        fn = self.methods.get(method)
        if fn is None:
            err = f"unknown rpc method: {method}"
        else:
            try:
                if self.raft is not None and method in WRITE_METHODS \
                        and not self.raft.is_leader():
                    result = self.raft.forward_rpc(method, args or {})
                else:
                    result = fn(args or {})
            except RpcRefused as e:
                # deliberate refusal (stopped raft node, fenced
                # leader): still an error to the caller, but expected
                # during staggered teardown — debug, not a traceback
                LOG.debug("rpc %s refused: %s", method, e)
                err = f"{type(e).__name__}: {e}"
            except Exception as e:          # surfaced to the caller
                LOG.exception("rpc %s failed", method)
                err = f"{type(e).__name__}: {e}"
        try:
            with wlock:
                codec.write_frame([seq, err, result])
        except (ConnectionError, OSError):
            pass
