"""The wire RPC layer: server<->client communication over TCP.

Reference: nomad/rpc.go (net/rpc + msgpack codec over yamux multiplexed
TCP, :24-30), helper/pool/pool.go (connection pooling), and the
client-side long-poll semantics of node_endpoint.go Node.GetClientAllocs
(:926). The rebuild keeps the shape — seq-tagged request/response frames
with server-side blocking queries — but replaces yamux stream
multiplexing with seq-demultiplexed concurrent requests on one TCP
connection (each request is served by its own handler thread; responses
are written under a lock and matched by seq client-side).
"""

from .codec import FrameCodec, RpcError, RpcRefused
from .server import RpcServer
from .client import RpcClient
from .transport import (ServerTransport, InProcTransport, RemoteTransport)

__all__ = ["FrameCodec", "RpcError", "RpcRefused", "RpcServer",
           "RpcClient", "ServerTransport", "InProcTransport",
           "RemoteTransport"]
