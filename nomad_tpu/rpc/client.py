"""Client-side RPC connection with seq demultiplexing and reconnect.

One TCP connection carries concurrent in-flight calls: a reader thread
matches response frames to waiting callers by seq (the role yamux +
net/rpc's pending map plays in the reference, helper/pool/pool.go).
On connection failure every pending call errors out and the next call
redials — the caller (the client agent's retry loops) owns backoff.
"""

from __future__ import annotations

import itertools
import socket
import threading
from typing import Any, Dict, Optional

from .codec import FrameCodec, RpcError
from ..utils.locks import make_lock


class _Pending:
    __slots__ = ("event", "result", "error")

    def __init__(self):
        self.event = threading.Event()
        self.result: Any = None
        self.error: Optional[str] = None


class RpcClient:
    def __init__(self, addr: str, dial_timeout_s: float = 5.0):
        host, _, port = addr.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self.dial_timeout_s = dial_timeout_s
        self._seq = itertools.count(1)
        self._lock = make_lock()          # connection + write lock
        self._codec: Optional[FrameCodec] = None
        self._pending: Dict[int, _Pending] = {}
        self._closed = False

    # -- connection management ----------------------------------------
    def _ensure_conn(self) -> FrameCodec:
        with self._lock:
            if self._codec is not None:
                return self._codec
            if self._closed:
                raise RpcError("client closed")
            sock = socket.create_connection(
                (self.host, self.port), timeout=self.dial_timeout_s)
            sock.settimeout(None)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._codec = FrameCodec(sock)
            t = threading.Thread(target=self._read_loop, daemon=True,
                                 args=(self._codec,), name="rpc-reader")
            t.start()
            return self._codec

    def _read_loop(self, codec: FrameCodec) -> None:
        try:
            while True:
                frame = codec.read_frame()
                if frame is None:
                    break
                seq, err, result = frame
                p = self._pending.pop(seq, None)
                if p is not None:
                    p.error = err
                    p.result = result
                    p.event.set()
        except (ConnectionError, OSError, RpcError):
            pass
        # connection died: fail everything in flight
        with self._lock:
            if self._codec is codec:
                self._codec = None
        for seq in list(self._pending):
            p = self._pending.pop(seq, None)
            if p is not None:
                p.error = "connection lost"
                p.event.set()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._codec is not None:
                try:
                    self._codec.sock.close()
                except OSError:
                    pass
                self._codec = None

    # -- calls ---------------------------------------------------------
    def call(self, method: str, args: Optional[Dict] = None,
             timeout_s: float = 60.0) -> Any:
        codec = self._ensure_conn()
        seq = next(self._seq)
        p = _Pending()
        self._pending[seq] = p
        try:
            with self._lock:
                codec.write_frame([seq, method, args or {}])
        except (ConnectionError, OSError) as e:
            self._pending.pop(seq, None)
            with self._lock:
                if self._codec is codec:
                    self._codec = None
            raise RpcError(f"send failed: {e}") from e
        if not p.event.wait(timeout_s):
            self._pending.pop(seq, None)
            raise RpcError(f"rpc {method} timed out after {timeout_s}s")
        if p.error is not None:
            raise RpcError(p.error)
        return p.result
