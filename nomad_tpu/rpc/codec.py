"""Wire framing and codec.

Frame layout: 4-byte big-endian length prefix + payload. The payload is
a 3-element array:
    request:  [seq, method, args]
    response: [seq, error-or-None, result]
encoded by a pluggable codec backend — msgpack by default (the
reference's go-msgpack codec, nomad/rpc.go:27), with the native C++
codec slot reserved (utils/native). Model objects cross the wire as
plain dicts via utils/codec.to_wire/from_wire.
"""

from __future__ import annotations

import socket
import struct
from typing import Any, Optional, Tuple

MAX_FRAME = 64 * 1024 * 1024


class RpcError(Exception):
    """Server-side error surfaced to the caller."""


class RpcRefused(RuntimeError):
    """Expected refusal a handler raises on purpose (e.g. a stopped
    raft node rejecting AppendEntries from a still-live leader during
    staggered shutdown, or a deposed leader refusing a forwarded
    write). The dispatcher surfaces it to the caller like any error
    but logs it at debug — it is a protocol outcome, not a server
    fault, and must not produce tracebacks on clean teardown or
    leadership movement. Subclasses RuntimeError so callers guarding
    raft writes with `except RuntimeError` treat a refusal exactly
    like the equivalent in-process raise."""


def _default_backend():
    # the native C++ codec (nomad_tpu/native/codec.cpp) when it builds
    # and self-checks; python-msgpack otherwise — both speak standard
    # msgpack, so mixed clusters interoperate
    from ..native import load_codec
    native = load_codec()
    if native is not None:
        return native.packb, native.unpackb

    import msgpack

    def dumps(obj):
        return msgpack.packb(obj, use_bin_type=True)

    def loads(buf):
        return msgpack.unpackb(buf, raw=False, strict_map_key=False)

    return dumps, loads


class FrameCodec:
    """Reads/writes length-prefixed frames on a socket."""

    def __init__(self, sock: socket.socket, backend=None):
        self.sock = sock
        self._dumps, self._loads = backend or _default_backend()
        self._rbuf = b""

    def write_frame(self, payload: Any) -> None:
        buf = self._dumps(payload)
        self.sock.sendall(struct.pack(">I", len(buf)) + buf)

    def read_frame(self) -> Optional[Any]:
        """One frame, or None on clean EOF."""
        header = self._read_exact(4)
        if header is None:
            return None
        (length,) = struct.unpack(">I", header)
        if length > MAX_FRAME:
            raise RpcError(f"frame too large: {length}")
        body = self._read_exact(length)
        if body is None:
            return None
        return self._loads(body)

    def _read_exact(self, n: int) -> Optional[bytes]:
        while len(self._rbuf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                return None
            self._rbuf += chunk
        out, self._rbuf = self._rbuf[:n], self._rbuf[n:]
        return out
