"""The client agent's narrow server surface, in-proc or over the wire.

The client agent only ever needs five verbs (client/client.go's RPC
usage): register, status update, heartbeat, long-poll allocs, push
alloc status. `InProcTransport` binds them to a Server object in the
same process (dev agent); `RemoteTransport` sends them through
RpcClient — the same split as the reference's dev-mode agent embedding
a server vs. a real cluster (command/agent/agent.go).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..models import Allocation, Node
from ..utils.codec import from_wire, to_wire


class ServerTransport:
    """Interface: what the client agent needs from a server."""

    def register_node(self, node: Node) -> float:
        raise NotImplementedError

    def update_node_status(self, node_id: str, status: str) -> None:
        raise NotImplementedError

    def heartbeat(self, node_id: str,
                  stats: Optional[dict] = None) -> float:
        """TTL renewal; `stats` is the optional compact host-stats
        summary the server folds into its cluster rollup (ISSUE 13)."""
        raise NotImplementedError

    def get_client_allocs(self, node_id: str, min_index: int,
                          max_wait_s: float
                          ) -> Tuple[List[Allocation], int]:
        raise NotImplementedError

    def update_alloc_status(self, allocs: List[Allocation]) -> None:
        raise NotImplementedError

    def update_alloc_status_batch(
            self, groups: List[List[Allocation]]) -> None:
        """Push N update groups in ONE verb (Node.UpdateAllocBatch,
        ISSUE 19): each group keeps its own eval derivation, all of
        them coalesce into one raft entry server-side. Default bridges
        to per-group pushes so custom transports keep working."""
        for g in groups:
            self.update_alloc_status(g)

    def derive_vault_token(self, alloc_id: str, tasks) -> dict:
        raise NotImplementedError

    def renew_vault_token(self, accessor: str, token: str) -> float:
        """Extend a derived token's lease; returns the new lease TTL.
        Raises if the lease is unknown or expired (re-derive then)."""
        raise NotImplementedError

    def update_services(self, upserts=None, delete_alloc_ids=None,
                        delete_ids=None) -> None:
        """Sync this client's service registrations into the catalog
        (the reference's Consul sync, command/agent/consul)."""
        raise NotImplementedError

    def get_csi_volume(self, namespace: str, volume_id: str):
        """Volume record stub (plugin_id + modes) for the client's CSI
        mount hook (csi_endpoint.go CSIVolume.Get)."""
        raise NotImplementedError


def _alloc_with_node(server, alloc_id: str):
    """{alloc: wire, node_rpc: addr} or None — the alloc-watcher's
    view of a predecessor (status + where to pull its disk from)."""
    from ..utils.codec import to_wire
    alloc = server.store.alloc_by_id(alloc_id)
    if alloc is None:
        return None
    node = server.store.node_by_id(alloc.node_id)
    node_rpc = ""
    if node is not None:
        node_rpc = node.attributes.get("nomad.client.rpc", "")
    return {"alloc": {"client_status": alloc.client_status,
                      "desired_status": alloc.desired_status,
                      "node_id": alloc.node_id},
            "node_rpc": node_rpc}


class InProcTransport(ServerTransport):
    def __init__(self, server):
        self.server = server

    def register_node(self, node: Node) -> float:
        self.server.register_node(node)
        return self.server.config.heartbeat_ttl_s

    def update_node_status(self, node_id: str, status: str) -> None:
        self.server.update_node_status(node_id, status)

    def heartbeat(self, node_id: str,
                  stats: Optional[dict] = None) -> float:
        return self.server.heartbeat(node_id, stats=stats)

    def get_client_allocs(self, node_id: str, min_index: int,
                          max_wait_s: float
                          ) -> Tuple[List[Allocation], int]:
        store = self.server.store
        if min_index > 0:
            store.block_min_index(min_index, timeout_s=max_wait_s)
        snap = store.snapshot()
        return snap.allocs_by_node(node_id), snap.latest_index()

    def update_alloc_status(self, allocs: List[Allocation]) -> None:
        self.server.update_alloc_status_from_client(allocs)

    def update_alloc_status_batch(
            self, groups: List[List[Allocation]]) -> None:
        self.server.update_alloc_status_from_client_batch(groups)

    def derive_vault_token(self, alloc_id: str, tasks) -> dict:
        return self.server.derive_vault_token(alloc_id, list(tasks))

    def renew_vault_token(self, accessor: str, token: str) -> float:
        return self.server.renew_vault_token(accessor, token)

    def update_services(self, upserts=None, delete_alloc_ids=None,
                        delete_ids=None) -> None:
        self.server.update_service_registrations(
            upserts=upserts, delete_alloc_ids=delete_alloc_ids,
            delete_ids=delete_ids)

    def get_alloc(self, alloc_id: str):
        return _alloc_with_node(self.server, alloc_id)

    def get_csi_volume(self, namespace: str, volume_id: str):
        v = self.server.store.csi_volume(namespace, volume_id)
        return v.stub() if v is not None else None


class RemoteTransport(ServerTransport):
    def __init__(self, addr: str):
        from .client import RpcClient
        self.rpc = RpcClient(addr)

    def close(self) -> None:
        self.rpc.close()

    def register_node(self, node: Node) -> float:
        res = self.rpc.call("Node.Register", {"node": to_wire(node)})
        return float(res.get("heartbeat_ttl_s", 10.0))

    def update_node_status(self, node_id: str, status: str) -> None:
        self.rpc.call("Node.UpdateStatus",
                      {"node_id": node_id, "status": status})

    def heartbeat(self, node_id: str,
                  stats: Optional[dict] = None) -> float:
        args = {"node_id": node_id}
        if stats:
            args["stats"] = stats
        return float(self.rpc.call("Node.Heartbeat", args)["ttl_s"])

    def get_client_allocs(self, node_id: str, min_index: int,
                          max_wait_s: float
                          ) -> Tuple[List[Allocation], int]:
        res = self.rpc.call(
            "Node.GetClientAllocs",
            {"node_id": node_id, "min_index": min_index,
             "max_wait_s": max_wait_s},
            timeout_s=max_wait_s + 30.0)
        allocs = [from_wire(Allocation, a) for a in res["allocs"]]
        return allocs, int(res["index"])

    def update_alloc_status(self, allocs: List[Allocation]) -> None:
        self.rpc.call("Node.UpdateAlloc",
                      {"allocs": [to_wire(a) for a in allocs]})

    def update_alloc_status_batch(
            self, groups: List[List[Allocation]]) -> None:
        self.rpc.call("Node.UpdateAllocBatch",
                      {"updates": [[to_wire(a) for a in g]
                                   for g in groups]})

    def derive_vault_token(self, alloc_id: str, tasks) -> dict:
        return self.rpc.call("Node.DeriveVaultToken",
                             {"alloc_id": alloc_id,
                              "tasks": list(tasks)})["tokens"]

    def renew_vault_token(self, accessor: str, token: str) -> float:
        return float(self.rpc.call(
            "Node.RenewVaultToken",
            {"accessor": accessor, "token": token})["lease_s"])

    def update_services(self, upserts=None, delete_alloc_ids=None,
                        delete_ids=None) -> None:
        self.rpc.call("Service.Update",
                      {"upserts": [to_wire(s) for s in upserts or []],
                       "delete_alloc_ids": list(delete_alloc_ids or []),
                       "delete_ids": list(delete_ids or [])})

    def get_alloc(self, alloc_id: str):
        """Status + owning-node info of any alloc (the alloc-watcher's
        predecessor probe, client/allocwatcher)."""
        return self.rpc.call("Alloc.GetAlloc", {"alloc_id": alloc_id})

    def get_csi_volume(self, namespace: str, volume_id: str):
        res = self.rpc.call("CSIVolume.Get",
                            {"namespace": namespace,
                             "volume_id": volume_id})
        return res.get("volume")
