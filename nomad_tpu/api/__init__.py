from .http import HTTPApiServer
from .client import ApiClient, ApiError
