"""North-bound HTTP JSON API.

Reference semantics: command/agent/http.go (route registry :252-350,
blocking-query params index/wait, X-Nomad-Index response header) and the
per-domain handlers in command/agent/*_endpoint.go. Routes:

  GET/PUT  /v1/jobs                    list / register
  GET/DELETE /v1/job/<id>              read / deregister (?purge=true)
  GET      /v1/job/<id>/allocations|evaluations|summary|versions
  GET      /v1/nodes, /v1/node/<id>, /v1/node/<id>/allocations
  POST     /v1/node/<id>/eligibility|drain
  GET      /v1/allocations, /v1/allocation/<id>
  GET      /v1/evaluations, /v1/evaluation/<id>
  GET      /v1/status/leader, /v1/agent/self, /v1/operator/scheduler/configuration
"""

from __future__ import annotations

import http.client
import json
import os
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from ..jobspec import parse_job
from ..jobspec.parse import parse_duration_s
from ..models import Job, NODE_SCHED_ELIGIBLE, NODE_SCHED_INELIGIBLE
from ..models.node import DrainSpec, DrainStrategy
from ..server.eval_broker import AdmissionOverloadError
from ..utils.codec import from_wire, to_wire


def _write_chunk(wfile, data: bytes) -> None:
    """One chunked-transfer-encoding frame (shared by the streaming
    endpoints and the federation proxy)."""
    wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
    wfile.flush()


class PlainText:
    """A route payload served verbatim as text/plain instead of JSON
    (the Prometheus exposition at /v1/metrics?format=prometheus)."""

    __slots__ = ("text", "content_type")

    def __init__(self, text: str,
                 content_type: str = "text/plain; version=0.0.4; "
                                     "charset=utf-8"):
        self.text = text
        self.content_type = content_type


class HTTPApiServer:
    def __init__(self, server, host: str = "127.0.0.1", port: int = 4646,
                 alloc_dir_bases=None, region_peers=None):
        self.server = server
        # where co-located clients keep alloc dirs — lets the agent
        # serve fs/logs endpoints directly (the reference forwards
        # these to the client over RPC, client/fs_endpoint.go)
        import tempfile
        self.alloc_dir_bases = list(alloc_dir_bases or []) + [
            os.path.join(tempfile.gettempdir(), "nomad-tpu-allocs")]
        # multi-region federation (nomad/rpc.go forwardRegion): other
        # regions' agent addresses; a request stamped with a foreign
        # region proxies there wholesale, and the remote region
        # enforces its own ACLs. Defaults to the server's configured
        # peers (the same map replication uses).
        self.region_peers: dict = dict(
            region_peers if region_peers is not None
            else getattr(server.config, "region_peers", None) or {})
        api = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _respond(self, code: int, payload, index: Optional[int] = None,
                         headers: Optional[dict] = None):
                if isinstance(payload, PlainText):
                    body = payload.text.encode()
                    ctype = payload.content_type
                else:
                    body = json.dumps(payload).encode()
                    ctype = "application/json"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                if index is not None:
                    self.send_header("X-Nomad-Index", str(index))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _error(self, code: int, msg: str,
                       headers: Optional[dict] = None):
                self._respond(code, {"error": msg}, headers=headers)

            def _read_body_bytes(self) -> bytes:
                """Read (and cache) the raw request body — callers that
                peek at it before routing must not consume it twice."""
                cached = getattr(self, "_body_cache", None)
                if cached is None:
                    length = int(self.headers.get("Content-Length", 0))
                    cached = self.rfile.read(length) if length else b""
                    self._body_cache = cached
                return cached

            def _body(self):
                raw = self._read_body_bytes()
                if not raw:
                    return {}
                return json.loads(raw)

            def _handle(self, method: str):
                try:
                    url = urlparse(self.path)
                    # embedded web UI (the reference serves its Ember
                    # build the same way); data requests out of the
                    # page carry the ACL token themselves
                    if method == "GET" and (
                            url.path == "/" or url.path == "/ui"
                            or url.path.startswith("/ui/")):
                        from .ui import INDEX_HTML
                        body = INDEX_HTML.encode()
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "text/html; charset=utf-8")
                        self.send_header("Content-Length",
                                         str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                        return
                    q = {k: v[0] for k, v in parse_qs(url.query).items()}
                    token = self.headers.get("X-Nomad-Token", "")
                    # region-keyed forwarding (nomad/rpc.go forward:502
                    # -> forwardRegion:638): a foreign-region stamp
                    # proxies the request WHOLESALE before any local
                    # work — local blocking-query indexes, ACLs, and
                    # stream dispatch all belong to the owning region
                    region = q.get("region", "")
                    local_region = getattr(api.server.config, "region",
                                           "global")
                    if region and region != local_region:
                        return api.proxy_region(self, region, method, url)
                    # ACL/namespace WRITES belong to the authoritative
                    # region (the reference forwards them,
                    # acl_endpoint.go/namespace_endpoint.go); accepting
                    # them locally would let the replicator silently
                    # delete them on its next sync
                    auth = getattr(api.server.config,
                                   "authoritative_region", "")
                    if auth and auth != local_region and \
                            method in ("PUT", "POST", "DELETE") and \
                            api._forwards_to_authoritative(self, method,
                                                           url.path):
                        return api.proxy_region(self, auth, method, url)
                    if url.path == "/v1/agent/monitor" and method == "GET":
                        acl = api.server.resolve_token(token)
                        if not (acl.is_management() or acl.allow_agent_read()):
                            raise PermissionError("Permission denied")
                        return api.stream_monitor(self, q)
                    if url.path == "/v1/event/stream" and method == "GET":
                        acl = api.server.resolve_token(token)
                        if not (acl.is_management() or acl.allow_namespace(
                                q.get("namespace", "default"))):
                            raise PermissionError("Permission denied")
                        # topics repeat: ?topic=Job:myjob&topic=Node:*
                        raw = parse_qs(url.query).get("topic", [])
                        return api.stream_events(self, raw,
                                                 int(q.get("index", 0)))
                    # blocking query support (http.go parseWait)
                    if "index" in q:
                        wait_s = parse_duration_s(q.get("wait", "5m"), 300.0)
                        api.server.store.block_min_index(
                            int(q["index"]), timeout_s=min(wait_s, 300.0))
                    body_fn = None
                    if method in ("PUT", "POST"):
                        handler = self

                        def body_fn():
                            return handler._body()
                        # decode-free size signal for the write-path
                        # admission hook: shed happens on the header,
                        # never after the JSON is already materialized
                        body_fn.hint_bytes = int(
                            self.headers.get("Content-Length") or 0)
                    result = api.route(method, url.path, q, body_fn,
                                       token=token)
                    if result is None:
                        self._error(404, "not found")
                    else:
                        payload, index = result
                        self._respond(200, payload, index)
                except PermissionError as e:
                    self._error(403, str(e) or "Permission denied")
                except AdmissionOverloadError as e:
                    # backpressure escalation: the broker's shed valve
                    # is full — refuse at the edge with Retry-After so
                    # well-behaved clients back off instead of piling
                    # onto the delayed heap
                    self._error(429, str(e), headers={
                        "Retry-After":
                        str(max(1, int(round(e.retry_after_s))))})
                except ValueError as e:
                    self._error(400, str(e))
                except KeyError as e:
                    self._error(404, str(e))
                except Exception as e:    # pragma: no cover
                    self._error(500, f"{type(e).__name__}: {e}")

            def do_GET(self):
                self._handle("GET")

            def do_PUT(self):
                self._handle("PUT")

            def do_POST(self):
                self._handle("POST")

            def do_DELETE(self):
                self._handle("DELETE")

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="http-api")
        self._thread.start()

    def shutdown(self) -> None:
        self.httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=2)

    # -- ACL enforcement (command/agent http.go wrap + acl checks) -----
    @staticmethod
    def _enforce(acl, method: str, path: str, ns: str) -> None:
        """Raise PermissionError unless the compiled ACL allows the
        route. Capability mapping follows the reference endpoints'
        aclObj checks (job_endpoint.go, node_endpoint.go, ...)."""
        if acl.is_management():
            return

        def need(ok: bool):
            if not ok:
                raise PermissionError("Permission denied")

        write = method in ("PUT", "POST", "DELETE")
        if path == "/v1/status/leader" or path == "/v1/jobs/parse":
            return
        if path.startswith("/v1/acl/"):
            return                      # own authz in the route bodies
        if path == "/v1/jobs":
            need(acl.allow_namespace_operation(
                ns, "submit-job" if write else "list-jobs"))
            return
        if path.startswith("/v1/job/"):
            cap = "read-job"
            if write:
                cap = "submit-job"
                if path.endswith("/scale"):
                    cap = "scale-job"
                elif path.endswith("/dispatch"):
                    cap = "dispatch-job"
            need(acl.allow_namespace_operation(ns, cap))
            return
        if path == "/v1/nodes" or path.startswith("/v1/node/"):
            sub_write = write or path.endswith(("/drain", "/eligibility"))
            need(acl.allow_node_write() if sub_write
                 else acl.allow_node_read())
            return
        if path.startswith(("/v1/allocation", "/v1/evaluation",
                            "/v1/deployment")):
            need(acl.allow_namespace_operation(
                ns, "submit-job" if write else "read-job"))
            return
        if path.startswith("/v1/client/fs/"):
            # logs need read-logs; browsing/reading arbitrary files
            # needs read-fs (the reference splits these capabilities)
            if path.startswith("/v1/client/fs/logs/"):
                need(acl.allow_namespace_operation(ns, "read-logs"))
            else:
                need(acl.allow_namespace_operation(ns, "read-fs"))
            return
        if path == "/v1/client/stats":
            # host stats are node-scoped reads (stats_endpoint.go
            # aclObj.AllowNodeRead)
            need(acl.allow_node_read())
            return
        if path.startswith("/v1/client/allocation/"):
            # restart/signal are lifecycle control; exec is its own,
            # stronger capability (acl.NamespaceCapabilityAllocExec /
            # AllocLifecycle); stats is a plain alloc read
            # (alloc_endpoint.go Stats -> AllowNsOp ReadJob)
            if path.endswith("/stats"):
                need(acl.allow_namespace_operation(ns, "read-job"))
            elif path.endswith(("/restart", "/signal")):
                need(acl.allow_namespace_operation(ns, "alloc-lifecycle"))
            else:
                need(acl.allow_namespace_operation(ns, "alloc-exec"))
            return
        if path == "/v1/volumes" or path.startswith("/v1/volume/"):
            need(acl.allow_namespace_operation(
                ns, "csi-write-volume" if write else "csi-read-volume"))
            return
        if path == "/v1/scaling/policies" or \
                path.startswith("/v1/scaling/policy/"):
            # the autoscaler's read surface needs only job-read
            # capabilities (nomad/scaling_endpoint.go aclObj checks:
            # ListPolicies list-jobs, GetPolicy read-job)
            need(acl.allow_namespace_operation(
                ns, "list-jobs" if path == "/v1/scaling/policies"
                else "read-job"))
            return
        if path == "/v1/namespaces":
            # list is allowed for any namespace capability; the route
            # filters the result to namespaces the token can read
            need(not write and (acl.allow_namespace(ns)
                                or acl.allow_node_read()
                                or acl.allow_operator_read()))
            return
        m_ns = re.match(r"^/v1/namespace/([^/]+)$", path)
        if m_ns:
            # reads authorize against the namespace NAMED IN THE PATH
            # (not the caller-chosen ?namespace= param); writes are an
            # operator surface (namespace_endpoint.go aclObj checks)
            need(acl.allow_operator_write() if write
                 else (acl.allow_namespace(m_ns.group(1))
                       or acl.allow_operator_read()))
            return
        if path == "/v1/services" or path.startswith("/v1/service/"):
            # service discovery reads ride read-job; deregistration is
            # a job-write-shaped operation
            need(acl.allow_namespace_operation(
                ns, "submit-job" if write else "read-job"))
            return
        if path == "/v1/search":
            need(acl.allow_namespace(ns) or acl.allow_node_read())
            return
        if path.startswith("/v1/agent") or path == "/v1/metrics":
            need(acl.allow_agent_write() if write else acl.allow_agent_read())
            return
        if path.startswith(("/v1/operator", "/v1/event/sink")):
            # sink CRUD is an operator surface (event_sink_manager.go)
            need(acl.allow_operator_write() if write
                 else acl.allow_operator_read())
            return
        if path.startswith("/v1/system"):
            need(acl.allow_operator_write())
            return
        raise PermissionError("Permission denied")

    # -- routing -------------------------------------------------------
    def route(self, method: str, path: str, q: dict, body_fn, token: str = ""):
        s = self.server
        store = s.store
        idx = store.latest_index()
        ns = q.get("namespace", "default")

        acl = s.resolve_token(token)
        if s.config.acl_enabled:
            self._enforce(acl, method, path, ns)

        if path.startswith("/v1/acl/"):
            return self._route_acl(method, path, body_fn, acl, token)

        return self._route_main(method, path, q, body_fn, ns, idx,
                                acl=acl)

    def _forwards_to_authoritative(self, handler, method: str,
                                   path: str) -> bool:
        """Which writes belong to the authoritative region: namespace
        CRUD, ACL policy CRUD, and GLOBAL token operations (local
        tokens stay regional — acl_endpoint.go UpsertTokens)."""
        if path.startswith("/v1/namespace/"):
            return True
        if path.startswith("/v1/acl/policy"):
            return True
        if path == "/v1/acl/token" and method in ("PUT", "POST"):
            try:
                body = json.loads(handler._read_body_bytes() or b"{}")
            except ValueError:
                return False
            return bool(body.get("global") or body.get("global_"))
        m = re.match(r"^/v1/acl/token/([^/]+)$", path)
        if m:
            tok = self.server.store.acl_token_by_accessor(m.group(1))
            return tok is not None and tok.global_
        return False

    def proxy_region(self, handler, region: str, method: str, url,
                     body: Optional[bytes] = None) -> None:
        """Proxy one request raw to the named region's agent
        (forwardRegion) and relay the response verbatim — remote status
        codes pass through untouched, and chunked bodies (event/monitor
        streams, blocking queries) relay frame-by-frame. Writes the
        response on `handler` directly."""
        import urllib.error
        import urllib.request
        from urllib.parse import urlencode
        peer = self.region_peers.get(region)
        if not peer:
            raise KeyError(f"no path to region {region!r}")
        # rebuild the query preserving repeated params (?topic=a&topic=b)
        pairs = [(k, v) for k, vs in parse_qs(url.query).items()
                 if k != "region" for v in vs]
        target = f"http://{peer}{url.path}"
        if pairs:
            target += "?" + urlencode(pairs)
        data = body
        if data is None and method in ("PUT", "POST"):
            data = handler._read_body_bytes() or b"{}"
        headers = {"Content-Type": "application/json"}
        token = handler.headers.get("X-Nomad-Token", "")
        if token:
            headers["X-Nomad-Token"] = token
        req = urllib.request.Request(target, data=data, method=method,
                                     headers=headers)
        # read timeout must outlive the remote's 300 s blocking-query
        # cap; streams heartbeat every <=5 s so reads never idle long
        try:
            resp = urllib.request.urlopen(req, timeout=330)
        except urllib.error.HTTPError as e:
            resp = e                     # file-like; relay code + body
        except urllib.error.URLError as e:
            raise RuntimeError(f"no route to region {region!r}: {e.reason}")
        with resp:
            try:
                self._relay_response(handler, resp)
            except (BrokenPipeError, ConnectionResetError, OSError,
                    http.client.HTTPException):
                # either side went away mid-body (HTTPException covers
                # IncompleteRead from a dying remote); headers are
                # already sent, so there's nothing valid left to write
                pass

    @staticmethod
    def _relay_response(handler, resp) -> None:
        code = getattr(resp, "status", None) or resp.code
        handler.send_response(code)
        handler.send_header("Content-Type", resp.headers.get(
            "Content-Type", "application/json"))
        ridx = resp.headers.get("X-Nomad-Index")
        if ridx:
            handler.send_header("X-Nomad-Index", ridx)
        clen = resp.headers.get("Content-Length")
        if clen is not None:
            handler.send_header("Content-Length", clen)
            handler.end_headers()
            handler.wfile.write(resp.read(int(clen)))
            return
        # chunked stream: relay each piece as it arrives (read1 returns
        # what's buffered instead of blocking for a full read)
        handler.send_header("Transfer-Encoding", "chunked")
        handler.end_headers()
        while True:
            chunk = resp.read1(65536)
            if not chunk:
                break
            _write_chunk(handler.wfile, chunk)
        handler.wfile.write(b"0\r\n\r\n")

    def _route_acl(self, method: str, path: str, body_fn, acl, token: str):
        """ACL endpoints (nomad/acl_endpoint.go): bootstrap once without
        a token; token/self with any valid token; everything else needs
        a management token."""
        s = self.server
        store = s.store
        idx = store.latest_index()

        if path == "/v1/acl/bootstrap" and method in ("PUT", "POST"):
            tok = s.bootstrap_acl()
            return to_wire(tok), store.latest_index()

        if path == "/v1/acl/token/self" and method == "GET":
            tok = store.acl_token_by_secret(token) if token else None
            if tok is None:
                raise PermissionError("ACL token not found")
            return to_wire(tok), idx

        if s.config.acl_enabled and not acl.is_management():
            raise PermissionError("Permission denied")

        from ..acl import AclPolicy
        if path == "/v1/acl/policies" and method == "GET":
            return [{"name": p.name, "description": p.description,
                     "modify_index": p.modify_index}
                    for p in store.acl_policies()], idx
        m = re.match(r"^/v1/acl/policy/([^/]+)$", path)
        if m:
            name = m.group(1)
            if method == "GET":
                p = store.acl_policy(name)
                return (to_wire(p), idx) if p else None
            if method in ("PUT", "POST"):
                data = body_fn()
                p = AclPolicy(name=name,
                              description=data.get("description", ""),
                              rules=data.get("rules", ""))
                s.upsert_acl_policies([p])
                return {"ok": True}, store.latest_index()
            if method == "DELETE":
                s.delete_acl_policies([name])
                return {"ok": True}, store.latest_index()
        if path == "/v1/acl/tokens" and method == "GET":
            return [t.stub() for t in store.acl_tokens()], idx
        if path == "/v1/acl/token" and method in ("PUT", "POST"):
            data = body_fn()
            tok = s.create_acl_token(
                name=data.get("name", ""),
                type_=data.get("type", "client"),
                policies=data.get("policies") or [],
                global_=bool(data.get("global", False)))
            return to_wire(tok), store.latest_index()
        m = re.match(r"^/v1/acl/token/([^/]+)$", path)
        if m:
            accessor = m.group(1)
            if method == "GET":
                tok = store.acl_token_by_accessor(accessor)
                return (to_wire(tok), idx) if tok else None
            if method == "DELETE":
                s.delete_acl_tokens([accessor])
                return {"ok": True}, store.latest_index()
        return None

    def _admit_write(self, body_fn=None) -> None:
        """The single write-path admission hook (ISSUE 19 satellite):
        every eval-creating write — register, bulk register, dispatch,
        evaluate, periodic force — funnels through here instead of
        copy-pasting the broker valve per route. Order matters: the
        ingest gateway's queue watermark sheds FIRST, before the body
        is decoded (the hint rides Content-Length via
        body_fn.hint_bytes), then the broker's delayed-heap valve runs.
        Both raise AdmissionOverloadError -> 429 + Retry-After."""
        s = self.server
        ing = getattr(s, "ingest", None)
        if ing is not None:
            ing.check_admission(
                int(getattr(body_fn, "hint_bytes", 0) or 0))
        s.eval_broker.check_register_admission()

    def _register_jobs_bulk(self, items: list) -> list:
        """Array-body `PUT /v1/jobs`: each element is the same
        envelope the single register takes ({"Job": ...} / {"job": ...}
        / bare spec / HCL string). Specs decode through the dedup pool
        (a storm of near-identical jobs materializes shared subtrees
        once), then the whole admitted run parks on the ingest gateway
        as one batch. A bad item fails ONLY its own slot; EnforceIndex
        CAS is a per-job serialization concern the coalesced path
        cannot honor, so those items error individually."""
        from ..state.columnar import WirePool, from_wire_pooled
        pool = WirePool()
        jobs = []               # parallel to items: Job | Exception
        for data in items:
            try:
                if not isinstance(data, dict):
                    raise ValueError(
                        "bulk register items must be objects")
                if data.get("EnforceIndex"):
                    raise ValueError(
                        "EnforceIndex is not supported in bulk "
                        "register; submit CAS registers individually")
                spec = data.get("Job", data.get("job", data))
                jobs.append(from_wire_pooled(Job, spec, pool)
                            if isinstance(spec, dict)
                            else parse_job(spec))
            except (ValueError, KeyError, TypeError) as e:
                jobs.append(e)
        results = iter(self.server.register_jobs_bulk(
            [j for j in jobs if not isinstance(j, Exception)]))
        out = []
        for j in jobs:
            r = j if isinstance(j, Exception) else next(results)
            if isinstance(r, Exception):
                out.append({"Error": str(r)})
            else:
                out.append({"EvalID": r.id if r is not None else "",
                            "JobModifyIndex": j.job_modify_index
                            or j.modify_index})
        return out

    def _route_main(self, method: str, path: str, q: dict, body_fn,
                    ns: str, idx: int, acl=None):
        s = self.server
        store = s.store

        if path == "/v1/jobs":
            if method == "GET":
                prefix = q.get("prefix", "")
                jobs = [self._job_stub(j) for j in store.jobs(ns)
                        if j.id.startswith(prefix)]
                return jobs, idx
            if method in ("PUT", "POST"):
                # backpressure escalation: refuse NEW work at the edge
                # while the ingest queue or the broker's delayed heap
                # is over watermark (429 + Retry-After) — before the
                # body is decoded; internal requeues and
                # already-admitted evals are never refused
                self._admit_write(body_fn)
                data = body_fn()
                if isinstance(data, list):
                    # array body = bulk register (ISSUE 19): the whole
                    # batch parks on the ingest gateway and lands as
                    # one raft entry; per-item results in order
                    return self._register_jobs_bulk(data), \
                        store.latest_index()
                spec = data.get("Job", data.get("job", data))
                job = from_wire(Job, spec) if isinstance(spec, dict) \
                    else parse_job(spec)
                # `job run -check-index` CAS (job_endpoint.go Register
                # EnforceIndex + JobModifyIndex)
                ev = s.register_job(
                    job,
                    enforce_index=bool(data.get("EnforceIndex")),
                    job_modify_index=int(data.get("JobModifyIndex")
                                         or 0))
                # periodic/parameterized registrations create no eval
                return {"EvalID": ev.id if ev is not None else "",
                        "JobModifyIndex": job.job_modify_index
                        or job.modify_index}, \
                    store.latest_index()

        if path == "/v1/jobs/parse" and method in ("PUT", "POST"):
            data = body_fn()
            job = parse_job(data.get("JobHCL", ""))
            return to_wire(job), idx

        m = re.match(r"^/v1/job/([^/]+)$", path)
        if m:
            job_id = m.group(1)
            if method == "GET":
                job = store.job_by_id(ns, job_id)
                if job is None:
                    return None
                return to_wire(job), idx
            if method == "DELETE":
                purge = q.get("purge", "").lower() == "true"
                if q.get("global", "").lower() == "true":
                    # multiregion stop fans to every region in the
                    # job's multiregion block (nomad job stop -global)
                    ev = s.deregister_job_global(ns, job_id, purge=purge)
                else:
                    ev = s.deregister_job(ns, job_id, purge=purge)
                return {"EvalID": ev.id}, store.latest_index()

        m = re.match(r"^/v1/job/([^/]+)/(\w+)$", path)
        if m:
            job_id, sub = m.group(1), m.group(2)
            if sub == "allocations":
                return [a.stub() for a in store.allocs_by_job(ns, job_id)], idx
            if sub == "evaluations":
                return [e.stub() for e in store.evals_by_job(ns, job_id)], idx
            if sub == "summary":
                summ = store.job_summary(ns, job_id)
                return (to_wire(summ), idx) if summ else None
            if sub == "versions":
                return [to_wire(j) for j in store.job_versions(ns, job_id)], idx
            if sub == "deployments":
                return [to_wire(d)
                        for d in store.deployments_by_job(ns, job_id)], idx
            if sub == "dispatch" and method in ("PUT", "POST"):
                # same edge valve as job register: parameterized
                # dispatch is the designed high-volume eval creator
                self._admit_write(body_fn)
                import base64 as _b64
                data = body_fn()
                payload = data.get("Payload") or data.get("payload") or ""
                ev = s.dispatch_job(
                    ns, job_id,
                    payload=_b64.b64decode(payload) if payload else b"",
                    meta=data.get("Meta") or data.get("meta") or {})
                return {"DispatchedJobID": ev.job_id,
                        "EvalID": ev.id}, store.latest_index()
            if sub == "evaluate" and method in ("PUT", "POST"):
                # force a fresh evaluation (job_endpoint.go Evaluate)
                self._admit_write(body_fn)
                ev = s.evaluate_job(ns, job_id)
                return {"EvalID": ev.id}, store.latest_index()
            if sub == "scaling-events":
                return {"ScalingEvents":
                        store.scaling_events(ns, job_id)}, idx

        m = re.match(r"^/v1/job/([^/]+)/periodic/force$", path)
        if m and method in ("PUT", "POST"):
            # launch a periodic job's child NOW (periodic_endpoint.go)
            self._admit_write(body_fn)
            ev = s.periodic.force_run(ns, m.group(1))
            if ev is None:
                return {"EvalID": "", "Skipped": True}, \
                    store.latest_index()
            return {"EvalID": ev.id,
                    "DispatchedJobID": ev.job_id}, store.latest_index()

        if path == "/v1/operator/members" and method == "GET":
            # the replicated voter set (agent_endpoint.go Members /
            # serf members, minus gossip metadata)
            raft = getattr(s, "raft", None)
            return {"Members": store.server_members(),
                    "Leader": raft.leader_addr if raft else "",
                    "ClusterSize": raft.cluster_size if raft else 1}, idx

        if path == "/v1/agent/members" and method == "GET":
            # scheduler-plane member view (ISSUE 16): the voter set
            # annotated with raft role, applied index, fence lag and
            # per-follower leased evals — the data `nomad server
            # members` renders and `operator debug` bundles
            raft = getattr(s, "raft", None)
            return {"Members": store.server_members(),
                    "Leader": raft.leader_addr if raft else "",
                    "ClusterSize": raft.cluster_size if raft else 1,
                    "SchedulerPlane": s.scheduler_plane_status()}, idx

        # durable event sinks (nomad/stream/sink.go CRUD)
        if path == "/v1/event/sinks" and method == "GET":
            return [sk.stub() for sk in store.event_sinks()], idx
        if path == "/v1/event/sink" and method in ("PUT", "POST"):
            from ..server.event_sink import EventSink
            from ..utils.ids import generate_uuid
            data = body_fn()
            sink = EventSink(
                id=data.get("ID") or data.get("id") or generate_uuid(),
                type=data.get("Type") or data.get("type") or "webhook",
                address=data.get("Address") or data.get("address") or "",
                topics=data.get("Topics") or data.get("topics") or {},
                latest_index=int(data.get("LatestIndex")
                                 or data.get("latest_index") or 0))
            if not sink.address:
                raise ValueError("event sink requires an address")
            from ..server.event_sink import SINK_WEBHOOK
            if sink.type != SINK_WEBHOOK:
                raise ValueError(
                    f"unsupported sink type {sink.type!r}; "
                    f"supported: {SINK_WEBHOOK}")
            # a malformed topics filter must be rejected here — a
            # non-dict filter raises inside the broker's publish loop
            # and would break delivery for every OTHER subscriber
            if not isinstance(sink.topics, dict) or not all(
                    isinstance(k, str) and isinstance(v, (list, tuple))
                    and all(isinstance(x, str) for x in v)
                    for k, v in sink.topics.items()):
                raise ValueError(
                    "Topics must map topic names to lists of keys")
            s.upsert_event_sink(sink)
            return {"ID": sink.id}, store.latest_index()
        m = re.match(r"^/v1/event/sink/([^/]+)$", path)
        if m:
            if method == "GET":
                sink = store.event_sink(m.group(1))
                return (sink.stub(), idx) if sink else None
            if method == "DELETE":
                s.delete_event_sink(m.group(1))
                return {}, store.latest_index()

        # autoscaling API: the external autoscaler's read surface
        # (nomad/scaling_endpoint.go:24 ListPolicies, :90 GetPolicy)
        if path == "/v1/scaling/policies" and method == "GET":
            pols = store.scaling_policies(
                namespace=ns, job_id=q.get("job") or None,
                policy_type=q.get("type") or None)
            return [p.stub() for p in pols], idx

        m = re.match(r"^/v1/scaling/policy/([^/]+)$", path)
        if m and method == "GET":
            pol = store.scaling_policy_by_id(m.group(1))
            if pol is None:
                return None
            return to_wire(pol), idx

        # namespaces (nomad/namespace_endpoint.go — the list is
        # filtered to namespaces the token can read)
        if path == "/v1/namespaces" and method == "GET":
            out = [to_wire(n) for n in store.namespaces()
                   if acl is None or not s.config.acl_enabled
                   or acl.is_management() or acl.allow_operator_read()
                   or acl.allow_namespace(n.name)]
            return out, idx

        m = re.match(r"^/v1/namespace/([^/]+)$", path)
        if m:
            name = m.group(1)
            if method == "GET":
                got = store.namespace_by_name(name)
                return (to_wire(got), idx) if got else None
            if method in ("PUT", "POST"):
                from ..models.namespace import Namespace
                data = body_fn() or {}
                ns_obj = Namespace(
                    name=data.get("name", name) or name,
                    description=data.get("description", ""),
                    meta=dict(data.get("meta") or {}))
                s.upsert_namespaces([ns_obj])
                return {"ok": True}, store.latest_index()
            if method == "DELETE":
                s.delete_namespaces([name])
                return {"ok": True}, store.latest_index()

        # built-in service catalog (nomad service list/info; the
        # reference's equivalent discovery surface lives in Consul)
        if path == "/v1/services" and method == "GET":
            return s.list_services(namespace=ns), idx

        m = re.match(r"^/v1/service/([^/]+)$", path)
        if m and method == "GET":
            regs = s.get_service(ns, m.group(1))
            if not regs:
                return None
            return [to_wire(r) for r in regs], idx

        m = re.match(r"^/v1/service/([^/]+)/([^/]+)$", path)
        if m and method == "DELETE":
            # the id must belong to the named service in the token's
            # namespace — a bare id would let a caller deregister
            # across namespace boundaries
            name, rid = m.group(1), m.group(2)
            if not any(r.id == rid
                       for r in store.service_by_name(ns, name)):
                return None
            s.update_service_registrations(delete_ids=[rid])
            return {}, idx

        if path == "/v1/nodes" and method == "GET":
            prefix = q.get("prefix", "")
            return [n.stub() for n in store.nodes()
                    if n.id.startswith(prefix)], idx

        m = re.match(r"^/v1/node/([^/]+)$", path)
        if m and method == "GET":
            node = self._find_node(m.group(1))
            if node is None:
                return None
            return to_wire(node), idx

        m = re.match(r"^/v1/node/([^/]+)/(\w+)$", path)
        if m:
            node = self._find_node(m.group(1))
            if node is None:
                return None
            sub = m.group(2)
            if sub == "allocations" and method == "GET":
                return [a.stub() for a in store.allocs_by_node(node.id)], idx
            if sub == "eligibility" and method in ("PUT", "POST"):
                data = body_fn()
                elig = data.get("Eligibility", "")
                if elig not in (NODE_SCHED_ELIGIBLE, NODE_SCHED_INELIGIBLE):
                    raise ValueError(f"invalid eligibility {elig}")
                s.raft_apply("node_eligibility_update",
                             dict(node_id=node.id, eligibility=elig))
                return {"NodeModifyIndex": store.latest_index()}, \
                    store.latest_index()
            if sub == "drain" and method in ("PUT", "POST"):
                data = body_fn()
                spec = data.get("DrainSpec")
                strategy = None
                if spec:
                    strategy = DrainStrategy(drain_spec=DrainSpec(
                        deadline_s=parse_duration_s(spec.get("Deadline"), 0.0),
                        ignore_system_jobs=bool(
                            spec.get("IgnoreSystemJobs", False))))
                s.update_node_drain(node.id, strategy,
                                    data.get("MarkEligible", False))
                return {"NodeModifyIndex": store.latest_index()}, \
                    store.latest_index()

        if path == "/v1/allocations" and method == "GET":
            prefix = q.get("prefix", "")
            return [a.stub() for a in store.allocs()
                    if a.id.startswith(prefix)], idx

        m = re.match(r"^/v1/allocation/([^/]+)/stop$", path)
        if m and method in ("PUT", "POST"):
            alloc = self._alloc_in_ns(m.group(1), ns)
            if alloc is None:
                return None
            ev = s.stop_alloc(alloc.id)
            return {"EvalID": ev.id}, store.latest_index()

        m = re.match(r"^/v1/allocation/([^/]+)$", path)
        if m and method == "GET":
            alloc = self._unique_prefix(store.allocs(), m.group(1), "allocation")
            if alloc is None:
                return None
            return to_wire(alloc), idx

        if path == "/v1/deployments" and method == "GET":
            prefix = q.get("prefix", "")
            return [to_wire(d) for d in store.deployments()
                    if d.id.startswith(prefix)], idx

        m = re.match(r"^/v1/deployment/([^/]+)/([^/]+)$", path)
        if m:
            action = m.group(1)
            d = self._unique_prefix(store.deployments(), m.group(2),
                                    "deployment")
            if d is None:
                return None
            if action == "allocations" and method == "GET":
                return [a.stub()
                        for a in store.allocs_by_deployment(d.id)], idx
            if method in ("PUT", "POST"):
                if action == "promote":
                    data = body_fn()
                    groups = data.get("Groups")
                    ev = s.promote_deployment(d.id, groups)
                    return {"EvalID": ev.id}, store.latest_index()
                if action == "fail":
                    ev = s.fail_deployment(d.id)
                    return {"EvalID": ev.id if ev else ""}, store.latest_index()
                if action == "pause":
                    data = body_fn()
                    s.pause_deployment(d.id, bool(data.get("Pause", False)))
                    return {"DeploymentModifyIndex": store.latest_index()}, \
                        store.latest_index()

        m = re.match(r"^/v1/deployment/([^/]+)$", path)
        if m and method == "GET":
            d = self._unique_prefix(store.deployments(), m.group(1),
                                    "deployment")
            if d is None:
                return None
            return to_wire(d), idx

        m = re.match(r"^/v1/job/([^/]+)/revert$", path)
        if m and method in ("PUT", "POST"):
            data = body_fn()
            ev = s.revert_job(ns, m.group(1),
                              int(data.get("JobVersion", 0)))
            return {"EvalID": ev.id if ev else ""}, store.latest_index()

        m = re.match(r"^/v1/job/([^/]+)/plan$", path)
        if m and method in ("PUT", "POST"):
            data = body_fn()
            spec = data.get("Job", data)
            job = from_wire(Job, spec) if isinstance(spec, dict) \
                else parse_job(spec)
            result = s.plan_job(job, diff=bool(data.get("Diff", True)))
            return result, idx

        m = re.match(r"^/v1/job/([^/]+)/scale$", path)
        if m:
            job_id = m.group(1)
            if method == "GET":
                job = store.job_by_id(ns, job_id)
                if job is None:
                    return None
                summ = store.job_summary(ns, job_id)
                return {
                    "JobID": job.id, "JobStopped": job.stopped(),
                    "TaskGroups": {
                        tg.name: {"Desired": tg.count,
                                  **(summ.summary.get(tg.name, {})
                                     if summ else {})}
                        for tg in job.task_groups},
                    "ScalingEvents": store.scaling_events(ns, job_id),
                }, idx
            if method in ("PUT", "POST"):
                data = body_fn()
                target = data.get("Target", {})
                ev = s.scale_job(
                    ns, job_id, target.get("Group", ""),
                    count=data.get("Count"),
                    message=data.get("Message", ""),
                    error=bool(data.get("Error", False)))
                return {"EvalID": ev.id if ev else ""}, store.latest_index()

        if path == "/v1/evaluations" and method == "GET":
            return [e.stub() for e in store.evals()], idx

        m = re.match(r"^/v1/evaluation/([^/]+)$", path)
        if m and method == "GET":
            ev = self._unique_prefix(store.evals(), m.group(1), "evaluation")
            if ev is None:
                return None
            return to_wire(ev), idx

        # operator snapshot (nomad operator snapshot save/restore;
        # nomad/operator_endpoint.go SnapshotSave): the full store dump.
        # Restore is allowed only outside raft mode — reseeding one
        # server's FSM under a live replicated log would desync
        # followers (they reseed via raft snapshot install instead).
        if path == "/v1/operator/snapshot":
            if method == "GET":
                snap = store.snapshot()
                return {"index": snap.latest_index(),
                        "snapshot": snap.dump()}, idx
            if method in ("PUT", "POST"):
                if getattr(s, "raft", None) is not None:
                    raise ValueError(
                        "snapshot restore over HTTP is only supported "
                        "on single-server (dev) mode; clustered "
                        "servers reseed via raft")
                data = body_fn() or {}
                payload = data.get("snapshot")
                if not isinstance(payload, dict):
                    raise ValueError("missing snapshot body")
                s.install_snapshot(payload)
                return {"index": store.latest_index()}, \
                    store.latest_index()

        # steady-state governor status (governor/): registered gauges
        # with watermark state, backpressure, and the structured event
        # log (watermark crossings, reclaims, drift findings)
        if path == "/v1/operator/governor" and method == "GET":
            gov = getattr(s, "governor", None)
            if gov is None:
                return {"enabled": False}, idx
            return gov.status(), idx

        # eval flight recorder (nomad_tpu/trace/): recent per-eval
        # span trees, pinned tail exemplars, per-stage p50/p95/p99.
        # ?format=chrome emits Chrome trace-event JSON (one track per
        # worker/gateway/applier) loadable in Perfetto;
        # ?exemplars=true restricts to the pinned exemplar set
        if path == "/v1/operator/trace" and method == "GET":
            from ..trace import tracer
            exemplars_only = str(q.get("exemplars", "")).lower() \
                in ("1", "true")
            limit = max(0, min(int(q.get("n", 32)), 512))
            if q.get("format", "") == "chrome":
                return tracer.export_chrome(
                    limit=limit, exemplars_only=exemplars_only), idx
            return tracer.status(
                limit=limit, exemplars_only=exemplars_only), idx

        # retained telemetry (ISSUE 11): the in-process history ring —
        # chronological gauge/counter/stage/device series plus derived
        # rates; ?n= limits to the most recent N samples. `nomad
        # operator top` renders trends from this instead of a single
        # snapshot
        if path == "/v1/operator/telemetry" and method == "GET":
            tel = getattr(s, "telemetry", None)
            if tel is None:
                return {"enabled": False}, idx
            last = max(0, min(int(q.get("n", 0) or 0), 100000))
            out = tel.status()
            out.update(tel.history(last=last or None))
            return out, idx

        # live flatness verdict (ISSUE 11): bench/soak.flatness_verdict
        # — the soak artifact's pass/fail math — run over the live
        # telemetry ring, so an operator (or the validation campaign)
        # reads steady-state health without a post-hoc harness
        if path == "/v1/operator/flatness" and method == "GET":
            tel = getattr(s, "telemetry", None)
            if tel is None:
                return {"enabled": False, "pass": None}, idx
            out = tel.flatness()
            out["enabled"] = True
            return out, idx

        # operator autopilot configuration (nomad/operator_endpoint.go
        # AutopilotGetConfiguration / AutopilotSetConfiguration)
        if path == "/v1/operator/autopilot/configuration":
            if method == "GET":
                return {"CleanupDeadServers":
                        s.config.dead_server_cleanup_s > 0,
                        "DeadServerCleanupSecs":
                        s.config.dead_server_cleanup_s}, idx
            if method in ("PUT", "POST"):
                data = body_fn() or {}
                if "DeadServerCleanupSecs" in data:
                    s.config.dead_server_cleanup_s = float(
                        data["DeadServerCleanupSecs"])
                elif data.get("CleanupDeadServers") is False:
                    s.config.dead_server_cleanup_s = 0.0
                elif data.get("CleanupDeadServers") is True and \
                        s.config.dead_server_cleanup_s <= 0:
                    s.config.dead_server_cleanup_s = 30.0  # default
                return {"Updated": True}, idx

        if path == "/v1/search" and method in ("PUT", "POST"):
            data = body_fn()
            return self._search(data.get("Prefix", ""),
                                data.get("Context", "all"), ns), idx

        if path == "/v1/status/leader":
            # status_endpoint.go Leader: the raft leader's RPC address;
            # mid-election there IS no leader and saying otherwise would
            # route leader-only traffic at a candidate
            raft = getattr(s, "raft", None)
            if raft is not None:
                if not raft.leader_addr:
                    raise RuntimeError("No cluster leader")
                return raft.leader_addr, idx
            rpc = getattr(s, "rpc_server", None)
            return (rpc.addr if rpc is not None else "127.0.0.1:4647"), idx

        m = re.match(r"^/v1/client/fs/(logs|ls|cat|stream)/([^/]+)$", path)
        if m and method == "GET":
            return self._client_fs(m.group(1), m.group(2), q, ns, idx)

        # client host stats (ISSUE 13; command/agent/stats_endpoint.go
        # — the server proxies to the owning client by node lookup,
        # nomad/client_stats_endpoint.go). ?node_id= picks the node; a
        # single-node cluster (the dev agent) defaults to it
        if path == "/v1/client/stats" and method == "GET":
            node = None
            if q.get("node_id"):
                node = self._find_node(q["node_id"])
                if node is None:
                    return None
            else:
                nodes = s.store.nodes()
                if len(nodes) == 1:
                    node = nodes[0]
                else:
                    raise ValueError(
                        "node_id parameter required on a multi-node "
                        "cluster")
            args = {}
            if q.get("history", "").lower() in ("1", "true"):
                args = {"history": True,
                        "n": max(0, int(q.get("n", 0) or 0))}
            return self._forward_node(node.id, "ClientStats.Host",
                                      args), idx

        # per-alloc ResourceUsage (client/alloc_endpoint.go Stats):
        # live task-level usage from the owning client's sampler
        m = re.match(r"^/v1/client/allocation/([^/]+)/stats$", path)
        if m and method == "GET":
            alloc = self._alloc_in_ns(m.group(1), ns)
            if alloc is None:
                return None
            return self._forward_client(alloc, "ClientStats.Alloc",
                                        {}), idx

        # alloc exec sessions (client/alloc_endpoint.go:163): start
        # returns a session id; io round-trips stdin/stdout frames
        m = re.match(r"^/v1/client/allocation/([^/]+)/(restart|signal)$",
                     path)
        if m and method in ("PUT", "POST"):
            alloc = self._alloc_in_ns(m.group(1), ns)
            if alloc is None:
                return None
            data = body_fn()
            args = {"task": data.get("Task") or data.get("task") or ""}
            if m.group(2) == "signal":
                args["signal"] = data.get("Signal") or data.get("signal")
            out = self._forward_client(
                alloc, "ClientAlloc.Restart" if m.group(2) == "restart"
                else "ClientAlloc.Signal", args)
            return out, idx

        m = re.match(r"^/v1/client/allocation/([^/]+)/exec$", path)
        if m and method in ("PUT", "POST"):
            return self._client_exec_start(m.group(1), body_fn(), ns, idx)
        m = re.match(r"^/v1/client/allocation/([^/]+)/exec/([^/]+)$", path)
        if m:
            if method in ("PUT", "POST"):
                return self._client_exec_io(m.group(1), m.group(2),
                                            body_fn(), ns, idx)
            if method == "DELETE":
                alloc = self._alloc_in_ns(m.group(1), ns)
                if alloc is None:
                    return None
                self._forward_client(alloc, "ClientExec.Stop",
                                     {"session_id": m.group(2)})
                return {}, idx

        if path == "/v1/volumes" and method == "GET":
            vols = store.csi_volumes(ns)
            return [v.stub() for v in vols], idx

        m = re.match(r"^/v1/volume/csi/([^/]+)$", path)
        if m:
            vol_id = m.group(1)
            if method == "GET":
                v = store.csi_volume(ns, vol_id)
                return (to_wire(v), idx) if v else None
            if method in ("PUT", "POST"):
                from ..models.csi import CSIVolume
                data = body_fn()
                spec = data.get("Volume", data.get("volume", data))
                vol = from_wire(CSIVolume, spec)
                vol.id = vol.id or vol_id
                vol.namespace = vol.namespace or ns
                s.register_csi_volume(vol)
                return {"ok": True}, store.latest_index()
            if method == "DELETE":
                s.deregister_csi_volume(
                    ns, vol_id, force=q.get("force", "") == "true")
                return {"ok": True}, store.latest_index()

        if path == "/v1/agent/self":
            return {"member": {"Name": "server", "Status": "alive"},
                    "stats": {"broker": self.server.eval_broker.stats.as_dict()},
                    "config": {"NumSchedulers":
                               self.server.config.num_schedulers}}, idx

        if path == "/v1/metrics" and method == "GET":
            from ..utils import metrics
            # ?format=prometheus: text exposition (histogram buckets +
            # counters + gauges) for a scrape config pointed straight
            # at the agent (ISSUE 11)
            if q.get("format", "") == "prometheus":
                return PlainText(metrics.prometheus()), idx
            return metrics.snapshot(), idx

        if path == "/v1/agent/pprof/cmdline" and method == "GET":
            import sys as _sys
            return {"cmdline": list(_sys.argv)}, idx

        if path == "/v1/agent/pprof/profile" and method == "GET":
            # agent_endpoint.go:339 — CPU profile for ?seconds=N; the
            # Python analog runs cProfile over the window and returns
            # the cumulative-sorted pstats report
            import cProfile
            import io as _io
            import pstats
            import time as _time
            seconds = min(float(q.get("seconds", 1)), 30.0)
            pr = cProfile.Profile()
            pr.enable()
            _time.sleep(seconds)
            pr.disable()
            out = _io.StringIO()
            pstats.Stats(pr, stream=out).sort_stats("cumulative") \
                .print_stats(50)
            return {"profile": out.getvalue(), "seconds": seconds}, idx

        if path == "/v1/agent/pprof/threads" and method == "GET":
            # goroutine-dump analog: all python thread stacks
            import sys as _sys
            import traceback as _tb
            frames = _sys._current_frames()
            import threading as _threading
            names = {t.ident: t.name for t in _threading.enumerate()}
            dump = {}
            for tid, frame in frames.items():
                dump[names.get(tid, str(tid))] = \
                    "".join(_tb.format_stack(frame))
            return {"threads": dump}, idx

        if path == "/v1/operator/raft/configuration" and method == "GET":
            raft = getattr(s, "raft", None)
            if raft is None:
                return {"Servers": [{"Address": "in-process",
                                     "Leader": True, "Term": 0}],
                        "Index": idx}, idx
            with raft._lock:
                servers = [{"Address": raft.self_addr,
                            "Role": raft.role,
                            "Leader": raft.is_leader(),
                            "Term": raft.term,
                            "LastLogIndex": raft.last_log()[0]}]
                for p in raft.peers:
                    servers.append({"Address": p,
                                    "Leader": p == raft.leader_addr})
            return {"Servers": servers, "Index": idx}, idx

        if path == "/v1/system/gc" and method in ("PUT", "POST"):
            s.force_gc()
            return {"ok": True}, idx

        if path == "/v1/operator/scheduler/configuration":
            if method == "GET":
                return {"SchedulerConfig":
                        to_wire(store.scheduler_config())}, idx
            if method in ("PUT", "POST"):
                data = body_fn()
                from ..models import SchedulerConfiguration
                cfg = from_wire(SchedulerConfiguration,
                                data.get("SchedulerConfig", data))
                self.server.raft_apply("scheduler_config", dict(config=cfg))
                return {"Updated": True}, store.latest_index()

        return None

    # -- search (nomad/search_endpoint.go: prefix search, 20-match cap) --
    TRUNCATE_LIMIT = 20

    def _search(self, prefix: str, context: str, ns: str) -> dict:
        store = self.server.store
        sources = {
            "jobs": lambda: [j.id for j in store.jobs(ns)],
            "nodes": lambda: [n.id for n in store.nodes()],
            "allocs": lambda: [a.id for a in store.allocs()],
            "evals": lambda: [e.id for e in store.evals()],
            "deployment": lambda: [d.id for d in store.deployments()],
        }
        if context != "all":
            if context not in sources:
                raise ValueError(f"invalid search context {context!r}")
            sources = {context: sources[context]}
        matches, truncations = {}, {}
        for name, fn in sources.items():
            ids = sorted(i for i in fn() if i.startswith(prefix))
            truncations[name] = len(ids) > self.TRUNCATE_LIMIT
            matches[name] = ids[:self.TRUNCATE_LIMIT]
        return {"Matches": matches, "Truncations": truncations}

    # -- event stream (nomad/stream/ndjson.go over chunked HTTP) --------
    def _alloc_base(self, alloc_id: str) -> Optional[str]:
        for base in self.alloc_dir_bases:
            p = os.path.join(base, alloc_id)
            if os.path.isdir(p):
                return p
        return None

    def _alloc_in_ns(self, alloc_prefix: str, ns: str):
        return self._unique_prefix(
            [a for a in self.server.store.allocs() if a.namespace == ns],
            alloc_prefix, "allocation")

    def _forward_node(self, node_id: str, method: str, args: dict):
        """Forward a request to a client's RPC listener by NODE lookup
        (nomad/client_fs_endpoint.go, client_stats_endpoint.go: the
        client advertises its address on the Node record). Connections
        are cached per node."""
        node = self.server.store.node_by_id(node_id)
        addr = node.attributes.get("nomad.client.rpc") if node else None
        if not addr:
            raise KeyError(
                f"node {node_id[:8]} has no reachable client RPC "
                "address")
        from ..rpc.client import RpcClient
        cache = getattr(self, "_client_rpc_cache", None)
        if cache is None:
            cache = self._client_rpc_cache = {}
        # keyed by node id: a restarted client re-advertises on a new
        # ephemeral port, and the stale connection must be closed and
        # replaced instead of accumulating per historical address
        hit = cache.get(node_id)
        if hit is None or hit[0] != addr:
            if hit is not None:
                try:
                    hit[1].close()
                except Exception:
                    pass
            hit = (addr, RpcClient(addr, dial_timeout_s=2.0))
            cache[node_id] = hit
        return hit[1].call(method, args, timeout_s=60.0)

    def _forward_client(self, alloc, method: str, args: dict):
        """Forward a logs/fs/exec/stats request to the client OWNING
        the alloc (servers proxy these to the node)."""
        args = dict(args)
        args["alloc_id"] = alloc.id
        return self._forward_node(alloc.node_id, method, args)

    def _default_task(self, alloc, task: str) -> str:
        if task:
            return task
        tg = alloc.job.lookup_task_group(alloc.task_group) \
            if alloc.job else None
        if tg and len(tg.tasks) == 1:
            return tg.tasks[0].name
        raise ValueError("task parameter required")

    def _client_fs(self, op: str, alloc_prefix: str, q: dict, ns: str,
                   idx: int):
        """/v1/client/fs/{logs,ls,cat,stream} (client/fs_endpoint.go):
        serve an alloc's log files and directory tree — from the local
        alloc dir when co-located, else forwarded to the owning client
        over RPC. The alloc must live in the request's (ACL-checked)
        namespace."""
        import base64

        from ..client import fs_service
        alloc = self._alloc_in_ns(alloc_prefix, ns)
        if alloc is None:
            return None
        base = self._alloc_base(alloc.id)
        offset = int(q.get("offset", 0))
        if op == "logs":
            task = self._default_task(alloc, q.get("task", ""))
            stream = q.get("type", "stdout")
            if base is not None:
                data, total = fs_service.read_logs(base, task, stream,
                                                   offset)
            else:
                r = self._forward_client(
                    alloc, "ClientFS.Logs",
                    {"task": task, "type": stream, "offset": offset})
                data, total = bytes(r.get("Data") or b""), r["Offset"]
            return {"Data": data.decode("utf-8", "replace"),
                    "Offset": total}, idx
        if op == "stream":
            log_type = q.get("log_type", "")
            task = self._default_task(alloc, q.get("task", "")) \
                if log_type else q.get("task", "")
            wait_s = min(float(q.get("wait_s", 0.0)), 30.0)
            if base is not None:
                frames = fs_service.stream_frames(
                    base, q.get("path"), offset, task=task,
                    log_type=log_type, wait_s=wait_s)
            else:
                r = self._forward_client(
                    alloc, "ClientFS.Stream",
                    {"path": q.get("path"), "offset": offset,
                     "task": task, "log_type": log_type,
                     "wait_s": wait_s})
                frames = r["Frames"]
            out = []
            for f in frames:
                f = dict(f)
                f["Data"] = base64.b64encode(
                    bytes(f.get("Data") or b"")).decode()
                out.append(f)
            return {"Frames": out}, idx
        rel = q.get("path", "/")
        if op == "ls":
            if base is not None:
                entries = fs_service.list_dir(base, rel)
            else:
                entries = self._forward_client(
                    alloc, "ClientFS.List", {"path": rel})["Entries"]
            return (entries, idx) if entries is not None else None
        # cat
        if base is not None:
            data = fs_service.cat_file(base, rel)
        else:
            data = self._forward_client(
                alloc, "ClientFS.Cat", {"path": rel})["Data"]
        if data is None:
            return None
        return {"Data": bytes(data).decode("utf-8", "replace")}, idx

    def _client_exec_start(self, alloc_prefix: str, body: dict, ns: str,
                           idx: int):
        """POST /v1/client/allocation/:alloc/exec — start a command in
        the task environment (AllocExecRequest,
        client/alloc_endpoint.go:163). Always routed through the
        owning client's RPC listener (co-located included) so one code
        path serves every topology."""
        alloc = self._alloc_in_ns(alloc_prefix, ns)
        if alloc is None:
            return None
        task = self._default_task(alloc, body.get("Task")
                                  or body.get("task") or "")
        cmd = body.get("Cmd") or body.get("cmd") or []
        r = self._forward_client(alloc, "ClientExec.Start",
                                 {"task": task, "cmd": list(cmd)})
        return {"SessionID": r["session_id"]}, idx

    def _client_exec_io(self, alloc_prefix: str, sid: str, body: dict,
                        ns: str, idx: int):
        import base64
        alloc = self._alloc_in_ns(alloc_prefix, ns)
        if alloc is None:
            return None
        stdin_b64 = body.get("Stdin") or body.get("stdin") or ""
        args = {"session_id": sid,
                "stdin": base64.b64decode(stdin_b64) if stdin_b64 else b"",
                "close_stdin": bool(body.get("CloseStdin")
                                    or body.get("close_stdin")),
                "wait_s": min(float(body.get("WaitS")
                                    or body.get("wait_s") or 0.0), 30.0)}
        sig = body.get("Signal") or body.get("signal")
        if sig:
            args["signal"] = int(sig)
        r = self._forward_client(alloc, "ClientExec.Io", args)
        return {"Stdout": base64.b64encode(
                    bytes(r.get("stdout") or b"")).decode(),
                "Stderr": base64.b64encode(
                    bytes(r.get("stderr") or b"")).decode(),
                "Exited": bool(r.get("exited")),
                "ExitCode": int(r.get("exit_code", -1))}, idx

    def stream_monitor(self, handler, q: dict):
        """/v1/agent/monitor (agent_endpoint.go monitor): stream agent
        log lines as NDJSON at >= log_level."""
        from ..utils.monitor import get_buffer, parse_level
        buf = get_buffer()
        level = parse_level(q.get("log_level", "info"))
        try:
            handler.send_response(200)
            handler.send_header("Content-Type", "application/json")
            handler.send_header("Transfer-Encoding", "chunked")
            handler.end_headers()

            def write_chunk(data: bytes):
                _write_chunk(handler.wfile, data)

            seq = 0
            while True:
                seq, lines = buf.read_since(seq, level, timeout_s=5.0)
                if not lines:
                    write_chunk(b"{}\n")            # keepalive
                    continue
                for line in lines:
                    write_chunk((json.dumps({"Data": line}) + "\n")
                                .encode())
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass  # client went away

    def stream_events(self, handler, raw_topics, from_index: int):
        from ..server.event_broker import ALL_KEYS, TOPIC_ALL
        from ..utils.codec import to_wire
        topics = {}
        for t in raw_topics:
            topic, _, key = t.partition(":")
            topics.setdefault(topic or TOPIC_ALL, []).append(key or ALL_KEYS)
        sub, backlog = self.server.events.subscribe(
            topics or None, from_index)
        try:
            handler.send_response(200)
            handler.send_header("Content-Type", "application/json")
            handler.send_header("Transfer-Encoding", "chunked")
            handler.end_headers()

            def write_chunk(data: bytes):
                _write_chunk(handler.wfile, data)

            def emit(events):
                if not events:
                    write_chunk(b"{}\n")  # heartbeat (ndjson.go keepalive)
                    return
                payload = {"Index": max(e.index for e in events),
                           "Events": [to_wire(e) for e in events]}
                write_chunk((json.dumps(payload) + "\n").encode())

            if backlog:
                emit(backlog)
            while True:
                emit(sub.next_events(timeout_s=5.0))
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass  # client went away
        finally:
            sub.unsubscribe()

    def _find_node(self, prefix: str):
        node = self.server.store.node_by_id(prefix)
        if node is not None:
            return node
        matches = self.server.store.node_by_prefix(prefix)
        if len(matches) > 1:
            raise ValueError(
                f"node prefix {prefix!r} matched {len(matches)} nodes")
        return matches[0] if matches else None

    @staticmethod
    def _unique_prefix(items, prefix: str, what: str):
        matches = [x for x in items if x.id.startswith(prefix)]
        if len(matches) > 1:
            raise ValueError(
                f"{what} prefix {prefix!r} matched {len(matches)} {what}s")
        return matches[0] if matches else None

    @staticmethod
    def _job_stub(job) -> dict:
        return {
            "ID": job.id, "Name": job.name, "Type": job.type,
            "Priority": job.priority, "Status": job.status,
            "Stop": job.stop,
            "JobModifyIndex": job.job_modify_index,
        }
