"""Embedded web UI.

The reference ships an Ember app (`ui/`, ~15k LoC) built into the
binary and served by the agent. Here a dependency-free single-page app
rides the same HTTP agent at /ui, consuming the public JSON API
(/v1/jobs, /v1/nodes, /v1/allocations, /v1/services, ...): cluster
overview, jobs with drill-down into groups/allocations/evaluations/
deployments, nodes with attributes and running allocs, and the service
catalog. Hash-routed, auto-refreshing, ACL-token aware.
"""

INDEX_HTML = """<!doctype html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>nomad-tpu</title>
<meta name="viewport" content="width=device-width, initial-scale=1">
<style>
:root {
  --bg: #f6f7f9; --panel: #fff; --ink: #1f2d3d; --sub: #6b7a90;
  --line: #e3e8ee; --green: #2eb039; --red: #c7384c; --amber: #d9a514;
  --blue: #1563ff;
}
* { box-sizing: border-box; }
body { margin: 0; font: 14px/1.5 -apple-system, "Segoe UI", Roboto,
       Helvetica, Arial, sans-serif; background: var(--bg);
       color: var(--ink); }
header { background: #161d26; color: #fff; padding: 10px 20px;
         display: flex; align-items: center; gap: 18px; }
header .brand { font-weight: 700; letter-spacing: .4px; }
header a { color: #c8d2e0; text-decoration: none; padding: 4px 8px;
           border-radius: 4px; }
header a.active, header a:hover { color: #fff; background: #273447; }
header .spacer { flex: 1; }
header input { background:#273447; border:1px solid #3a4a61;
               color:#fff; border-radius:4px; padding:4px 8px; }
main { max-width: 1100px; margin: 18px auto; padding: 0 16px; }
h1 { font-size: 20px; margin: 8px 0 14px; }
h2 { font-size: 15px; margin: 18px 0 8px; color: var(--sub);
     text-transform: uppercase; letter-spacing: .6px; }
table { width: 100%; border-collapse: collapse; background: var(--panel);
        border: 1px solid var(--line); border-radius: 6px;
        overflow: hidden; }
th, td { text-align: left; padding: 8px 12px;
         border-bottom: 1px solid var(--line); }
th { background: #fbfcfd; color: var(--sub); font-weight: 600;
     font-size: 12px; text-transform: uppercase; letter-spacing: .5px; }
tr:last-child td { border-bottom: 0; }
tr.row { cursor: pointer; }
tr.row:hover { background: #f0f4fa; }
.badge { display: inline-block; padding: 1px 8px; border-radius: 10px;
         font-size: 12px; font-weight: 600; color: #fff; }
.badge.running, .badge.ready, .badge.passing, .badge.complete,
.badge.successful, .badge.active { background: var(--green); }
.badge.pending, .badge.initializing, .badge.paused { background: var(--amber); }
.badge.failed, .badge.dead, .badge.down, .badge.critical,
.badge.lost, .badge.cancelled { background: var(--red); }
.badge.other { background: var(--sub); }
.cards { display: flex; gap: 12px; flex-wrap: wrap; margin: 12px 0; }
.card { background: var(--panel); border: 1px solid var(--line);
        border-radius: 6px; padding: 12px 18px; min-width: 130px; }
.card .num { font-size: 24px; font-weight: 700; }
.card .lbl { color: var(--sub); font-size: 12px;
             text-transform: uppercase; letter-spacing: .5px; }
.kv { background: var(--panel); border: 1px solid var(--line);
      border-radius: 6px; padding: 10px 14px; }
.kv div { display: flex; border-bottom: 1px solid var(--line);
          padding: 4px 0; }
.kv div:last-child { border-bottom: 0; }
.kv b { width: 240px; color: var(--sub); font-weight: 600; flex-shrink: 0; }
.err { background: #fdecec; border: 1px solid #f5c0c8; color: #8e1b2c;
       padding: 10px 14px; border-radius: 6px; margin: 10px 0; }
.muted { color: var(--sub); }
code { background: #eef1f5; padding: 1px 5px; border-radius: 3px; }
</style>
</head>
<body>
<header>
  <span class="brand">nomad-tpu</span>
  <a href="#/jobs" data-nav="jobs">Jobs</a>
  <a href="#/nodes" data-nav="nodes">Clients</a>
  <a href="#/allocations" data-nav="allocations">Allocations</a>
  <a href="#/services" data-nav="services">Services</a>
  <a href="#/topology" data-nav="topology">Topology</a>
  <span class="spacer"></span>
  <input id="token" placeholder="ACL token" size="18">
</header>
<main id="main">Loading&hellip;</main>
<script>
"use strict";
const $main = document.getElementById("main");
const $token = document.getElementById("token");
$token.value = localStorage.getItem("nomad_token") || "";
$token.addEventListener("change", () => {
  localStorage.setItem("nomad_token", $token.value); render();
});

async function api(path) {
  const headers = {};
  if ($token.value) headers["X-Nomad-Token"] = $token.value;
  const r = await fetch(path, { headers });
  if (!r.ok) {
    let msg = r.statusText;
    try { msg = (await r.json()).error || msg; } catch (e) {}
    throw new Error(`${r.status}: ${msg}`);
  }
  return r.json();
}

const esc = s => String(s ?? "").replace(/[&<>"]/g,
  c => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;"}[c]));
const short = id => esc(String(id || "").slice(0, 8));
function badge(status) {
  const known = ["running","ready","passing","complete","successful",
    "active","pending","initializing","paused","failed","dead","down",
    "critical","lost","cancelled"];
  const cls = known.includes(status) ? status : "other";
  return `<span class="badge ${cls}">${esc(status || "?")}</span>`;
}
function table(headers, rows, onclickPrefix) {
  const h = headers.map(x => `<th>${x}</th>`).join("");
  const b = rows.map(r => {
    // ids are user-controlled (job IDs are arbitrary strings):
    // URI-encode for the hash route, then HTML-escape for the attr
    const link = onclickPrefix && r._id ?
      ` class="row" data-href="${esc(onclickPrefix +
        encodeURIComponent(r._id))}"` : "";
    return `<tr${link}>` +
      r.cells.map(c => `<td>${c}</td>`).join("") + "</tr>";
  }).join("");
  return `<table><thead><tr>${h}</tr></thead><tbody>${b ||
    '<tr><td class="muted" colspan="' + headers.length +
    '">none</td></tr>'}</tbody></table>`;
}
const card = (n, l) =>
  `<div class="card"><div class="num">${n}</div>` +
  `<div class="lbl">${l}</div></div>`;
const kv = obj => '<div class="kv">' + Object.entries(obj).map(
  ([k, v]) => `<div><b>${esc(k)}</b><span>${v}</span></div>`
).join("") + "</div>";

// ---- views ---------------------------------------------------------
function jobsTable(jobs) {
  const rows = jobs.map(j => ({ _id: j.ID, cells: [
    esc(j.ID), badge(j.Status), esc(j.Type),
    String(j.Priority ?? "")] }));
  return table(["Job", "Status", "Type", "Priority"], rows, "#/jobs/");
}
async function viewJobs() {
  return `<h1>Jobs</h1>` + jobsTable(await api("/v1/jobs"));
}

async function viewJob(id) {
  const [job, allocs, evals] = await Promise.all([
    api(`/v1/job/${encodeURIComponent(id)}`),
    api(`/v1/job/${encodeURIComponent(id)}/allocations`),
    api(`/v1/job/${encodeURIComponent(id)}/evaluations`)]);
  let deployments = [];
  try { deployments =
    await api(`/v1/job/${encodeURIComponent(id)}/deployments`); }
  catch (e) {}
  const groups = (job.task_groups || []).map(g => ({ cells: [
    esc(g.name), String(g.count),
    (g.tasks || []).map(t => `<code>${esc(t.name)}</code> ` +
      `<span class="muted">${esc(t.driver)}</span>`).join(", ")] }));
  const arows = allocs.map(a => ({ _id: a.id, cells: [
    short(a.id), esc(a.task_group), badge(a.client_status),
    esc(a.desired_status), short(a.node_id)] }));
  const erows = evals.map(ev => ({ cells: [
    short(ev.id), badge(ev.status), esc(ev.triggered_by),
    esc(ev.type)] }));
  const drows = deployments.map(d => ({ cells: [
    short(d.id), badge(d.status),
    esc(d.status_description || "")] }));
  return `<h1>${esc(job.id)} ${badge(job.status)}</h1>` +
    kv({ Type: esc(job.type), Priority: job.priority,
         Namespace: esc(job.namespace), Region: esc(job.region),
         Datacenters: esc((job.datacenters || []).join(", ")),
         Version: job.version }) +
    `<h2>Task groups</h2>` +
    table(["Group", "Count", "Tasks"], groups) +
    `<h2>Allocations</h2>` +
    table(["ID", "Group", "Status", "Desired", "Node"], arows,
          "#/allocations/") +
    `<h2>Evaluations</h2>` +
    table(["ID", "Status", "Triggered by", "Type"], erows) +
    (drows.length ? `<h2>Deployments</h2>` +
      table(["ID", "Status", "Description"], drows) : "");
}

async function viewNodes() {
  const nodes = await api("/v1/nodes");
  const rows = nodes.map(n => ({ _id: n.id, cells: [
    esc(n.name), badge(n.status), esc(n.datacenter),
    `<span class="badge ${n.scheduling_eligibility === "eligible"
      ? "running" : "failed"}">${esc(n.scheduling_eligibility)}</span>`,
    n.drain ? badge("draining") : ""] }));
  return `<h1>Clients</h1>` +
    table(["Name", "Status", "DC", "Eligibility", "Drain"], rows,
          "#/nodes/");
}

async function viewNode(id) {
  const [node, allocs] = await Promise.all([
    api(`/v1/node/${encodeURIComponent(id)}`),
    api(`/v1/node/${encodeURIComponent(id)}/allocations`)]);
  const arows = allocs.map(a => ({ _id: a.id, cells: [
    short(a.id), esc(a.job_id), badge(a.client_status),
    esc(a.task_group)] }));
  const attrs = Object.entries(node.attributes || {}).sort()
    .map(([k, v]) => `<div><b>${esc(k)}</b><span>${esc(v)}</span></div>`)
    .join("");
  return `<h1>${esc(node.name)} ${badge(node.status)}</h1>` +
    kv({ ID: short(node.id), Datacenter: esc(node.datacenter),
         Class: esc(node.node_class || "-"),
         Drain: node.drain ? "yes" : "no",
         Eligibility: esc(node.scheduling_eligibility) }) +
    `<h2>Allocations</h2>` +
    table(["ID", "Job", "Status", "Group"], arows, "#/allocations/") +
    `<h2>Attributes</h2><div class="kv">${attrs}</div>`;
}

async function viewAllocs() {
  const allocs = await api("/v1/allocations");
  const rows = allocs.map(a => ({ _id: a.id, cells: [
    short(a.id), esc(a.job_id), esc(a.task_group),
    badge(a.client_status), esc(a.desired_status),
    short(a.node_id)] }));
  return `<h1>Allocations</h1>` +
    table(["ID", "Job", "Group", "Status", "Desired", "Node"], rows,
          "#/allocations/");
}

async function viewAlloc(id) {
  const a = await api(`/v1/allocation/${encodeURIComponent(id)}`);
  const tasks = Object.entries(a.task_states || {}).map(([name, ts]) =>
    ({ cells: [esc(name), badge(ts.state),
       String(ts.restarts || 0),
       (ts.events || []).slice(-3).map(e =>
         esc(e.type)).join(" → ")] }));
  return `<h1>Allocation ${short(a.id)} ` +
    `${badge(a.client_status)}</h1>` +
    kv({ Job: `<a href="#/jobs/${esc(a.job_id)}">${esc(a.job_id)}</a>`,
         "Task group": esc(a.task_group),
         Node: short(a.node_id),
         Desired: esc(a.desired_status),
         Name: esc(a.name) }) +
    `<h2>Tasks</h2>` +
    table(["Task", "State", "Restarts", "Recent events"], tasks);
}

async function viewServices() {
  const services = await api("/v1/services");
  const blocks = await Promise.all(services.map(async s => {
    const regs = await api(
      `/v1/service/${encodeURIComponent(s.ServiceName)}`);
    const rows = regs.map(r => ({ cells: [
      short(r.alloc_id), esc(r.task_name || "(group)"),
      `<code>${esc(r.address)}:${r.port}</code>`,
      badge(r.status)] }));
    return `<h2>${esc(s.ServiceName)} ` +
      `<span class="muted">${esc(s.Tags.join(", "))}</span></h2>` +
      table(["Alloc", "Task", "Address", "Health"], rows);
  }));
  return `<h1>Services</h1>` +
    (blocks.join("") || '<p class="muted">No registered services.</p>');
}

async function viewTopology() {
  const [nodes, allocs] = await Promise.all([
    api("/v1/nodes"), api("/v1/allocations")]);
  const byNode = {};
  for (const a of allocs) {
    if (a.client_status !== "running") continue;
    (byNode[a.node_id] = byNode[a.node_id] || []).push(a);
  }
  const rows = nodes.map(n => {
    const running = byNode[n.id] || [];
    const boxes = running.map(a =>
      `<span class="badge running" title="${esc(a.job_id)}">` +
      `${esc(a.job_id).slice(0, 10)}</span>`).join(" ");
    return { _id: n.id, cells: [esc(n.name), badge(n.status),
      String(running.length), boxes] };
  });
  const total = allocs.filter(
    a => a.client_status === "running").length;
  return `<h1>Topology</h1>` +
    `<div class="cards">${card(nodes.length, "clients")}` +
    `${card(total, "running allocs")}</div>` +
    table(["Client", "Status", "Allocs", "Jobs"], rows, "#/nodes/");
}

async function viewOverview() {
  const [jobs, nodes, allocs] = await Promise.all([
    api("/v1/jobs"), api("/v1/nodes"), api("/v1/allocations")]);
  const running = jobs.filter(j => j.Status === "running").length;
  const ready = nodes.filter(n => n.status === "ready").length;
  const live = allocs.filter(
    a => a.client_status === "running").length;
  return `<h1>Cluster</h1><div class="cards">` +
    card(jobs.length, "jobs") + card(running, "running jobs") +
    card(ready + "/" + nodes.length, "ready clients") +
    card(live, "running allocs") + `</div>` +
    `<h2>Jobs</h2>` + jobsTable(jobs);
}

// ---- router --------------------------------------------------------
const routes = [
  [/^#\\/jobs\\/(.+)$/, m => viewJob(decodeURIComponent(m[1]))],
  [/^#\\/jobs$/, () => viewJobs()],
  [/^#\\/nodes\\/(.+)$/, m => viewNode(decodeURIComponent(m[1]))],
  [/^#\\/nodes$/, () => viewNodes()],
  [/^#\\/allocations\\/(.+)$/,
   m => viewAlloc(decodeURIComponent(m[1]))],
  [/^#\\/allocations$/, () => viewAllocs()],
  [/^#\\/services$/, () => viewServices()],
  [/^#\\/topology$/, () => viewTopology()],
];

let renderSeq = 0;
async function render() {
  const seq = ++renderSeq;
  const hash = location.hash || "#/";
  document.querySelectorAll("header a").forEach(a => {
    a.classList.toggle("active",
      hash.startsWith("#/" + a.dataset.nav));
  });
  let view = viewOverview;
  let match = null;
  for (const [re, fn] of routes) {
    match = hash.match(re);
    if (match) { view = () => fn(match); break; }
  }
  try {
    const html = await view();
    if (seq === renderSeq) $main.innerHTML = html;
  } catch (e) {
    if (seq === renderSeq)
      $main.innerHTML = `<div class="err">${esc(e.message)}</div>`;
  }
}
document.addEventListener("click", e => {
  const tr = e.target.closest("tr[data-href]");
  if (tr) location.hash = tr.dataset.href;
});
window.addEventListener("hashchange", render);
render();
setInterval(() => {
  if (document.visibilityState === "visible") render();
}, 5000);
</script>
</body>
</html>
"""
