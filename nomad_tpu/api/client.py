"""Python API client for the HTTP API (the api/ Go SDK equivalent,
reference: api/api.go NewClient + typed wrappers)."""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Optional


class ApiError(RuntimeError):
    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status


class ApiClient:
    def __init__(self, address: str = "http://127.0.0.1:4646",
                 region: str = "",
                 token: str = ""):
        self.address = address.rstrip("/")
        self.token = token
        # foreign region: every request carries ?region= so the local
        # agent forwards it (nomad/rpc.go forwardRegion)
        self.region = region

    def _request(self, method: str, path: str, body: Optional[dict] = None,
                 params: Optional[dict] = None, raw: bool = False) -> Any:
        url = self.address + path
        if self.region:
            params = dict(params or {})
            params.setdefault("region", self.region)
        if params:
            from urllib.parse import urlencode
            url += "?" + urlencode(params)
        data = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["X-Nomad-Token"] = self.token
        req = urllib.request.Request(url, data=data, method=method,
                                     headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=310) as resp:
                payload = resp.read()
                if raw:
                    # non-JSON bodies (Prometheus text exposition)
                    return payload.decode("utf-8", "replace")
                return json.loads(payload or "null")
        except urllib.error.HTTPError as e:
            try:
                msg = json.loads(e.read()).get("error", str(e))
            except Exception:
                msg = str(e)
            raise ApiError(e.code, msg)
        except urllib.error.URLError as e:
            raise ApiError(0, f"unable to reach agent at {self.address}: "
                              f"{e.reason}")

    # -- jobs ----------------------------------------------------------
    def register_job(self, spec, check_index: Optional[int] = None
                     ) -> dict:
        body = {"Job": spec}
        if check_index is not None:
            body["EnforceIndex"] = True
            body["JobModifyIndex"] = int(check_index)
        return self._request("PUT", "/v1/jobs", body)

    def register_jobs_bulk(self, specs: list) -> list:
        """Bulk register (ISSUE 19): PUT /v1/jobs with an array body —
        the agent coalesces the whole batch into one raft entry.
        Each element may be a job spec dict or an {"Job": spec}
        envelope; returns one result per input in order, either
        {"EvalID", "JobModifyIndex"} or {"Error"}."""
        body = [s if isinstance(s, dict) and ("Job" in s or "job" in s)
                else {"Job": s} for s in specs]
        return self._request("PUT", "/v1/jobs", body)

    def list_jobs(self, prefix: str = "") -> list:
        return self._request("GET", "/v1/jobs",
                             params={"prefix": prefix} if prefix else None)

    def get_job(self, job_id: str) -> dict:
        return self._request("GET", f"/v1/job/{job_id}")

    def deregister_job(self, job_id: str, purge: bool = False) -> dict:
        return self._request("DELETE", f"/v1/job/{job_id}",
                             params={"purge": str(purge).lower()})

    def job_allocations(self, job_id: str) -> list:
        return self._request("GET", f"/v1/job/{job_id}/allocations")

    def job_evaluations(self, job_id: str) -> list:
        return self._request("GET", f"/v1/job/{job_id}/evaluations")

    def job_summary(self, job_id: str) -> dict:
        return self._request("GET", f"/v1/job/{job_id}/summary")

    # -- nodes ---------------------------------------------------------
    def list_nodes(self) -> list:
        return self._request("GET", "/v1/nodes")

    def get_node(self, node_id: str) -> dict:
        return self._request("GET", f"/v1/node/{node_id}")

    def node_allocations(self, node_id: str) -> list:
        return self._request("GET", f"/v1/node/{node_id}/allocations")

    def set_node_eligibility(self, node_id: str, eligible: bool) -> dict:
        return self._request("POST", f"/v1/node/{node_id}/eligibility",
                             {"Eligibility":
                              "eligible" if eligible else "ineligible"})

    def drain_node(self, node_id: str, deadline_s: float = 0.0,
                   mark_eligible: bool = False,
                   enable: bool = True) -> dict:
        spec = {"Deadline": deadline_s} if enable else None
        return self._request("POST", f"/v1/node/{node_id}/drain",
                             {"DrainSpec": spec,
                              "MarkEligible": mark_eligible})

    def plan_job(self, job_id: str, spec, diff: bool = True) -> dict:
        return self._request("POST", f"/v1/job/{job_id}/plan",
                             {"Job": spec, "Diff": diff})

    def scale_job(self, job_id: str, group: str, count: int,
                  message: str = "") -> dict:
        return self._request("POST", f"/v1/job/{job_id}/scale",
                             {"Count": count, "Target": {"Group": group},
                              "Message": message})

    def list_scaling_policies(self, job: str = "",
                              policy_type: str = "") -> list:
        """GET /v1/scaling/policies (nomad/scaling_endpoint.go:24)."""
        params = {}
        if job:
            params["job"] = job
        if policy_type:
            params["type"] = policy_type
        return self._request("GET", "/v1/scaling/policies", params=params)

    def get_scaling_policy(self, policy_id: str) -> dict:
        """GET /v1/scaling/policy/:id (nomad/scaling_endpoint.go:90)."""
        return self._request("GET", f"/v1/scaling/policy/{policy_id}")

    def job_scale_status(self, job_id: str) -> dict:
        return self._request("GET", f"/v1/job/{job_id}/scale")

    def job_deployments(self, job_id: str) -> list:
        return self._request("GET", f"/v1/job/{job_id}/deployments")

    def job_versions(self, job_id: str) -> list:
        return self._request("GET", f"/v1/job/{job_id}/versions")

    def revert_job(self, job_id: str, version: int) -> dict:
        return self._request("POST", f"/v1/job/{job_id}/revert",
                             {"JobID": job_id, "JobVersion": version})

    # -- deployments ---------------------------------------------------
    def list_deployments(self, prefix: str = "") -> list:
        return self._request("GET", "/v1/deployments",
                             params={"prefix": prefix} if prefix else None)

    def get_deployment(self, deployment_id: str) -> dict:
        return self._request("GET", f"/v1/deployment/{deployment_id}")

    def deployment_allocations(self, deployment_id: str) -> list:
        return self._request("GET",
                             f"/v1/deployment/allocations/{deployment_id}")

    def promote_deployment(self, deployment_id: str,
                           groups: Optional[list] = None) -> dict:
        return self._request("POST", f"/v1/deployment/promote/{deployment_id}",
                             {"DeploymentID": deployment_id, "Groups": groups})

    def fail_deployment(self, deployment_id: str) -> dict:
        return self._request("POST", f"/v1/deployment/fail/{deployment_id}",
                             {})

    def pause_deployment(self, deployment_id: str, pause: bool) -> dict:
        return self._request("POST", f"/v1/deployment/pause/{deployment_id}",
                             {"Pause": pause})

    # -- allocs / evals ------------------------------------------------
    def alloc_fs_stream(self, alloc_id: str, path: str = "",
                        offset: int = 0, task: str = "",
                        log_type: str = "", wait_s: float = 0.0) -> list:
        """GET /v1/client/fs/stream/:alloc — framed file/log stream
        (client/lib/streamframer shape over poll round trips). Returns
        decoded frames [{File, Offset, Data(bytes), Heartbeat?,
        FileEvent?}]; resume from the last frame's Offset+len(Data)."""
        import base64
        params = {"offset": offset, "wait_s": wait_s}
        if path:
            params["path"] = path
        if task:
            params["task"] = task
        if log_type:
            params["log_type"] = log_type
        r = self._request("GET", f"/v1/client/fs/stream/{alloc_id}",
                          params=params)
        frames = []
        for f in r.get("Frames", []):
            f = dict(f)
            f["Data"] = base64.b64decode(f.get("Data") or "")
            frames.append(f)
        return frames

    def alloc_exec_start(self, alloc_id: str, cmd: list,
                         task: str = "") -> str:
        """POST /v1/client/allocation/:alloc/exec → session id
        (AllocExecRequest, client/alloc_endpoint.go:163)."""
        r = self._request("POST", f"/v1/client/allocation/{alloc_id}/exec",
                          {"Task": task, "Cmd": list(cmd)})
        return r["SessionID"]

    def alloc_exec_io(self, alloc_id: str, session_id: str,
                      stdin: bytes = b"", close_stdin: bool = False,
                      wait_s: float = 0.0, signal: int = 0) -> dict:
        """One stdin/stdout round trip of an exec session. Returns
        {stdout: bytes, stderr: bytes, exited: bool, exit_code: int}."""
        import base64
        body = {"Stdin": base64.b64encode(stdin).decode()
                if stdin else "",
                "CloseStdin": close_stdin, "WaitS": wait_s}
        if signal:
            body["Signal"] = signal
        r = self._request(
            "POST", f"/v1/client/allocation/{alloc_id}/exec/{session_id}",
            body)
        return {"stdout": base64.b64decode(r.get("Stdout") or ""),
                "stderr": base64.b64decode(r.get("Stderr") or ""),
                "exited": bool(r.get("Exited")),
                "exit_code": int(r.get("ExitCode", -1))}

    def alloc_exec_stop(self, alloc_id: str, session_id: str) -> None:
        self._request(
            "DELETE",
            f"/v1/client/allocation/{alloc_id}/exec/{session_id}")

    def alloc_stats(self, alloc_id: str) -> dict:
        """GET /v1/client/allocation/:alloc/stats — live task-level
        AllocResourceUsage from the owning client's sampler
        (client/alloc_endpoint.go Stats; ISSUE 13)."""
        return self._request(
            "GET", f"/v1/client/allocation/{alloc_id}/stats")

    def client_host_stats(self, node_id: str = "",
                          history: bool = False,
                          last: Optional[int] = None) -> dict:
        """GET /v1/client/stats — a node's HostStats, proxied by the
        server to the owning client (stats_endpoint.go); node_id may
        be omitted on a single-node cluster. history=True attaches the
        client-side retained ring."""
        params = {}
        if node_id:
            params["node_id"] = node_id
        if history:
            params["history"] = "true"
            if last:
                params["n"] = str(last)
        return self._request("GET", "/v1/client/stats",
                             params=params or None)

    def get_allocation(self, alloc_id: str) -> dict:
        return self._request("GET", f"/v1/allocation/{alloc_id}")

    def list_allocations(self) -> list:
        return self._request("GET", "/v1/allocations")

    def get_evaluation(self, eval_id: str) -> dict:
        return self._request("GET", f"/v1/evaluation/{eval_id}")

    def search(self, prefix: str, context: str = "all") -> dict:
        return self._request("POST", "/v1/search",
                             {"Prefix": prefix, "Context": context})

    def stream_events(self, topics: Optional[list] = None,
                      index: int = 0):
        """Generator of event batches from /v1/event/stream (NDJSON).
        topics: ["Job:my-job", "Node:*"]-style filters."""
        from urllib.parse import urlencode
        params = [("topic", t) for t in (topics or [])] + [("index", index)]
        if self.region:
            params.append(("region", self.region))
        url = f"{self.address}/v1/event/stream?{urlencode(params)}"
        req = urllib.request.Request(url)
        with urllib.request.urlopen(req, timeout=310) as resp:
            for line in resp:
                line = line.strip()
                if not line or line == b"{}":
                    continue
                yield json.loads(line)

    # -- volumes ---------------------------------------------------------
    def list_volumes(self, namespace: str = "default") -> list:
        return self._request("GET", "/v1/volumes",
                             params={"namespace": namespace})

    def get_volume(self, volume_id: str,
                   namespace: str = "default") -> dict:
        return self._request("GET", f"/v1/volume/csi/{volume_id}",
                             params={"namespace": namespace})

    def register_volume(self, spec: dict,
                        namespace: str = "default") -> dict:
        vol_id = spec.get("id", spec.get("ID", ""))
        if not vol_id:
            raise ApiError(400, "volume spec requires an id")
        return self._request("PUT", f"/v1/volume/csi/{vol_id}",
                             {"Volume": spec},
                             params={"namespace": namespace})

    def deregister_volume(self, volume_id: str, force: bool = False,
                          namespace: str = "default") -> dict:
        return self._request(
            "DELETE", f"/v1/volume/csi/{volume_id}",
            params={"namespace": namespace,
                    "force": str(force).lower()})

    # -- operator --------------------------------------------------------
    def snapshot_save(self) -> dict:
        return self._request("GET", "/v1/operator/snapshot")

    def snapshot_restore(self, snapshot: dict) -> dict:
        return self._request("PUT", "/v1/operator/snapshot",
                             {"snapshot": snapshot})

    def autopilot_config(self) -> dict:
        return self._request("GET",
                             "/v1/operator/autopilot/configuration")

    def governor(self) -> dict:
        return self._request("GET", "/v1/operator/governor")

    def trace(self, params: Optional[dict] = None) -> dict:
        """Eval flight recorder: recent span trees, tail exemplars,
        and per-stage percentiles; params: n, exemplars=true,
        format=chrome (Perfetto-loadable trace-event JSON)."""
        return self._request("GET", "/v1/operator/trace",
                             params=params)

    def set_autopilot_config(self, config: dict) -> dict:
        return self._request("PUT",
                             "/v1/operator/autopilot/configuration",
                             config)

    # -- namespaces ------------------------------------------------------
    def list_namespaces(self) -> list:
        return self._request("GET", "/v1/namespaces")

    def get_namespace(self, name: str) -> dict:
        return self._request("GET", f"/v1/namespace/{name}")

    def apply_namespace(self, name: str, description: str = "",
                        meta: Optional[dict] = None) -> dict:
        return self._request("PUT", f"/v1/namespace/{name}",
                             {"name": name, "description": description,
                              "meta": meta or {}})

    def delete_namespace(self, name: str) -> dict:
        return self._request("DELETE", f"/v1/namespace/{name}")

    # -- service catalog ------------------------------------------------
    def list_services(self, namespace: str = "default") -> list:
        return self._request("GET", "/v1/services",
                             params={"namespace": namespace})

    def get_service(self, name: str, namespace: str = "default") -> list:
        return self._request("GET", f"/v1/service/{name}",
                             params={"namespace": namespace})

    def delete_service_registration(self, name: str, reg_id: str) -> dict:
        return self._request("DELETE", f"/v1/service/{name}/{reg_id}")

    def agent_self(self) -> dict:
        return self._request("GET", "/v1/agent/self")

    def metrics(self, format: str = "") -> Any:
        """InmemSink snapshot (JSON), or the Prometheus text
        exposition when format='prometheus' (returned as str)."""
        if format == "prometheus":
            return self._request("GET", "/v1/metrics",
                                 params={"format": "prometheus"},
                                 raw=True)
        return self._request("GET", "/v1/metrics")

    def telemetry(self, last: Optional[int] = None) -> dict:
        """Retained telemetry history ring (ISSUE 11): chronological
        series + derived rates from /v1/operator/telemetry."""
        return self._request(
            "GET", "/v1/operator/telemetry",
            params={"n": str(last)} if last else None)

    def flatness(self) -> dict:
        """Live steady-state verdict: bench/soak.flatness_verdict run
        over the in-process telemetry ring."""
        return self._request("GET", "/v1/operator/flatness")

    def agent_profile(self, seconds: float = 1.0) -> dict:
        return self._request("GET", "/v1/agent/pprof/profile",
                             params={"seconds": seconds})

    def agent_threads(self) -> dict:
        return self._request("GET", "/v1/agent/pprof/threads")

    # -- ACL ------------------------------------------------------------
    def acl_bootstrap(self) -> dict:
        return self._request("POST", "/v1/acl/bootstrap")

    def acl_policies(self) -> list:
        return self._request("GET", "/v1/acl/policies")

    def acl_policy(self, name: str) -> dict:
        return self._request("GET", f"/v1/acl/policy/{name}")

    def acl_upsert_policy(self, name: str, rules: str,
                          description: str = "") -> dict:
        return self._request("PUT", f"/v1/acl/policy/{name}",
                             {"rules": rules, "description": description})

    def acl_delete_policy(self, name: str) -> dict:
        return self._request("DELETE", f"/v1/acl/policy/{name}")

    def acl_tokens(self) -> list:
        return self._request("GET", "/v1/acl/tokens")

    def acl_create_token(self, name: str = "", type_: str = "client",
                         policies=None) -> dict:
        return self._request("PUT", "/v1/acl/token",
                             {"name": name, "type": type_,
                              "policies": policies or []})

    def acl_delete_token(self, accessor_id: str) -> dict:
        return self._request("DELETE", f"/v1/acl/token/{accessor_id}")

    def acl_token_self(self) -> dict:
        return self._request("GET", "/v1/acl/token/self")

    def list_event_sinks(self) -> list:
        return self._request("GET", "/v1/event/sinks")

    def upsert_event_sink(self, address: str, sink_id: str = "",
                          topics: Optional[dict] = None,
                          type_: str = "webhook") -> dict:
        body = {"Address": address, "Type": type_,
                "Topics": topics or {}}
        if sink_id:
            body["ID"] = sink_id
        return self._request("PUT", "/v1/event/sink", body)

    def delete_event_sink(self, sink_id: str) -> dict:
        return self._request("DELETE", f"/v1/event/sink/{sink_id}")

    def scheduler_config(self) -> dict:
        return self._request("GET", "/v1/operator/scheduler/configuration")
