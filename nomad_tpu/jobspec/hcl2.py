"""HCL2 jobspec features: variables, locals, functions, dynamic blocks.

Reference: jobspec2/parse.go — hashicorp/hcl/v2 + cty evaluation with
`variable`/`locals` blocks, `var.*`/`local.*` references, the function
library (jobspec2/functions.go), and Terraform-style `dynamic` blocks.

This layer evaluates the raw dict produced by the in-tree HCL parser
(jobspec/hcl.py) before job mapping:
  - `variable "name" { default, type, description }` declarations with
    caller-supplied overrides (-var / NOMAD_VAR_* in the CLI)
  - `locals { ... }` evaluated after variables (may reference them)
  - `${...}` expressions in any string: literals, var./local./each.
    references, indexing, arithmetic/comparison/logic, conditionals,
    and ~30 stdlib functions
  - bare `var.x` / `local.x` attribute values
  - `dynamic "block" { for_each, labels, content {} }` expansion with
    each.key/each.value (iterator named after the block label)
  - runtime interpolations (${node.*}, ${attr.*}, ${meta.*}, ${env.*},
    ${NOMAD_*}) pass through untouched for the client to resolve

Expressions outside plain references must be written inside "${...}"
(the parser dialect keeps attribute values literal otherwise).
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, List, Optional, Tuple

from .hcl import HclError

_RUNTIME_ROOTS = ("node", "attr", "meta", "env", "NOMAD_")
_BARE_REF = re.compile(r"^([A-Za-z_]\w*)\.[A-Za-z_][\w.\-]*$")


class Hcl2Error(HclError):
    pass


# -- function library (jobspec2/functions.go subset) --------------------
def _fn_format(fmt, *args):
    # cty %s/%d/%v-style: map to python
    return re.sub(r"%[vds]", "{}", fmt).format(*args) \
        if "%" in fmt else fmt.format(*args)


FUNCTIONS = {
    "upper": lambda s: str(s).upper(),
    "lower": lambda s: str(s).lower(),
    "title": lambda s: str(s).title(),
    "trimspace": lambda s: str(s).strip(),
    "trimprefix": lambda s, p: str(s)[len(p):]
        if str(s).startswith(p) else str(s),
    "trimsuffix": lambda s, p: str(s)[:-len(p)]
        if p and str(s).endswith(p) else str(s),
    "replace": lambda s, a, b: str(s).replace(a, b),
    "regex_replace": lambda s, pat, rep: re.sub(pat, rep, str(s)),
    "split": lambda sep, s: str(s).split(sep),
    "join": lambda sep, parts: sep.join(str(p) for p in parts),
    "format": _fn_format,
    "substr": lambda s, off, ln: str(s)[off:off + ln]
        if ln >= 0 else str(s)[off:],
    "length": lambda x: len(x),
    "min": lambda *a: min(a),
    "max": lambda *a: max(a),
    "abs": abs,
    "ceil": lambda x: -(-int(x) // 1) if x == int(x) else int(x) + 1,
    "floor": lambda x: int(x) if x >= 0 or x == int(x) else int(x) - 1,
    "concat": lambda *lists: [x for lst in lists for x in lst],
    "contains": lambda lst, v: v in lst,
    "distinct": lambda lst: list(dict.fromkeys(lst)),
    "flatten": lambda lst: [x for sub in lst
                            for x in (sub if isinstance(sub, list)
                                      else [sub])],
    "keys": lambda m: sorted(m.keys()),
    "values": lambda m: [m[k] for k in sorted(m.keys())],
    "lookup": lambda m, k, default=None: m.get(k, default),
    "merge": lambda *ms: {k: v for m in ms for k, v in m.items()},
    "range": lambda *a: list(range(*a)),
    "reverse": lambda lst: list(reversed(lst)),
    "sort": lambda lst: sorted(lst, key=str),
    "coalesce": lambda *a: next((x for x in a if x not in (None, "")),
                                None),
    "compact": lambda lst: [x for x in lst if x not in (None, "")],
    "element": lambda lst, i: lst[int(i) % len(lst)],
    "index": lambda lst, v: lst.index(v),
    "jsonencode": lambda v: json.dumps(v),
    "jsondecode": lambda s: json.loads(s),
    "base64encode": lambda s: __import__("base64")
        .b64encode(str(s).encode()).decode(),
    "base64decode": lambda s: __import__("base64")
        .b64decode(s).decode(),
    "tostring": lambda v: str(v),
    "tonumber": lambda v: float(v) if "." in str(v) else int(v),
    "toset": lambda lst: list(dict.fromkeys(lst)),
    "chunklist": lambda lst, n: [lst[i:i + n]
                                 for i in range(0, len(lst), n)],
}


# -- expression evaluator ----------------------------------------------
_TOKEN = re.compile(r"""
    (?P<ws>\s+)
  | (?P<num>\d+\.\d+|\d+)
  | (?P<str>"(?:[^"\\]|\\.)*")
  | (?P<op>==|!=|<=|>=|&&|\|\||[-+*/%<>?:(),\[\]{}.!])
  | (?P<ident>[A-Za-z_][\w-]*)
""", re.X)


def _tokenize(src: str) -> List[Tuple[str, str]]:
    out = []
    i = 0
    while i < len(src):
        m = _TOKEN.match(src, i)
        if not m:
            raise Hcl2Error(f"bad expression near {src[i:]!r}")
        i = m.end()
        kind = m.lastgroup
        if kind != "ws":
            out.append((kind, m.group()))
    out.append(("eof", ""))
    return out


class _ExprParser:
    """Pratt-ish parser for the ${...} expression language."""

    def __init__(self, tokens: List[Tuple[str, str]], scope: Dict):
        self.toks = tokens
        self.i = 0
        self.scope = scope

    def peek(self):
        return self.toks[self.i]

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, val: str):
        t = self.next()
        if t[1] != val:
            raise Hcl2Error(f"expected {val!r}, got {t[1]!r}")

    def parse(self):
        v = self.ternary()
        if self.peek()[0] != "eof":
            raise Hcl2Error(f"trailing tokens at {self.peek()[1]!r}")
        return v

    def ternary(self):
        cond = self.or_()
        if self.peek()[1] == "?":
            self.next()
            a = self.ternary()
            self.expect(":")
            b = self.ternary()
            return a if cond else b
        return cond

    def or_(self):
        v = self.and_()
        while self.peek()[1] == "||":
            self.next()
            rhs = self.and_()
            v = bool(v) or bool(rhs)
        return v

    def and_(self):
        v = self.cmp()
        while self.peek()[1] == "&&":
            self.next()
            rhs = self.cmp()
            v = bool(v) and bool(rhs)
        return v

    def cmp(self):
        v = self.add()
        while self.peek()[1] in ("==", "!=", "<", ">", "<=", ">="):
            op = self.next()[1]
            rhs = self.add()
            v = {"==": v == rhs, "!=": v != rhs, "<": v < rhs,
                 ">": v > rhs, "<=": v <= rhs, ">=": v >= rhs}[op]
        return v

    def add(self):
        v = self.mul()
        while self.peek()[1] in ("+", "-"):
            op = self.next()[1]
            rhs = self.mul()
            v = v + rhs if op == "+" else v - rhs
        return v

    def mul(self):
        v = self.unary()
        while self.peek()[1] in ("*", "/", "%"):
            op = self.next()[1]
            rhs = self.unary()
            if op == "*":
                v = v * rhs
            elif op == "/":
                v = v / rhs
            else:
                v = v % rhs
        return v

    def unary(self):
        if self.peek()[1] == "!":
            self.next()
            return not self.unary()
        if self.peek()[1] == "-":
            self.next()
            return -self.unary()
        return self.postfix()

    def postfix(self):
        v = self.primary()
        while True:
            t = self.peek()
            if t[1] == "[":
                self.next()
                idx = self.ternary()
                self.expect("]")
                v = v[idx]
            elif t[1] == ".":
                self.next()
                attr = self.next()[1]
                if isinstance(v, dict):
                    v = v[attr]
                else:
                    v = getattr(v, attr)
            else:
                return v

    def primary(self):
        kind, val = self.next()
        if kind == "num":
            return float(val) if "." in val else int(val)
        if kind == "str":
            return json.loads(val)
        if val == "(":
            v = self.ternary()
            self.expect(")")
            return v
        if val == "[":
            out = []
            while self.peek()[1] != "]":
                out.append(self.ternary())
                if self.peek()[1] == ",":
                    self.next()
            self.next()
            return out
        if kind == "ident":
            if val in ("true", "false"):
                return val == "true"
            if val == "null":
                return None
            if self.peek()[1] == "(":
                self.next()
                args = []
                while self.peek()[1] != ")":
                    args.append(self.ternary())
                    if self.peek()[1] == ",":
                        self.next()
                self.next()
                fn = FUNCTIONS.get(val)
                if fn is None:
                    raise Hcl2Error(f"unknown function {val!r}")
                return fn(*args)
            # root reference
            root = self.scope.get(val)
            if root is None and val not in self.scope:
                raise Hcl2Error(f"unknown reference {val!r}")
            return root
        raise Hcl2Error(f"unexpected token {val!r}")


def eval_expr(src: str, scope: Dict) -> Any:
    return _ExprParser(_tokenize(src), scope).parse()


_INTERP = re.compile(r"\$\{([^{}]+)\}")


def _is_runtime(expr: str) -> bool:
    e = expr.strip()
    return e.startswith(_RUNTIME_ROOTS)


def interpolate_value(s: str, scope: Dict) -> Any:
    """Evaluate ${...} segments in a string. A string that is exactly
    one expression returns the typed value (cty semantics); mixed text
    concatenates. Runtime interpolations pass through."""
    if "${" not in s:
        return s
    m = _INTERP.fullmatch(s)
    if m is not None:
        if _is_runtime(m.group(1)):
            return s
        return eval_expr(m.group(1), scope)

    def sub(m: re.Match) -> str:
        if _is_runtime(m.group(1)):
            return m.group(0)
        v = eval_expr(m.group(1), scope)
        return str(v)

    return _INTERP.sub(sub, s)


# -- dynamic block expansion -------------------------------------------
def _expand_dynamic(body: dict, scope: Dict) -> dict:
    """Terraform-style dynamic blocks: dynamic "tag" { for_each,
    labels, content {} } -> repeated "tag" blocks with each.* bound."""
    dyn = body.pop("dynamic", None)
    if dyn is None:
        return body
    for label, variants in (dyn or {}).items():
        variants = variants if isinstance(variants, list) else [variants]
        for spec in variants:
            items = _walk(spec.get("for_each"), scope)
            if isinstance(items, dict):
                pairs = list(items.items())
            else:
                pairs = list(enumerate(items or []))
            out = []
            labeled = {}
            for k, v in pairs:
                each = {"key": k, "value": v}
                inner_scope = {**scope, "each": each, label: each}
                content = _walk_dict(dict(spec.get("content") or {}),
                                     inner_scope)
                labels = spec.get("labels")
                if labels:
                    lbls = [_walk(x, inner_scope) for x in labels]
                    tgt = labeled
                    for lbl in lbls[:-1]:
                        tgt = tgt.setdefault(str(lbl), {})
                    tgt[str(lbls[-1])] = content
                else:
                    out.append(content)
            existing = body.get(label)
            if labeled:
                merged = dict(existing) if isinstance(existing, dict) else {}
                merged.update(labeled)
                body[label] = merged
            elif out:
                if existing is None:
                    body[label] = out if len(out) > 1 else out[0]
                else:
                    cur = existing if isinstance(existing, list) \
                        else [existing]
                    body[label] = cur + out
    return body


def _walk_dict(d: dict, scope: Dict) -> dict:
    d = _expand_dynamic(d, scope)
    return {k: _walk(v, scope) for k, v in d.items()}


def _walk(v, scope: Dict):
    if isinstance(v, str):
        m = _BARE_REF.match(v)
        if m and m.group(1) in scope:
            return eval_expr(v, scope)
        return interpolate_value(v, scope)
    if isinstance(v, dict):
        return _walk_dict(dict(v), scope)
    if isinstance(v, list):
        return [_walk(x, scope) for x in v]
    return v


# -- entry --------------------------------------------------------------
def evaluate(parsed: dict,
             variables: Optional[Dict[str, Any]] = None) -> dict:
    """Evaluate variables/locals/expressions/dynamic blocks over a
    parsed HCL dict; returns the evaluated dict with the declaration
    blocks removed (jobspec2/parse.go decode ordering)."""
    parsed = dict(parsed)
    var_decls = parsed.pop("variable", {}) or {}
    values: Dict[str, Any] = {}
    for name, decl in var_decls.items():
        decl = decl if isinstance(decl, dict) else {}
        if variables and name in variables:
            values[name] = variables[name]
        elif "default" in decl:
            values[name] = decl["default"]
        else:
            raise Hcl2Error(f"missing value for required variable {name!r}")
    if variables:
        for name in variables:
            if name not in var_decls:
                raise Hcl2Error(f"undeclared variable {name!r}")

    scope: Dict[str, Any] = {"var": values}
    locals_blocks = parsed.pop("locals", None)
    if locals_blocks:
        blocks = locals_blocks if isinstance(locals_blocks, list) \
            else [locals_blocks]
        local_vals: Dict[str, Any] = {}
        scope["local"] = local_vals
        for blk in blocks:
            for k, v in (blk or {}).items():
                local_vals[k] = _walk(v, scope)
    return _walk_dict(parsed, scope)
