from .hcl import parse_hcl, HclError
from .parse import parse_job, parse_job_file, job_to_spec

__all__ = ["parse_hcl", "HclError", "parse_job", "parse_job_file",
           "job_to_spec"]
