"""A compact HCL parser covering the jobspec grammar.

Reference: jobspec/parse.go consumes HCL1; jobspec2/ consumes HCL2.
This implements the common subset both accept for job files: blocks
(`job "name" { ... }`), attributes (`key = value`), strings with
escapes, numbers, bools, lists, objects, heredocs, comments (#, //,
/* */), and duration-literal passthrough (durations stay strings for
the caller to interpret).

Output shape matches hashicorp/hcl's JSON form: a block `b "x" "y" {..}`
becomes nested dicts {"b": {"x": {"y": {...}}}}; repeated blocks
accumulate into lists.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple


class HclError(ValueError):
    pass


class _Lexer:
    def __init__(self, src: str):
        self.src = src
        self.i = 0
        self.line = 1

    def error(self, msg: str):
        raise HclError(f"line {self.line}: {msg}")

    def _peek(self, offset=0) -> str:
        j = self.i + offset
        return self.src[j] if j < len(self.src) else ""

    def _advance(self) -> str:
        ch = self.src[self.i]
        self.i += 1
        if ch == "\n":
            self.line += 1
        return ch

    def skip_ws(self, skip_newlines=True):
        while self.i < len(self.src):
            ch = self._peek()
            if ch in " \t\r" or (skip_newlines and ch == "\n"):
                self._advance()
            elif ch == "#" or (ch == "/" and self._peek(1) == "/"):
                while self.i < len(self.src) and self._peek() != "\n":
                    self._advance()
            elif ch == "/" and self._peek(1) == "*":
                self._advance(); self._advance()
                while self.i < len(self.src):
                    if self._peek() == "*" and self._peek(1) == "/":
                        self._advance(); self._advance()
                        break
                    self._advance()
                else:
                    self.error("unterminated block comment")
            else:
                return

    def next_token(self) -> Tuple[str, Any]:
        """Returns (kind, value). Kinds: ident, string, number, bool,
        lbrace, rbrace, lbracket, rbracket, assign, comma, newline,
        heredoc, eof."""
        self.skip_ws(skip_newlines=False)
        if self.i >= len(self.src):
            return ("eof", None)
        ch = self._peek()
        if ch == "\n":
            self._advance()
            return ("newline", None)
        if ch == "{":
            self._advance()
            return ("lbrace", None)
        if ch == "}":
            self._advance()
            return ("rbrace", None)
        if ch == "[":
            self._advance()
            return ("lbracket", None)
        if ch == "]":
            self._advance()
            return ("rbracket", None)
        if ch == "=":
            self._advance()
            return ("assign", None)
        if ch == ",":
            self._advance()
            return ("comma", None)
        if ch == ":":
            self._advance()
            return ("colon", None)
        if ch == '"':
            return ("string", self._string())
        if ch == "<" and self._peek(1) == "<":
            return ("heredoc", self._heredoc())
        if ch.isdigit() or (ch == "-" and self._peek(1).isdigit()):
            return self._number_or_duration()
        if ch.isalpha() or ch == "_":
            ident = self._ident()
            if ident == "true":
                return ("bool", True)
            if ident == "false":
                return ("bool", False)
            if ident == "null":
                return ("null", None)
            return ("ident", ident)
        self.error(f"unexpected character {ch!r}")

    def _string(self) -> str:
        self._advance()  # opening quote
        out = []
        while True:
            if self.i >= len(self.src):
                self.error("unterminated string")
            ch = self._advance()
            if ch == '"':
                return "".join(out)
            if ch == "\\":
                esc = self._advance()
                out.append({"n": "\n", "t": "\t", '"': '"', "\\": "\\",
                            "r": "\r"}.get(esc, esc))
            else:
                out.append(ch)

    def _heredoc(self) -> str:
        self._advance(); self._advance()  # <<
        indent = False
        if self._peek() == "-":
            self._advance()
            indent = True
        marker = []
        while self.i < len(self.src) and self._peek() not in "\n":
            marker.append(self._advance())
        marker_s = "".join(marker).strip()
        if self._peek() == "\n":
            self._advance()
        lines: List[str] = []
        while True:
            if self.i >= len(self.src):
                self.error(f"unterminated heredoc <<{marker_s}")
            start = self.i
            while self.i < len(self.src) and self._peek() != "\n":
                self._advance()
            line = self.src[start:self.i]
            if self._peek() == "\n":
                self._advance()
            if line.strip() == marker_s:
                break
            lines.append(line)
        if indent:
            strip = min((len(l) - len(l.lstrip()) for l in lines if l.strip()),
                        default=0)
            lines = [l[strip:] for l in lines]
        return "\n".join(lines) + ("\n" if lines else "")

    def _number_or_duration(self):
        start = self.i
        if self._peek() == "-":
            self._advance()
        while self.i < len(self.src) and (self._peek().isdigit()
                                          or self._peek() in ".eE+-"):
            # stop at duration suffixes
            if self._peek() in "eE" and not self._peek(1).isdigit() \
                    and self._peek(1) not in "+-":
                break
            if self._peek() in "+-" and self.src[self.i - 1] not in "eE":
                break
            self._advance()
        text = self.src[start:self.i]
        # duration suffix? (5s, 10m, 300ms, 1h30m)
        if self.i < len(self.src) and (self._peek().isalpha()):
            while self.i < len(self.src) and (self._peek().isalnum()):
                self._advance()
            return ("string", self.src[start:self.i])
        try:
            if any(c in text for c in ".eE"):
                return ("number", float(text))
            return ("number", int(text))
        except ValueError:
            self.error(f"bad number {text!r}")

    def _ident(self) -> str:
        start = self.i
        while self.i < len(self.src) and (self._peek().isalnum()
                                          or self._peek() in "_-."):
            self._advance()
        return self.src[start:self.i]


class _Parser:
    def __init__(self, src: str):
        self.lex = _Lexer(src)
        self._pushed: Optional[Tuple[str, Any]] = None

    def _next(self, skip_newlines=False) -> Tuple[str, Any]:
        if self._pushed is not None:
            tok = self._pushed
            self._pushed = None
            if not (skip_newlines and tok[0] == "newline"):
                return tok
        while True:
            tok = self.lex.next_token()
            if skip_newlines and tok[0] == "newline":
                continue
            return tok

    def _push(self, tok: Tuple[str, Any]):
        self._pushed = tok

    def parse(self) -> dict:
        body = self._body(top=True)
        return body

    def _body(self, top=False) -> dict:
        out: dict = {}
        while True:
            tok = self._next(skip_newlines=True)
            if tok[0] == "eof":
                if not top:
                    self.lex.error("unexpected EOF inside block")
                return out
            if tok[0] == "rbrace":
                if top:
                    self.lex.error("unexpected '}'")
                return out
            if tok[0] not in ("ident", "string"):
                self.lex.error(f"expected identifier, got {tok[0]}")
            key = tok[1]
            self._statement(out, key)

    def _statement(self, out: dict, key: str):
        labels: List[str] = []
        while True:
            tok = self._next()
            if tok[0] == "assign":
                value = self._value()
                self._set_attr(out, key, value)
                return
            if tok[0] == "string" or tok[0] == "ident":
                labels.append(tok[1])
                continue
            if tok[0] == "lbrace":
                block = self._body()
                self._set_block(out, key, labels, block)
                return
            self.lex.error(f"expected '=', label or '{{' after {key!r}, "
                           f"got {tok[0]}")

    @staticmethod
    def _set_attr(out: dict, key: str, value):
        out[key] = value

    @staticmethod
    def _set_block(out: dict, key: str, labels: List[str], block: dict):
        target = out
        path = [key] + labels
        for part in path[:-1]:
            nxt = target.get(part)
            if not isinstance(nxt, dict) or part not in target:
                nxt = target.setdefault(part, {})
            if isinstance(nxt, list):
                # mixed labeled/unlabeled: append dict container
                container = {}
                nxt.append(container)
                nxt = container
            target = nxt
        last = path[-1]
        existing = target.get(last)
        if existing is None:
            target[last] = block
        elif isinstance(existing, list):
            existing.append(block)
        else:
            target[last] = [existing, block]

    def _value(self):
        tok = self._next(skip_newlines=True)
        kind, val = tok
        if kind in ("string", "number", "bool", "heredoc"):
            return val
        if kind == "null":
            return None
        if kind == "ident":
            return val  # bare word treated as string
        if kind == "lbracket":
            return self._list()
        if kind == "lbrace":
            return self._object()
        self.lex.error(f"unexpected {kind} in value position")

    def _list(self) -> list:
        out = []
        while True:
            tok = self._next(skip_newlines=True)
            if tok[0] == "rbracket":
                return out
            if tok[0] == "comma":
                continue
            self._push(tok)
            out.append(self._value())

    def _object(self) -> dict:
        out = {}
        while True:
            tok = self._next(skip_newlines=True)
            if tok[0] == "rbrace":
                return out
            if tok[0] == "comma":
                continue
            if tok[0] not in ("ident", "string"):
                self.lex.error(f"expected key in object, got {tok[0]}")
            key = tok[1]
            eq = self._next(skip_newlines=True)
            if eq[0] not in ("assign", "colon"):
                self.lex.error("expected '=' or ':' in object")
            out[key] = self._value()


def parse_hcl(src: str) -> dict:
    return _Parser(src).parse()
