"""Jobspec -> Job model mapping.

Reference semantics: jobspec/parse.go (parseJob, parseGroups:xx,
parseConstraints:128, parseAffinities:217, parseSpread:301,
parseUpdate:409, parseTasks, parseResources) and the JSON jobspec
accepted by the HTTP API.
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, List, Optional, Union

from ..models import (
    Affinity, Constraint, EphemeralDisk, Job, LogConfig, MigrateStrategy,
    NetworkResource, ParameterizedJobConfig, PeriodicConfig, Port,
    ReschedulePolicy, Resources, RestartPolicy, Service, ServiceCheck,
    Spread, SpreadTarget, Task, TaskGroup, TaskLifecycleConfig,
    UpdateStrategy, VolumeRequest, VolumeMount,
)
from ..models.resources import RequestedDevice
from .hcl import parse_hcl

_DUR_RE = re.compile(r"(\d+(?:\.\d+)?)(ms|s|m|h|d)")


def parse_duration_s(val: Union[str, int, float, None],
                     default: float = 0.0) -> float:
    """'1h30m' / '500ms' / 30 -> seconds."""
    if val is None:
        return default
    if isinstance(val, (int, float)):
        return float(val)
    s = str(val).strip()
    if not s:
        return default
    total = 0.0
    matched = False
    for num, unit in _DUR_RE.findall(s):
        matched = True
        total += float(num) * {"ms": 0.001, "s": 1, "m": 60, "h": 3600,
                               "d": 86400}[unit]
    if not matched:
        try:
            return float(s)
        except ValueError:
            return default
    return total


def _as_list(v) -> list:
    if v is None:
        return []
    if isinstance(v, list):
        return v
    return [v]


def _one(v):
    """HCL single-block access: a block parses as dict or [dict]."""
    if isinstance(v, list):
        return v[0] if v else None
    return v


def _labeled(v: Optional[dict]) -> List[tuple]:
    """{'name1': {...}, 'name2': {...}} or bare {...} -> [(label, body)]."""
    if v is None:
        return []
    if isinstance(v, list):
        out = []
        for item in v:
            out.extend(_labeled(item))
        return out
    if isinstance(v, dict):
        # labeled form: every value is a dict
        if v and all(isinstance(x, (dict, list)) for x in v.values()):
            out = []
            for label, body in v.items():
                for b in _as_list(body):
                    out.append((label, b))
            return out
        return [("", v)]
    return []


def _constraints(body: dict) -> List[Constraint]:
    out = []
    for c in _as_list(body.get("constraint")):
        if not isinstance(c, dict):
            continue
        operand = c.get("operator", c.get("operand", "="))
        ltarget = c.get("attribute", c.get("ltarget", ""))
        rtarget = str(c.get("value", c.get("rtarget", "")))
        # shorthand forms: distinct_hosts = true, regexp = "...", etc.
        for short in ("distinct_hosts", "distinct_property", "regexp",
                      "version", "semver", "set_contains", "is_set",
                      "is_not_set"):
            if short in c:
                operand = short
                if short in ("distinct_hosts",):
                    ltarget = ltarget or ""
                elif short in ("is_set", "is_not_set"):
                    ltarget = ltarget or str(c[short])
                elif short == "distinct_property":
                    ltarget = str(c[short])
                    rtarget = str(c.get("value", ""))
                else:
                    rtarget = str(c[short])
        out.append(Constraint(ltarget=ltarget, rtarget=rtarget,
                              operand=operand))
    return out


def _affinities(body: dict) -> List[Affinity]:
    out = []
    for a in _as_list(body.get("affinity")):
        if not isinstance(a, dict):
            continue
        operand = a.get("operator", "=")
        for short in ("regexp", "version", "semver", "set_contains",
                      "set_contains_any", "set_contains_all"):
            if short in a:
                operand = short
        out.append(Affinity(
            ltarget=a.get("attribute", ""),
            rtarget=str(a.get("value", a.get(operand, ""))),
            operand=operand,
            weight=int(a.get("weight", 50))))
    return out


def _spreads(body: dict) -> List[Spread]:
    out = []
    for s in _as_list(body.get("spread")):
        if not isinstance(s, dict):
            continue
        targets = []
        for label, t in _labeled(s.get("target")):
            targets.append(SpreadTarget(
                value=label or t.get("value", ""),
                percent=int(t.get("percent", 0))))
        out.append(Spread(attribute=s.get("attribute", ""),
                          weight=int(s.get("weight", 50)),
                          spread_target=targets))
    return out


def _network(body: dict) -> List[NetworkResource]:
    out = []
    for nw in _as_list(body.get("network")):
        if not isinstance(nw, dict):
            continue
        n = NetworkResource(mbits=int(nw.get("mbits", 0)),
                            mode=nw.get("mode", ""))
        for label, p in _labeled(nw.get("port")):
            port = Port(label=label,
                        value=int(p.get("static", 0)),
                        to=int(p.get("to", 0)))
            if port.value:
                n.reserved_ports.append(port)
            else:
                n.dynamic_ports.append(port)
        out.append(n)
    return out


def _resources(body: Optional[dict]) -> Resources:
    if not body:
        return Resources()
    r = Resources(
        cpu=int(body.get("cpu", 100)),
        memory_mb=int(body.get("memory", body.get("memory_mb", 300))),
        disk_mb=int(body.get("disk", 0)),
        networks=_network(body),
    )
    for label, d in _labeled(body.get("device")):
        r.devices.append(RequestedDevice(
            name=label or d.get("name", ""),
            count=int(d.get("count", 1)),
            constraints=_constraints(d),
            affinities=_affinities(d)))
    return r


def _connect(body: dict):
    """connect { sidecar_service { proxy { upstreams ... } } } /
    connect { native = true } / connect { gateway { ingress {...} } }
    (jobspec/parse_service.go parseConnect)."""
    from ..models.services import (
        ConsulConnect, ConsulExposeConfig, ConsulExposePath,
        ConsulGateway, ConsulIngressListener, ConsulIngressService,
        ConsulProxy, ConsulSidecarService, ConsulUpstream, SidecarTask)
    raw = body.get("connect")
    if not raw:
        return None
    cn = _one(raw)
    connect = ConsulConnect(native=bool(cn.get("native", False)))
    if "sidecar_service" in cn:
        ss = _one(cn["sidecar_service"]) or {}
        proxy = None
        if "proxy" in ss:
            pr = _one(ss["proxy"]) or {}
            upstreams = [ConsulUpstream(
                destination_name=u.get("destination_name", ""),
                local_bind_port=int(u.get("local_bind_port", 0)))
                for u in _as_list(pr.get("upstreams"))]
            expose = None
            if "expose" in pr:
                ex = _one(pr["expose"]) or {}
                expose = ConsulExposeConfig(paths=[ConsulExposePath(
                    path=p.get("path", ""),
                    protocol=p.get("protocol", ""),
                    local_path_port=int(p.get("local_path_port", 0)),
                    listener_port=p.get("listener_port", ""))
                    for p in _as_list(ex.get("path"))])
            proxy = ConsulProxy(
                local_service_address=pr.get("local_service_address", ""),
                local_service_port=int(pr.get("local_service_port", 0)),
                upstreams=upstreams, expose=expose,
                config=dict(pr.get("config", {})))
        connect.sidecar_service = ConsulSidecarService(
            tags=list(ss.get("tags", [])), port=ss.get("port", ""),
            proxy=proxy)
    if "sidecar_task" in cn:
        st = _one(cn["sidecar_task"]) or {}
        resources = None
        if "resources" in st:
            r = _one(st["resources"]) or {}
            from ..models import Resources
            resources = Resources(cpu=int(r.get("cpu", 250)),
                                  memory_mb=int(r.get("memory", 128)))
        connect.sidecar_task = SidecarTask(
            name=st.get("name", ""), driver=st.get("driver", ""),
            user=st.get("user", ""), config=dict(_one(st.get("config"))
                                                 or {}),
            env=dict(_one(st.get("env")) or {}), resources=resources,
            meta=dict(_one(st.get("meta")) or {}),
            kill_timeout_s=parse_duration_s(st["kill_timeout"], 5.0)
            if "kill_timeout" in st else None,
            shutdown_delay_s=parse_duration_s(st["shutdown_delay"], 0.0)
            if "shutdown_delay" in st else None,
            kill_signal=st.get("kill_signal", ""))
    if "gateway" in cn:
        gw = _one(cn["gateway"]) or {}
        listeners = []
        ing = _one(gw.get("ingress")) or {}
        for lst in _as_list(ing.get("listener")):
            listeners.append(ConsulIngressListener(
                port=int(lst.get("port", 0)),
                protocol=lst.get("protocol", "tcp"),
                services=[ConsulIngressService(
                    name=sv.get("name", ""),
                    hosts=list(sv.get("hosts", [])))
                    for sv in _as_list(lst.get("service"))]))
        connect.gateway = ConsulGateway(ingress_listeners=listeners)
    return connect


def _services(body: dict) -> List[Service]:
    from ..models import CheckRestart
    out = []
    for s in _as_list(body.get("service")):
        if not isinstance(s, dict):
            continue
        checks = []
        for c in _as_list(s.get("check")):
            cr = None
            if "check_restart" in c:
                crb = _one(c["check_restart"]) or {}
                cr = CheckRestart(
                    limit=int(crb.get("limit", 0)),
                    grace_s=parse_duration_s(crb.get("grace"), 1.0),
                    ignore_warnings=bool(crb.get("ignore_warnings",
                                                 False)))
            checks.append(ServiceCheck(
                name=c.get("name", ""), type=c.get("type", ""),
                path=c.get("path", ""),
                interval_s=parse_duration_s(c.get("interval"), 10.0),
                timeout_s=parse_duration_s(c.get("timeout"), 2.0),
                port_label=c.get("port", ""),
                method=c.get("method", ""),
                protocol=c.get("protocol", ""),
                expose=bool(c.get("expose", False)),
                task_name=c.get("task", ""),
                check_restart=cr))
        out.append(Service(
            name=s.get("name", ""), port_label=s.get("port", ""),
            tags=list(s.get("tags", [])), checks=checks,
            task_name=s.get("task", ""),
            meta=dict(_one(s.get("meta")) or {}),
            connect=_connect(s)))
    return out


def _task(name: str, body: dict) -> Task:
    lifecycle = None
    lc = body.get("lifecycle")
    if isinstance(lc, dict):
        lifecycle = TaskLifecycleConfig(hook=lc.get("hook", ""),
                                        sidecar=bool(lc.get("sidecar", False)))
    volume_mounts = []
    for vm in _as_list(body.get("volume_mount")):
        volume_mounts.append(VolumeMount(
            volume=vm.get("volume", ""),
            destination=vm.get("destination", ""),
            read_only=bool(vm.get("read_only", False))))
    return Task(
        name=name,
        driver=body.get("driver", ""),
        user=body.get("user", ""),
        config=dict(body.get("config", {})),
        env=dict(body.get("env", {})),
        meta=dict(body.get("meta", {})),
        kill_timeout_s=parse_duration_s(body.get("kill_timeout"), 5.0),
        kill_signal=body.get("kill_signal", ""),
        leader=bool(body.get("leader", False)),
        resources=_resources(body.get("resources")),
        constraints=_constraints(body),
        affinities=_affinities(body),
        services=_services(body),
        lifecycle=lifecycle,
        volume_mounts=volume_mounts,
    )


def _restart(body: Optional[dict]) -> Optional[RestartPolicy]:
    if not body:
        return None
    return RestartPolicy(
        attempts=int(body.get("attempts", 2)),
        interval_s=parse_duration_s(body.get("interval"), 1800.0),
        delay_s=parse_duration_s(body.get("delay"), 15.0),
        mode=body.get("mode", "fail"))


def _reschedule(body: Optional[dict]) -> Optional[ReschedulePolicy]:
    if not body:
        return None
    return ReschedulePolicy(
        attempts=int(body.get("attempts", 0)),
        interval_s=parse_duration_s(body.get("interval"), 0.0),
        delay_s=parse_duration_s(body.get("delay"), 30.0),
        delay_function=body.get("delay_function", "exponential"),
        max_delay_s=parse_duration_s(body.get("max_delay"), 3600.0),
        unlimited=bool(body.get("unlimited", "attempts" not in body)))


def _update(body: Optional[dict]) -> Optional[UpdateStrategy]:
    if not body:
        return None
    return UpdateStrategy(
        stagger_s=parse_duration_s(body.get("stagger"), 30.0),
        max_parallel=int(body.get("max_parallel", 1)),
        health_check=body.get("health_check", "checks"),
        min_healthy_time_s=parse_duration_s(body.get("min_healthy_time"), 10.0),
        healthy_deadline_s=parse_duration_s(body.get("healthy_deadline"), 300.0),
        progress_deadline_s=parse_duration_s(body.get("progress_deadline"), 600.0),
        auto_revert=bool(body.get("auto_revert", False)),
        auto_promote=bool(body.get("auto_promote", False)),
        canary=int(body.get("canary", 0)))


def _group(name: str, body: dict, job_update: Optional[dict],
           job_migrate: Optional[dict] = None) -> TaskGroup:
    tasks = [_task(label, b) for label, b in _labeled(body.get("task"))]
    ed = body.get("ephemeral_disk")
    volumes = {}
    for label, v in _labeled(body.get("volume")):
        volumes[label] = VolumeRequest(
            name=label, type=v.get("type", "host"),
            source=v.get("source", ""),
            read_only=bool(v.get("read_only", False)))
    update_body = body.get("update", job_update)
    migrate = body.get("migrate", job_migrate)
    sacd = body.get("stop_after_client_disconnect")
    scaling = body.get("scaling")
    if isinstance(scaling, dict):
        from ..models.job import Scaling
        scaling = Scaling(enabled=bool(scaling.get("enabled", True)),
                          min=int(scaling.get("min", 0)),
                          max=int(scaling.get("max", 0)),
                          policy=dict(scaling.get("policy", {})))
    else:
        scaling = None
    return TaskGroup(
        name=name,
        count=int(body.get("count", 1)),
        constraints=_constraints(body),
        affinities=_affinities(body),
        spreads=_spreads(body),
        tasks=tasks,
        meta=dict(body.get("meta", {})),
        networks=_network(body),
        services=_services(body),
        volumes=volumes,
        restart_policy=_restart(body.get("restart")),
        reschedule_policy=_reschedule(body.get("reschedule")),
        update=_update(update_body),
        scaling=scaling,
        migrate=MigrateStrategy(
            max_parallel=int(migrate.get("max_parallel", 1)),
            min_healthy_time_s=parse_duration_s(
                migrate.get("min_healthy_time"), 10.0),
            healthy_deadline_s=parse_duration_s(
                migrate.get("healthy_deadline"), 300.0),
        ) if isinstance(migrate, dict) else None,
        ephemeral_disk=EphemeralDisk(
            sticky=bool(ed.get("sticky", False)),
            size_mb=int(ed.get("size", ed.get("size_mb", 300))),
            migrate=bool(ed.get("migrate", False)),
        ) if isinstance(ed, dict) else EphemeralDisk(),
        stop_after_client_disconnect_s=(
            parse_duration_s(sacd) if sacd is not None else None),
    )


def parse_job(src: str, variables: dict = None) -> Job:
    """Parse an HCL or JSON jobspec into a canonicalized Job. HCL goes
    through the HCL2 evaluation layer (variables/locals/functions/
    dynamic blocks, jobspec2/parse.go) with caller-supplied variable
    values."""
    src = src.strip()
    if src.startswith("{"):
        data = json.loads(src)
        if "job" in data or "Job" in data:
            data = data.get("job", data.get("Job"))
        if isinstance(data, dict) and "task_groups" in data:
            # the API wire shape: decode straight into the model
            from ..utils.codec import from_wire
            job = from_wire(Job, data)
            job.canonicalize()
            return job
    else:
        from .hcl2 import evaluate
        parsed = evaluate(parse_hcl(src), variables)
        data = parsed.get("job")
        if data is None:
            raise ValueError("jobspec must contain a 'job' block")
    # labeled: {"name": {...}}
    if isinstance(data, dict) and len(data) == 1 and \
            isinstance(next(iter(data.values())), dict) and \
            "group" not in data and "task_groups" not in data:
        job_id, body = next(iter(data.items()))
    else:
        job_id, body = data.get("id", data.get("ID", "")), data
    if not isinstance(body, dict):
        raise ValueError("malformed job block")

    job_update = body.get("update")
    job_migrate = body.get("migrate")
    groups = [_group(label, b, job_update, job_migrate)
              for label, b in _labeled(body.get("group"))]

    periodic = None
    p = body.get("periodic")
    if isinstance(p, dict):
        periodic = PeriodicConfig(
            enabled=bool(p.get("enabled", True)),
            spec=p.get("cron", p.get("spec", "")),
            prohibit_overlap=bool(p.get("prohibit_overlap", False)),
            timezone=p.get("time_zone", "UTC"))
    parameterized = None
    pz = body.get("parameterized")
    if isinstance(pz, dict):
        parameterized = ParameterizedJobConfig(
            payload=pz.get("payload", "optional"),
            meta_required=list(pz.get("meta_required", [])),
            meta_optional=list(pz.get("meta_optional", [])))

    multiregion = None
    mr = _one(body.get("multiregion"))
    if isinstance(mr, dict):
        from ..models.job import (Multiregion, MultiregionRegion,
                                  MultiregionStrategy)
        strategy = None
        st = _one(mr.get("strategy"))
        if isinstance(st, dict):
            strategy = MultiregionStrategy(
                max_parallel=int(st.get("max_parallel", 0)),
                on_failure=st.get("on_failure", ""))
        regions = [MultiregionRegion(
            name=label, count=int(b.get("count", 0)),
            datacenters=list(b.get("datacenters", [])),
            meta=dict(_one(b.get("meta")) or {}))
            for label, b in _labeled(mr.get("region"))]
        multiregion = Multiregion(strategy=strategy, regions=regions)

    job = Job(
        id=job_id,
        name=body.get("name", job_id),
        region=body.get("region", "global"),
        multiregion=multiregion,
        namespace=body.get("namespace", "default"),
        type=body.get("type", "service"),
        priority=int(body.get("priority", 50)),
        all_at_once=bool(body.get("all_at_once", False)),
        datacenters=list(body.get("datacenters", [])),
        constraints=_constraints(body),
        affinities=_affinities(body),
        spreads=_spreads(body),
        update=_update(job_update),
        task_groups=groups,
        meta=dict(body.get("meta", {})),
        periodic=periodic,
        parameterized_job=parameterized,
    )
    job.canonicalize()
    return job


def parse_job_file(path: str, variables: dict = None) -> Job:
    with open(path) as f:
        return parse_job(f.read(), variables)


def job_to_spec(job: Job) -> dict:
    """Job -> wire dict (the JSON API shape)."""
    from ..utils.codec import to_wire
    return to_wire(job)
