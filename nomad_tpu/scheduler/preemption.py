"""Preemption: choosing victim allocations on a node so a higher
priority placement fits.

Reference semantics: scheduler/preemption.go — candidates grouped by
priority ascending with a >=10 priority delta (filterAndGroupPreemptibleAllocs:663),
greedy closest-resource-distance selection (basicResourceDistance:608,
scoreForTaskGroup:640 with the maxParallel penalty:13), then a
superset-filter pass dropping redundant victims (filterSuperset:702).
Node choice across candidates uses the logistic preemption score
(rank.go preemptionScore:773: 1/(1+e^(0.0048*(netPriority-2048)))).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from ..models import Allocation, ComparableResources

MAX_PARALLEL_PENALTY = 50.0
PRIORITY_DELTA = 10


def basic_resource_distance(ask: ComparableResources,
                            used: ComparableResources) -> float:
    mem = cpu = disk = 0.0
    if ask.memory_mb > 0:
        mem = (ask.memory_mb - used.memory_mb) / ask.memory_mb
    if ask.cpu_shares > 0:
        cpu = (ask.cpu_shares - used.cpu_shares) / ask.cpu_shares
    if ask.disk_mb > 0:
        disk = (ask.disk_mb - used.disk_mb) / ask.disk_mb
    return math.sqrt(mem * mem + cpu * cpu + disk * disk)


def score_for_task_group(ask: ComparableResources, used: ComparableResources,
                         max_parallel: int, num_preempted: int) -> float:
    penalty = 0.0
    if max_parallel > 0 and num_preempted >= max_parallel:
        penalty = float(num_preempted + 1 - max_parallel) * MAX_PARALLEL_PENALTY
    return basic_resource_distance(ask, used) + penalty


def net_priority(allocs: List[Allocation]) -> float:
    """rank.go netPriority:749: max priority plus sum/max crowding factor."""
    total = 0
    mx = 0.0
    for a in allocs:
        prio = a.job.priority if a.job else 50
        mx = max(mx, float(prio))
        total += prio
    if mx == 0:
        return 0.0
    return mx + total / mx


def preemption_score(netprio: float) -> float:
    """rank.go preemptionScore:773 — logistic, inflection at 2048."""
    rate = 0.0048
    origin = 2048.0
    return 1.0 / (1.0 + math.exp(rate * (netprio - origin)))


class Preemptor:
    def __init__(self, job_priority: int, namespace: str, job_id: str):
        self.job_priority = job_priority
        self.namespace = namespace
        self.job_id = job_id
        self.current_preemptions: Dict[Tuple[str, str, str], int] = {}
        self.alloc_details: Dict[str, Tuple[int, ComparableResources]] = {}
        self.node_remaining: Optional[ComparableResources] = None
        self.current_allocs: List[Allocation] = []
        self.all_usage = ComparableResources()

    def set_node(self, node) -> None:
        remaining = node.comparable_resources()
        remaining.subtract(node.comparable_reserved_resources())
        self.node_remaining = remaining

    def set_candidates(self, allocs: List[Allocation]) -> None:
        """Candidates exclude the placing job's own allocs, but ALL
        proposed allocs count against the node's remaining capacity —
        otherwise same-job allocs on the node are invisible to the math
        and preemption can approve an oversubscribing placement."""
        self.current_allocs = []
        self.all_usage = ComparableResources()
        for alloc in allocs:
            res = alloc.comparable_resources() or ComparableResources()
            self.all_usage.add(res)
            if alloc.job_id == self.job_id and alloc.namespace == self.namespace:
                continue
            max_parallel = 0
            tg = alloc.job.lookup_task_group(alloc.task_group) if alloc.job else None
            if tg is not None and tg.migrate is not None:
                max_parallel = tg.migrate.max_parallel
            self.alloc_details[alloc.id] = (max_parallel, res)
            self.current_allocs.append(alloc)

    def set_preemptions(self, allocs: List[Allocation]) -> None:
        self.current_preemptions = {}
        for a in allocs:
            key = (a.namespace, a.job_id, a.task_group)
            self.current_preemptions[key] = self.current_preemptions.get(key, 0) + 1

    def _num_preemptions(self, alloc: Allocation) -> int:
        return self.current_preemptions.get(
            (alloc.namespace, alloc.job_id, alloc.task_group), 0)

    def preempt_for_task_group(self, ask: ComparableResources
                               ) -> Optional[List[Allocation]]:
        """Find victims so `ask` fits; None if impossible."""
        needed = ask.copy()
        remaining = self.node_remaining.copy()
        remaining.subtract(self.all_usage)

        groups = self._filter_and_group()
        best: List[Allocation] = []
        all_met = False
        available = remaining.copy()

        for _prio, allocs in groups:
            allocs = list(allocs)
            while allocs and not all_met:
                best_idx = -1
                best_dist = math.inf
                for i, alloc in enumerate(allocs):
                    max_parallel, res = self.alloc_details[alloc.id]
                    dist = score_for_task_group(
                        needed, res, max_parallel,
                        self._num_preemptions(alloc))
                    if dist < best_dist:
                        best_dist = dist
                        best_idx = i
                closest = allocs.pop(best_idx)
                closest_res = self.alloc_details[closest.id][1]
                available.add(closest_res)
                all_met, _dim = available.superset(ask)
                best.append(closest)
                needed.subtract(closest_res)
            if all_met:
                break
        if not all_met:
            return None
        return self._filter_superset(best, remaining, ask)

    def _filter_and_group(self) -> List[Tuple[int, List[Allocation]]]:
        by_prio: Dict[int, List[Allocation]] = {}
        for alloc in self.current_allocs:
            if alloc.job is None:
                continue
            if self.job_priority - alloc.job.priority < PRIORITY_DELTA:
                continue
            by_prio.setdefault(alloc.job.priority, []).append(alloc)
        return sorted(by_prio.items())

    def _filter_superset(self, best: List[Allocation],
                         remaining: ComparableResources,
                         ask: ComparableResources) -> List[Allocation]:
        # sort by distance descending (largest victims first)
        best = sorted(
            best,
            key=lambda a: basic_resource_distance(
                self.alloc_details[a.id][1], ask),
            reverse=True)
        available = remaining.copy()
        out: List[Allocation] = []
        for alloc in best:
            out.append(alloc)
            available.add(self.alloc_details[alloc.id][1])
            met, _ = available.superset(ask)
            if met:
                break
        return out


def link_preemptions(plan, alloc, victims: List[Allocation]) -> None:
    """Record victims on the preempting alloc and stamp the victim stubs
    with the preemptor's id (generic_sched.go handlePreemptions)."""
    alloc.preempted_allocations = [v.id for v in victims]
    victim_ids = set(alloc.preempted_allocations)
    for stubs in plan.node_preemptions.values():
        for stub in stubs:
            if stub.id in victim_ids and not stub.preempted_by_allocation:
                stub.preempted_by_allocation = alloc.id
                stub.desired_description = f"Preempted by alloc ID {alloc.id}"


def preemption_enabled(sched_config, scheduler_type: str) -> bool:
    """operator.go PreemptionConfig gates per scheduler type."""
    pc = sched_config.preemption_config
    if scheduler_type == "system":
        return pc.system_scheduler_enabled
    if scheduler_type == "batch":
        return pc.batch_scheduler_enabled
    if scheduler_type == "service":
        return pc.service_scheduler_enabled
    return False


def find_preemption_placement(snapshot, table, mask, used, ask_vec, job,
                              plan) -> Optional[Tuple[int, List[Allocation], float]]:
    """Across feasible-but-full nodes, find the best (node_idx, victims,
    score) by the logistic preemption score combined with bin-packing —
    the host-side PreemptionScoringIterator + BinPack fallback
    (rank.go:415-448, 732-745)."""
    import numpy as np
    from ..models.funcs import ScoreFitBinPack

    ask = ComparableResources(cpu_shares=float(ask_vec[0]),
                              memory_mb=float(ask_vec[1]),
                              disk_mb=float(ask_vec[2]))
    current_preempted: List[Allocation] = []
    for allocs in plan.node_preemptions.values():
        current_preempted.extend(allocs)

    stopped_ids = {a.id for allocs in plan.node_update.values() for a in allocs}
    stopped_ids |= {a.id for a in current_preempted}

    best: Optional[Tuple[int, List[Allocation], float]] = None
    fits = np.all(used + np.asarray(ask_vec)[None, :] <= table.capacity + 1e-6,
                  axis=1)
    for i in np.nonzero(mask & ~fits)[0]:
        node = table.nodes[i]
        proposed = [a for a in snapshot.allocs_by_node(node.id)
                    if not a.terminal_status() and a.id not in stopped_ids]
        proposed.extend(plan.node_allocation.get(node.id, []))
        p = Preemptor(job.priority, job.namespace, job.id)
        p.set_node(node)
        p.set_candidates(proposed)
        p.set_preemptions(current_preempted)
        victims = p.preempt_for_task_group(ask)
        if not victims:
            continue
        # bandwidth guard: victims are chosen by cpu/mem/disk distance,
        # so verify the eviction also covers the ask's network dimension
        # (full network-preemption variant: preemption.go PreemptForNetwork
        # — tracked as the in-kernel preemption milestone)
        if len(ask_vec) > 3 and ask_vec[3] > 0:
            freed_mbits = 0.0
            for v in victims:
                cr = v.comparable_resources()
                if cr is not None:
                    freed_mbits += sum(nw.mbits for nw in cr.networks)
            if used[i, 3] - freed_mbits + ask_vec[3] > \
                    table.capacity[i, 3] + 1e-6:
                continue
        # score: binpack fit after eviction + logistic preemption score
        util = ComparableResources()
        victim_ids = {v.id for v in victims}
        for a in proposed:
            if a.id not in victim_ids:
                util.add(a.comparable_resources())
        util.add(ask)
        binpack = ScoreFitBinPack(node, util) / 18.0
        pscore = preemption_score(net_priority(victims))
        final = (binpack + pscore) / 2.0
        if best is None or final > best[2]:
            best = (int(i), victims, final)
    return best
