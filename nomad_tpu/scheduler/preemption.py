"""Preemption: choosing victim allocations on a node so a higher
priority placement fits.

Reference semantics: scheduler/preemption.go — candidates grouped by
priority ascending with a >=10 priority delta (filterAndGroupPreemptibleAllocs:663),
greedy closest-resource-distance selection (basicResourceDistance:608,
scoreForTaskGroup:640 with the maxParallel penalty:13), then a
superset-filter pass dropping redundant victims (filterSuperset:702).
Node choice across candidates uses the logistic preemption score
(rank.go preemptionScore:773: 1/(1+e^(0.0048*(netPriority-2048)))).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from ..models import Allocation, ComparableResources

MAX_PARALLEL_PENALTY = 50.0
PRIORITY_DELTA = 10


def basic_resource_distance(ask: ComparableResources,
                            used: ComparableResources) -> float:
    mem = cpu = disk = 0.0
    if ask.memory_mb > 0:
        mem = (ask.memory_mb - used.memory_mb) / ask.memory_mb
    if ask.cpu_shares > 0:
        cpu = (ask.cpu_shares - used.cpu_shares) / ask.cpu_shares
    if ask.disk_mb > 0:
        disk = (ask.disk_mb - used.disk_mb) / ask.disk_mb
    return math.sqrt(mem * mem + cpu * cpu + disk * disk)


def score_for_task_group(ask: ComparableResources, used: ComparableResources,
                         max_parallel: int, num_preempted: int) -> float:
    penalty = 0.0
    if max_parallel > 0 and num_preempted >= max_parallel:
        penalty = float(num_preempted + 1 - max_parallel) * MAX_PARALLEL_PENALTY
    return basic_resource_distance(ask, used) + penalty


def net_priority(allocs: List[Allocation]) -> float:
    """rank.go netPriority:749: max priority plus sum/max crowding factor."""
    total = 0
    mx = 0.0
    for a in allocs:
        prio = a.job.priority if a.job else 50
        mx = max(mx, float(prio))
        total += prio
    if mx == 0:
        return 0.0
    return mx + total / mx


def preemption_score(netprio: float) -> float:
    """rank.go preemptionScore:773 — logistic, inflection at 2048."""
    rate = 0.0048
    origin = 2048.0
    return 1.0 / (1.0 + math.exp(rate * (netprio - origin)))


class Preemptor:
    def __init__(self, job_priority: int, namespace: str, job_id: str):
        self.job_priority = job_priority
        self.namespace = namespace
        self.job_id = job_id
        self.current_preemptions: Dict[Tuple[str, str, str], int] = {}
        self.alloc_details: Dict[str, Tuple[int, ComparableResources]] = {}
        self.node_remaining: Optional[ComparableResources] = None
        self.current_allocs: List[Allocation] = []
        self.all_usage = ComparableResources()

    def set_node(self, node) -> None:
        remaining = node.comparable_resources()
        remaining.subtract(node.comparable_reserved_resources())
        self.node_remaining = remaining

    def set_candidates(self, allocs: List[Allocation]) -> None:
        """Candidates exclude the placing job's own allocs, but ALL
        proposed allocs count against the node's remaining capacity —
        otherwise same-job allocs on the node are invisible to the math
        and preemption can approve an oversubscribing placement."""
        self.current_allocs = []
        self.all_usage = ComparableResources()
        for alloc in allocs:
            res = alloc.comparable_resources() or ComparableResources()
            self.all_usage.add(res)
            if alloc.job_id == self.job_id and alloc.namespace == self.namespace:
                continue
            max_parallel = 0
            tg = alloc.job.lookup_task_group(alloc.task_group) if alloc.job else None
            if tg is not None and tg.migrate is not None:
                max_parallel = tg.migrate.max_parallel
            self.alloc_details[alloc.id] = (max_parallel, res)
            self.current_allocs.append(alloc)

    def set_preemptions(self, allocs: List[Allocation]) -> None:
        self.current_preemptions = {}
        for a in allocs:
            key = (a.namespace, a.job_id, a.task_group)
            self.current_preemptions[key] = self.current_preemptions.get(key, 0) + 1

    def _num_preemptions(self, alloc: Allocation) -> int:
        return self.current_preemptions.get(
            (alloc.namespace, alloc.job_id, alloc.task_group), 0)

    def preempt_for_task_group(self, ask: ComparableResources
                               ) -> Optional[List[Allocation]]:
        """Find victims so `ask` fits; None if impossible."""
        needed = ask.copy()
        remaining = self.node_remaining.copy()
        remaining.subtract(self.all_usage)

        groups = self._filter_and_group()
        best: List[Allocation] = []
        all_met = False
        available = remaining.copy()

        for _prio, allocs in groups:
            allocs = list(allocs)
            while allocs and not all_met:
                best_idx = -1
                best_dist = math.inf
                for i, alloc in enumerate(allocs):
                    max_parallel, res = self.alloc_details[alloc.id]
                    dist = score_for_task_group(
                        needed, res, max_parallel,
                        self._num_preemptions(alloc))
                    if dist < best_dist:
                        best_dist = dist
                        best_idx = i
                closest = allocs.pop(best_idx)
                closest_res = self.alloc_details[closest.id][1]
                available.add(closest_res)
                all_met, _dim = available.superset(ask)
                best.append(closest)
                needed.subtract(closest_res)
            if all_met:
                break
        if not all_met:
            return None
        return self._filter_superset(best, remaining, ask)

    def _filter_and_group(self) -> List[Tuple[int, List[Allocation]]]:
        by_prio: Dict[int, List[Allocation]] = {}
        for alloc in self.current_allocs:
            if alloc.job is None:
                continue
            if self.job_priority - alloc.job.priority < PRIORITY_DELTA:
                continue
            by_prio.setdefault(alloc.job.priority, []).append(alloc)
        return sorted(by_prio.items())

    def _filter_superset(self, best: List[Allocation],
                         remaining: ComparableResources,
                         ask: ComparableResources) -> List[Allocation]:
        # sort by distance descending (largest victims first)
        best = sorted(
            best,
            key=lambda a: basic_resource_distance(
                self.alloc_details[a.id][1], ask),
            reverse=True)
        available = remaining.copy()
        out: List[Allocation] = []
        for alloc in best:
            out.append(alloc)
            available.add(self.alloc_details[alloc.id][1])
            met, _ = available.superset(ask)
            if met:
                break
        return out


def link_preemptions(plan, alloc, victims: List[Allocation]) -> None:
    """Record victims on the preempting alloc and stamp the victim stubs
    with the preemptor's id (generic_sched.go handlePreemptions)."""
    alloc.preempted_allocations = [v.id for v in victims]
    victim_ids = set(alloc.preempted_allocations)
    for stubs in plan.node_preemptions.values():
        for stub in stubs:
            if stub.id in victim_ids and not stub.preempted_by_allocation:
                stub.preempted_by_allocation = alloc.id
                stub.desired_description = f"Preempted by alloc ID {alloc.id}"


def preemption_enabled(sched_config, scheduler_type: str) -> bool:
    """operator.go PreemptionConfig gates per scheduler type."""
    pc = sched_config.preemption_config
    if scheduler_type == "system":
        return pc.system_scheduler_enabled
    if scheduler_type == "batch":
        return pc.batch_scheduler_enabled
    if scheduler_type == "service":
        return pc.service_scheduler_enabled
    return False


class PreemptionRound:
    """Preemption placement across nodes, amortized over an eval.

    The naive fallback recomputed every node's victim set for every
    failed instance — O(instances x nodes) Preemptor runs, the dominant
    cost of preemption-heavy evals. This round object computes each
    node's (victims, score) entry once and then only re-derives entries
    whose inputs changed: the plan state touching the node (placements,
    stops, preemptions) is captured in a per-node signature, plus the
    global max_parallel preemption counts for the job groups present on
    the node (the only cross-node coupling in the scoring —
    scoreForTaskGroup's penalty). Semantics per node are byte-identical
    to the one-shot path: PreemptionScoringIterator + BinPack fallback
    (rank.go:415-448, 732-745).
    """

    def __init__(self, snapshot, table, mask, ask_vec, job, plan):
        import numpy as np
        self.snapshot = snapshot
        self.table = table
        self.mask = mask
        self.ask_vec = ask_vec
        self.job = job
        self.plan = plan
        self.ask = ComparableResources(cpu_shares=float(ask_vec[0]),
                                       memory_mb=float(ask_vec[1]),
                                       disk_mb=float(ask_vec[2]))
        n = len(table.nodes)
        # computed state: known[i] -> score[i] (-1 = infeasible) and
        # victim lists; invalidation is *dirty-tracked* from the plan's
        # per-node entry counts instead of re-hashed per call
        self._known = np.zeros(n, bool)
        self._scores = np.full(n, -1.0, np.float64)
        self._victims: Dict[int, List[Allocation]] = {}
        # idx -> group keys on the node that carry max_parallel > 0
        self._mp_groups: Dict[int, frozenset] = {}
        self._last_counts: Dict[str, Tuple[int, int, int]] = {}
        self._last_mp_counts: Dict[Tuple, int] = {}

    # -- plan-state dirty tracking ------------------------------------
    def _preempted_now(self) -> List[Allocation]:
        out: List[Allocation] = []
        for allocs in self.plan.node_preemptions.values():
            out.extend(allocs)
        return out

    def _invalidate_dirty(self, current: List[Allocation]) -> None:
        """Drop cached entries for nodes whose plan state changed since
        the last call. Only nodes that appear in the plan's dicts can
        have changed — O(touched nodes), not O(all nodes)."""
        p = self.plan
        id_to_idx = self.table.id_to_idx
        touched: Dict[str, Tuple[int, int, int]] = {}
        for nid in (p.node_allocation.keys() | p.node_update.keys()
                    | p.node_preemptions.keys()):
            touched[nid] = (len(p.node_allocation.get(nid, ())),
                            len(p.node_update.get(nid, ())),
                            len(p.node_preemptions.get(nid, ())))
        for nid, counts in touched.items():
            if self._last_counts.get(nid) != counts:
                self._last_counts[nid] = counts
                idx = id_to_idx.get(nid)
                if idx is not None:
                    self._known[idx] = False
        # global coupling: max_parallel penalties depend on the total
        # preempted count per group; invalidate nodes holding candidates
        # of groups whose count changed
        mp_counts: Dict[Tuple, int] = {}
        for a in current:
            key = (a.namespace, a.job_id, a.task_group)
            mp_counts[key] = mp_counts.get(key, 0) + 1
        if mp_counts != self._last_mp_counts:
            changed = {k for k in (mp_counts.keys()
                                   | self._last_mp_counts.keys())
                       if mp_counts.get(k) != self._last_mp_counts.get(k)}
            self._last_mp_counts = mp_counts
            for idx, groups in self._mp_groups.items():
                if groups & changed:
                    self._known[idx] = False

    # -- per-node evaluation (exact one-shot semantics) ----------------
    def _evaluate_node(self, i: int, used_row,
                       current: List[Allocation],
                       stopped_ids: set) -> Tuple[Optional[List[Allocation]],
                                                  float]:
        from ..models.funcs import ScoreFitBinPack

        node = self.table.nodes[i]
        proposed = [a for a in self.snapshot.allocs_by_node(node.id)
                    if not a.terminal_status() and a.id not in stopped_ids]
        proposed.extend(self.plan.node_allocation.get(node.id, []))
        p = Preemptor(self.job.priority, self.job.namespace, self.job.id)
        p.set_node(node)
        p.set_candidates(proposed)
        p.set_preemptions(current)
        # remember the max_parallel-bearing groups for invalidation
        mp = set()
        for a in p.current_allocs:
            if p.alloc_details[a.id][0] > 0:
                mp.add((a.namespace, a.job_id, a.task_group))
        self._mp_groups[i] = frozenset(mp)
        victims = p.preempt_for_task_group(self.ask)
        if not victims:
            return None, 0.0
        # bandwidth guard: victims are chosen by cpu/mem/disk distance,
        # so verify the eviction also covers the ask's network dimension
        # (full network-preemption variant: preemption.go PreemptForNetwork)
        if len(self.ask_vec) > 3 and self.ask_vec[3] > 0:
            freed_mbits = 0.0
            for v in victims:
                cr = v.comparable_resources()
                if cr is not None:
                    freed_mbits += sum(nw.mbits for nw in cr.networks)
            if used_row[3] - freed_mbits + self.ask_vec[3] > \
                    self.table.capacity[i, 3] + 1e-6:
                return None, 0.0
        # score: binpack fit after eviction + logistic preemption score
        util = ComparableResources()
        victim_ids = {v.id for v in victims}
        for a in proposed:
            if a.id not in victim_ids:
                util.add(a.comparable_resources())
        util.add(self.ask)
        binpack = ScoreFitBinPack(node, util) / 18.0
        pscore = preemption_score(net_priority(victims))
        return victims, (binpack + pscore) / 2.0

    # -- entry ---------------------------------------------------------
    def find_placement(self, used) -> Optional[Tuple[int, List[Allocation],
                                                     float]]:
        """Best (node_idx, victims, score) for one failed instance, or
        None. `used` is the current proposed usage [N, D]."""
        import numpy as np

        current = self._preempted_now()
        self._invalidate_dirty(current)

        fits = np.all(used + np.asarray(self.ask_vec)[None, :]
                      <= self.table.capacity + 1e-6, axis=1)
        candidates = self.mask & ~fits
        pending = np.nonzero(candidates & ~self._known)[0]
        if len(pending):
            stopped_ids = {a.id for allocs in self.plan.node_update.values()
                           for a in allocs}
            stopped_ids |= {a.id for a in current}
            for i in pending:
                i = int(i)
                victims, score = self._evaluate_node(
                    i, used[i], current, stopped_ids)
                self._known[i] = True
                if victims:
                    self._scores[i] = score
                    self._victims[i] = victims
                else:
                    self._scores[i] = -1.0
                    self._victims.pop(i, None)
        masked = np.where(candidates & self._known, self._scores, -1.0)
        best_i = int(np.argmax(masked))
        if masked[best_i] < 0:
            return None
        return best_i, self._victims[best_i], float(masked[best_i])


def find_preemption_placement(snapshot, table, mask, used, ask_vec, job,
                              plan) -> Optional[Tuple[int, List[Allocation], float]]:
    """One-shot wrapper over PreemptionRound (kept for callers that
    only need a single placement)."""
    return PreemptionRound(snapshot, table, mask, ask_vec, job,
                           plan).find_placement(used)
