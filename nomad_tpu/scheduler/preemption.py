"""Preemption: choosing victim allocations on a node so a higher
priority placement fits.

Reference semantics: scheduler/preemption.go — candidates grouped by
priority ascending with a >=10 priority delta (filterAndGroupPreemptibleAllocs:663),
greedy closest-resource-distance selection (basicResourceDistance:608,
scoreForTaskGroup:640 with the maxParallel penalty:13), then a
superset-filter pass dropping redundant victims (filterSuperset:702).
Node choice across candidates uses the logistic preemption score
(rank.go preemptionScore:773: 1/(1+e^(0.0048*(netPriority-2048)))).
"""

from __future__ import annotations

import math
import os
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..models import Allocation, ComparableResources
from ..utils import stages

MAX_PARALLEL_PENALTY = 50.0
PRIORITY_DELTA = 10

# -- batched columnar victim selection (ISSUE 10) ----------------------
#
# ServerConfig.preempt_* knobs land here via configure() (the
# store.alloc_index.enabled idiom — the scheduler has no ServerConfig
# in scope). NOMAD_TPU_COLUMNAR_PREEMPT=0 is the runtime kill switch:
# it forces the per-node reference Preemptor for every round, exactly
# like NOMAD_TPU_COLUMNAR_RECONCILE=0 reverts the reconcile engine.

_COLUMNAR = True
# per-node candidate cap for the dense [nodes, candidates] matrix; a
# node with more eligible candidates than this takes the per-node
# reference path (the matrix would pad every other node to its width)
ROWS_MAX = 4096
# victim-set memo bound (table.preempt_cache); crossing it clears the
# memo — the governor's preemption.victim_cache_entries watermark
# (governor_preempt_cache_high) reclaims earlier
CACHE_MAX = 200_000

# unlocked counters (the BUILD_STATS idiom: racy increments are
# tolerated — these feed gauges and the bench artifact, not billing)
PREEMPT_STATS: Dict[str, float] = {
    "nodes_scanned": 0, "candidate_rows": 0,
    "cache_hits": 0, "cache_misses": 0,
    "invalidations": 0, "cache_clears": 0,
    "columnar_nodes": 0, "fallback_nodes": 0,
    "select_s": 0.0,
}


def configure(columnar: Optional[bool] = None,
              rows_max: Optional[int] = None,
              cache_max: Optional[int] = None) -> None:
    """Install ServerConfig.preempt_* knobs (Server.__init__)."""
    global _COLUMNAR, ROWS_MAX, CACHE_MAX
    if columnar is not None:
        _COLUMNAR = bool(columnar)
    if rows_max is not None:
        ROWS_MAX = int(rows_max)
    if cache_max is not None:
        CACHE_MAX = int(cache_max)


def columnar_enabled() -> bool:
    # same env grammar as reconcile_columnar.columnar_enabled — an
    # operator flipping both kill switches must not need two spellings
    return _COLUMNAR and os.environ.get(
        "NOMAD_TPU_COLUMNAR_PREEMPT", "1").lower() \
        not in ("0", "false", "no", "off")


def preempt_stats() -> Dict[str, float]:
    return dict(PREEMPT_STATS)


def basic_resource_distance(ask: ComparableResources,
                            used: ComparableResources) -> float:
    mem = cpu = disk = 0.0
    if ask.memory_mb > 0:
        mem = (ask.memory_mb - used.memory_mb) / ask.memory_mb
    if ask.cpu_shares > 0:
        cpu = (ask.cpu_shares - used.cpu_shares) / ask.cpu_shares
    if ask.disk_mb > 0:
        disk = (ask.disk_mb - used.disk_mb) / ask.disk_mb
    return math.sqrt(mem * mem + cpu * cpu + disk * disk)


def score_for_task_group(ask: ComparableResources, used: ComparableResources,
                         max_parallel: int, num_preempted: int) -> float:
    penalty = 0.0
    if max_parallel > 0 and num_preempted >= max_parallel:
        penalty = float(num_preempted + 1 - max_parallel) * MAX_PARALLEL_PENALTY
    return basic_resource_distance(ask, used) + penalty


def net_priority(allocs: List[Allocation]) -> float:
    """rank.go netPriority:749: max priority plus sum/max crowding factor."""
    total = 0
    mx = 0.0
    for a in allocs:
        prio = a.job.priority if a.job else 50
        mx = max(mx, float(prio))
        total += prio
    if mx == 0:
        return 0.0
    return mx + total / mx


def preemption_score(netprio: float) -> float:
    """rank.go preemptionScore:773 — logistic, inflection at 2048."""
    rate = 0.0048
    origin = 2048.0
    return 1.0 / (1.0 + math.exp(rate * (netprio - origin)))


class Preemptor:
    def __init__(self, job_priority: int, namespace: str, job_id: str):
        self.job_priority = job_priority
        self.namespace = namespace
        self.job_id = job_id
        self.current_preemptions: Dict[Tuple[str, str, str], int] = {}
        self.alloc_details: Dict[str, Tuple[int, ComparableResources]] = {}
        self.node_remaining: Optional[ComparableResources] = None
        self.current_allocs: List[Allocation] = []
        self.all_usage = ComparableResources()

    def set_node(self, node) -> None:
        remaining = node.comparable_resources()
        remaining.subtract(node.comparable_reserved_resources())
        self.node_remaining = remaining

    def set_candidates(self, allocs: List[Allocation]) -> None:
        """Candidates exclude the placing job's own allocs, but ALL
        proposed allocs count against the node's remaining capacity —
        otherwise same-job allocs on the node are invisible to the math
        and preemption can approve an oversubscribing placement."""
        self.current_allocs = []
        self.all_usage = ComparableResources()
        for alloc in allocs:
            res = alloc.comparable_resources() or ComparableResources()
            self.all_usage.add(res)
            if alloc.job_id == self.job_id and alloc.namespace == self.namespace:
                continue
            max_parallel = 0
            tg = alloc.job.lookup_task_group(alloc.task_group) if alloc.job else None
            if tg is not None and tg.migrate is not None:
                max_parallel = tg.migrate.max_parallel
            self.alloc_details[alloc.id] = (max_parallel, res)
            self.current_allocs.append(alloc)

    def set_preemptions(self, allocs: List[Allocation]) -> None:
        self.current_preemptions = {}
        for a in allocs:
            key = (a.namespace, a.job_id, a.task_group)
            self.current_preemptions[key] = self.current_preemptions.get(key, 0) + 1

    def _num_preemptions(self, alloc: Allocation) -> int:
        return self.current_preemptions.get(
            (alloc.namespace, alloc.job_id, alloc.task_group), 0)

    def preempt_for_task_group(self, ask: ComparableResources
                               ) -> Optional[List[Allocation]]:
        """Find victims so `ask` fits; None if impossible."""
        needed = ask.copy()
        remaining = self.node_remaining.copy()
        remaining.subtract(self.all_usage)

        groups = self._filter_and_group()
        best: List[Allocation] = []
        all_met = False
        available = remaining.copy()

        for _prio, allocs in groups:
            allocs = list(allocs)
            while allocs and not all_met:
                best_idx = -1
                best_dist = math.inf
                for i, alloc in enumerate(allocs):
                    max_parallel, res = self.alloc_details[alloc.id]
                    dist = score_for_task_group(
                        needed, res, max_parallel,
                        self._num_preemptions(alloc))
                    if dist < best_dist:
                        best_dist = dist
                        best_idx = i
                closest = allocs.pop(best_idx)
                closest_res = self.alloc_details[closest.id][1]
                available.add(closest_res)
                all_met, _dim = available.superset(ask)
                best.append(closest)
                needed.subtract(closest_res)
            if all_met:
                break
        if not all_met:
            return None
        return self._filter_superset(best, remaining, ask)

    def preempt_for_device(self, req, node) -> Optional[List[Allocation]]:
        """Victims freeing device instances so `req` (a RequestedDevice)
        fits — preemption.go PreemptForDevice:472. Candidates holding
        instances of a matching group are taken lowest-priority-first,
        closest-distance within a priority band, until enough instances
        are free."""
        from .devices import group_satisfies

        def held_in(alloc, gid) -> int:
            res = alloc.allocated_resources
            if res is None:
                return 0
            return sum(len(dev.device_ids)
                       for tr in res.tasks.values()
                       for dev in tr.devices if dev.id_tuple() == gid)

        best: Optional[List[Allocation]] = None
        for g in node.node_resources.devices:
            if not group_satisfies(g, req):
                continue
            gid = g.id_tuple()
            total = sum(1 for i in g.instances if i.healthy)
            held_all = 0
            holders: List[Tuple[int, Allocation, int]] = []
            for alloc in self.current_allocs:
                h = held_in(alloc, gid)
                if h == 0:
                    continue
                held_all += h
                if alloc.job is not None and \
                        self.job_priority - alloc.job.priority >= \
                        PRIORITY_DELTA:
                    holders.append((alloc.job.priority, alloc, h))
            free = total - held_all
            if free >= req.count:
                return []                   # nothing to evict
            holders.sort(key=lambda t: (t[0], t[1].id))
            victims: List[Allocation] = []
            for _prio, alloc, h in holders:
                victims.append(alloc)
                free += h
                if free >= req.count:
                    break
            if free >= req.count and \
                    (best is None or len(victims) < len(best)):
                best = victims
        return best

    def preempt_for_network(self, reserved_ports: List[int],
                            mbits_needed: float, node,
                            already_freed_mbits: float = 0.0,
                            skip_ids: Optional[set] = None
                            ) -> Optional[List[Allocation]]:
        """Victims freeing colliding reserved ports and/or bandwidth —
        preemption.go PreemptForNetwork:270. Port holders are mandatory
        victims; bandwidth shortfall fills lowest-priority-first."""
        want_ports = set(reserved_ports or [])
        victims: List[Allocation] = []
        victim_ids = set()
        eligible: List[Tuple[int, float, Allocation, float]] = []
        used_mbits = 0.0
        node_mbits = sum(nw.mbits for nw in
                         node.node_resources.networks) or 0.0
        for alloc in self.current_allocs:
            _mp, res = self.alloc_details[alloc.id]
            alloc_ports = set()
            alloc_mbits = 0.0
            for nw in res.networks:
                alloc_mbits += nw.mbits
                alloc_ports.update(p.value for p in nw.reserved_ports)
            used_mbits += alloc_mbits
            is_eligible = (alloc.job is not None and self.job_priority -
                           alloc.job.priority >= PRIORITY_DELTA)
            if want_ports & alloc_ports:
                if skip_ids and alloc.id in skip_ids:
                    continue                # already evicted upstream
                if not is_eligible:
                    return None             # holder can't be preempted
                victims.append(alloc)
                victim_ids.add(alloc.id)
            elif is_eligible and alloc_mbits > 0:
                eligible.append((alloc.job.priority,
                                 -alloc_mbits, alloc, alloc_mbits))
        freed = already_freed_mbits + sum(
            sum(nw.mbits for nw in self.alloc_details[v.id][1].networks)
            for v in victims)
        if node_mbits and mbits_needed > 0:
            shortfall = (used_mbits - freed + mbits_needed) - node_mbits
            if shortfall > 0:
                eligible.sort(key=lambda t: (t[0], t[1], t[2].id))
                for _prio, _neg, alloc, mb in eligible:
                    if alloc.id in victim_ids or \
                            (skip_ids and alloc.id in skip_ids):
                        continue
                    victims.append(alloc)
                    victim_ids.add(alloc.id)
                    shortfall -= mb
                    if shortfall <= 0:
                        break
                if shortfall > 0:
                    return None
        return victims

    def _filter_and_group(self) -> List[Tuple[int, List[Allocation]]]:
        by_prio: Dict[int, List[Allocation]] = {}
        for alloc in self.current_allocs:
            if alloc.job is None:
                continue
            if self.job_priority - alloc.job.priority < PRIORITY_DELTA:
                continue
            by_prio.setdefault(alloc.job.priority, []).append(alloc)
        return sorted(by_prio.items())

    def _filter_superset(self, best: List[Allocation],
                         remaining: ComparableResources,
                         ask: ComparableResources) -> List[Allocation]:
        # sort by distance descending (largest victims first)
        best = sorted(
            best,
            key=lambda a: basic_resource_distance(
                self.alloc_details[a.id][1], ask),
            reverse=True)
        available = remaining.copy()
        out: List[Allocation] = []
        for alloc in best:
            out.append(alloc)
            available.add(self.alloc_details[alloc.id][1])
            met, _ = available.superset(ask)
            if met:
                break
        return out


def link_preemptions(plan, alloc, victims: List[Allocation]) -> None:
    """Record victims on the preempting alloc and stamp the victim stubs
    with the preemptor's id (generic_sched.go handlePreemptions)."""
    alloc.preempted_allocations = [v.id for v in victims]
    victim_ids = set(alloc.preempted_allocations)
    for stubs in plan.node_preemptions.values():
        for stub in stubs:
            if stub.id in victim_ids and not stub.preempted_by_allocation:
                stub.preempted_by_allocation = alloc.id
                stub.desired_description = f"Preempted by alloc ID {alloc.id}"


def preemption_enabled(sched_config, scheduler_type: str) -> bool:
    """operator.go PreemptionConfig gates per scheduler type."""
    pc = sched_config.preemption_config
    if scheduler_type == "system":
        return pc.system_scheduler_enabled
    if scheduler_type == "batch":
        return pc.batch_scheduler_enabled
    if scheduler_type == "service":
        return pc.service_scheduler_enabled
    return False


class PreemptionRound:
    """Preemption placement across nodes, amortized over an eval.

    The naive fallback recomputed every node's victim set for every
    failed instance — O(instances x nodes) Preemptor runs, the dominant
    cost of preemption-heavy evals. This round object computes each
    node's (victims, score) entry once and then only re-derives entries
    whose inputs changed: the plan state touching the node (placements,
    stops, preemptions) is captured in a per-node signature, plus the
    global max_parallel preemption counts for the job groups present on
    the node (the only cross-node coupling in the scoring —
    scoreForTaskGroup's penalty). Semantics per node are byte-identical
    to the one-shot path: PreemptionScoringIterator + BinPack fallback
    (rank.go:415-448, 732-745).
    """

    def __init__(self, snapshot, table, mask, ask_vec, job, plan,
                 tg=None):
        self.snapshot = snapshot
        self.table = table
        self.mask = mask
        self.ask_vec = ask_vec
        self.job = job
        self.plan = plan
        self.tg = tg          # enables device/network preemption variants
        self.ask = ComparableResources(cpu_shares=float(ask_vec[0]),
                                       memory_mb=float(ask_vec[1]),
                                       disk_mb=float(ask_vec[2]))
        n = len(table.nodes)
        # cross-eval cache key parts: the tg's port/device shape and the
        # ask vector (victims depend on both); the per-node row identity
        # completes the key at lookup time
        reserved: Tuple = ()
        devs: Tuple = ()
        if tg is not None:
            from .stack import PlacementEngine
            dyn, rs = PlacementEngine._port_asks(tg)
            reserved = (dyn, tuple(sorted(rs)))
            from .devices import combined_device_asks
            # constraints/affinities change the victim set
            # (group_satisfies evaluates them), so they are part of the
            # cache identity
            devs = tuple(
                (r.name, r.count,
                 tuple((c.ltarget, c.rtarget, c.operand)
                       for c in (r.constraints or [])),
                 tuple((a.ltarget, a.rtarget, a.operand, a.weight)
                       for a in (r.affinities or [])))
                for r in combined_device_asks(tg))
        self._cache_sig = (job.priority, tuple(float(x) for x in ask_vec),
                          reserved, devs)
        # batched victim selection handles the resource dimensions; a
        # device or network-port/bandwidth ask keeps the per-node
        # reference path — PreemptForDevice / PreemptForNetwork walk
        # instance tables and port bitsets per alloc, exactly the rows
        # reconcile_columnar.py also drops to Python for
        mbits_need = float(ask_vec[3]) if len(ask_vec) > 3 else 0.0
        self._columnar = (columnar_enabled() and not devs
                          and not (reserved and reserved[1])
                          and not mbits_need > 0)
        # computed state: known[i] -> score[i] (-1 = infeasible) and
        # victim lists; invalidation is *dirty-tracked* from the plan's
        # per-node entry counts instead of re-hashed per call
        self._known = np.zeros(n, bool)
        self._scores = np.full(n, -1.0, np.float64)
        self._logistic = np.zeros(n, np.float64)
        self._freed = np.zeros((n, 4), np.float64)
        self._victims: Dict[int, List[Allocation]] = {}
        # idx -> group keys on the node that carry max_parallel > 0
        self._mp_groups: Dict[int, frozenset] = {}
        self._last_counts: Dict[str, Tuple[int, int, int]] = {}
        self._last_mp_counts: Dict[Tuple, int] = {}

    # -- plan-state dirty tracking ------------------------------------
    def _preempted_now(self) -> List[Allocation]:
        out: List[Allocation] = []
        for allocs in self.plan.node_preemptions.values():
            out.extend(allocs)
        return out

    def _invalidate_dirty(self, current: List[Allocation]) -> None:
        """Drop cached entries for nodes whose plan state changed since
        the last call. Only nodes that appear in the plan's dicts can
        have changed — O(touched nodes), not O(all nodes)."""
        p = self.plan
        id_to_idx = self.table.id_to_idx
        touched: Dict[str, Tuple[int, int, int]] = {}
        for nid in (p.node_allocation.keys() | p.node_update.keys()
                    | p.node_preemptions.keys()):
            touched[nid] = (len(p.node_allocation.get(nid, ())),
                            len(p.node_update.get(nid, ())),
                            len(p.node_preemptions.get(nid, ())))
        for nid, counts in touched.items():
            if self._last_counts.get(nid) != counts:
                self._last_counts[nid] = counts
                idx = id_to_idx.get(nid)
                if idx is not None:
                    if self._known[idx]:
                        PREEMPT_STATS["invalidations"] += 1
                    self._known[idx] = False
        # global coupling: max_parallel penalties depend on the total
        # preempted count per group; invalidate nodes holding candidates
        # of groups whose count changed
        mp_counts: Dict[Tuple, int] = {}
        for a in current:
            key = (a.namespace, a.job_id, a.task_group)
            mp_counts[key] = mp_counts.get(key, 0) + 1
        if mp_counts != self._last_mp_counts:
            changed = {k for k in (mp_counts.keys()
                                   | self._last_mp_counts.keys())
                       if mp_counts.get(k) != self._last_mp_counts.get(k)}
            self._last_mp_counts = mp_counts
            for idx, groups in self._mp_groups.items():
                if groups & changed:
                    if self._known[idx]:
                        PREEMPT_STATS["invalidations"] += 1
                    self._known[idx] = False

    # -- per-node evaluation (exact one-shot semantics) ----------------
    def _cacheable(self, i: int) -> bool:
        """A node's victim entry can cross evals when nothing specific
        to THIS eval touches it: no plan entries on the node, and no
        allocs of the placing job among its candidates (the own-job
        exclusion makes victims job-relative)."""
        node_id = self.table.ids[i]
        p = self.plan
        if node_id in p.node_allocation or node_id in p.node_update \
                or node_id in p.node_preemptions:
            return False
        ns, jid = self.job.namespace, self.job.id
        for a in self.table.live_allocs[i]:
            if a.job_id == jid and a.namespace == ns:
                return False
        return True

    def _evaluate_node(self, i: int, used_row,
                       current: List[Allocation],
                       stopped_ids: set) -> Tuple[Optional[List[Allocation]],
                                                  float]:
        from ..models.funcs import ScoreFitBinPack

        # cross-eval fast path: an unchanged live-alloc row (identity —
        # rows are replaced copy-on-write) under the same priority/ask/
        # port/device signature yields the same victims; entries with
        # max_parallel-bearing candidates are never cached because their
        # penalty couples to the eval's running preemption counts
        cacheable = self._cacheable(i)
        row = self.table.live_allocs[i]
        key = (id(row), self._cache_sig)
        if cacheable:
            hit = self.table.preempt_cache.get(key)
            if hit is not None and hit[0] is row:
                PREEMPT_STATS["cache_hits"] += 1
                _row, victims, score, logistic, freed = hit
                self._logistic[i] = logistic
                self._freed[i] = freed
                self._mp_groups[i] = frozenset()
                return (list(victims) if victims is not None else None,
                        score)

        node = self.table.nodes[i]
        proposed = [a for a in self.snapshot.allocs_by_node(node.id)
                    if not a.terminal_status() and a.id not in stopped_ids]
        proposed.extend(self.plan.node_allocation.get(node.id, []))
        p = Preemptor(self.job.priority, self.job.namespace, self.job.id)
        p.set_node(node)
        p.set_candidates(proposed)
        p.set_preemptions(current)
        # remember the max_parallel-bearing groups for invalidation
        mp = set()
        for a in p.current_allocs:
            if p.alloc_details[a.id][0] > 0:
                mp.add((a.namespace, a.job_id, a.task_group))
        self._mp_groups[i] = frozenset(mp)

        def memo(victims_out, score, logistic=0.0, freed=None):
            """Record the result in the cross-eval cache when safe: the
            node wasn't eval-specific (_cacheable) and no candidate
            carries max_parallel (whose penalty couples to the running
            preemption counts of this eval)."""
            if cacheable and not mp:
                if len(self.table.preempt_cache) > CACHE_MAX:
                    self.table.preempt_cache.clear()
                    PREEMPT_STATS["cache_clears"] += 1
                self.table.preempt_cache[key] = (
                    row,
                    list(victims_out) if victims_out is not None else None,
                    score, logistic,
                    freed if freed is not None else np.zeros(4, np.float64))
            return victims_out, score

        # resource-dimension victims (skipped when the node already
        # fits on cpu/mem/disk and is a candidate only for device/port
        # reasons)
        res_fits = bool(np.all(
            used_row[:3] + np.asarray(self.ask_vec[:3])
            <= self.table.capacity[i, :3] + 1e-6))
        if res_fits:
            victims: List[Allocation] = []
        else:
            victims = p.preempt_for_task_group(self.ask)
            if not victims:
                return memo(None, 0.0)
            victims = list(victims)
        victim_ids = {v.id for v in victims}

        # device variant (preemption.go PreemptForDevice:472)
        if self.tg is not None:
            from .devices import combined_device_asks
            for reqd in combined_device_asks(self.tg):
                dvict = p.preempt_for_device(reqd, node)
                if dvict is None:
                    return memo(None, 0.0)
                for v in dvict:
                    if v.id not in victim_ids:
                        victims.append(v)
                        victim_ids.add(v.id)

        # network variant (preemption.go PreemptForNetwork:270):
        # reserved-port collisions and the bandwidth dimension
        reserved_ports: List[int] = []
        if self.tg is not None:
            from .stack import PlacementEngine
            _dyn, reserved_ports = PlacementEngine._port_asks(self.tg)
        mbits_needed = float(self.ask_vec[3]) \
            if len(self.ask_vec) > 3 else 0.0
        if reserved_ports or mbits_needed > 0:
            freed_mbits = 0.0
            for v in victims:
                cr = v.comparable_resources()
                if cr is not None:
                    freed_mbits += sum(nw.mbits for nw in cr.networks)
            nvict = p.preempt_for_network(reserved_ports, mbits_needed,
                                          node,
                                          already_freed_mbits=freed_mbits,
                                          skip_ids=victim_ids)
            if nvict is None:
                return memo(None, 0.0)
            for v in nvict:
                if v.id not in victim_ids:
                    victims.append(v)
                    victim_ids.add(v.id)
        if not victims:
            return memo(None, 0.0)
        # score: binpack fit after eviction + logistic preemption score
        util = ComparableResources()
        victim_ids = {v.id for v in victims}
        for a in proposed:
            if a.id not in victim_ids:
                util.add(a.comparable_resources())
        util.add(self.ask)
        binpack = ScoreFitBinPack(node, util) / 18.0
        pscore = preemption_score(net_priority(victims))
        # resources the evictions free, in kernel dim order
        # (cpu, memory, disk, network mbits)
        freed = np.zeros(4, np.float64)
        for v in victims:
            cr = v.comparable_resources()
            if cr is None:
                continue
            freed[0] += cr.cpu_shares
            freed[1] += cr.memory_mb
            freed[2] += cr.disk_mb
            freed[3] += sum(nw.mbits for nw in cr.networks)
        self._logistic[i] = pscore
        self._freed[i] = freed
        return memo(victims, (binpack + pscore) / 2.0, pscore, freed)

    # -- batched columnar victim selection (the ISSUE 10 tentpole) -----
    def _record(self, i: int, victims: Optional[List[Allocation]],
                score: float) -> None:
        self._known[i] = True
        if victims:
            self._scores[i] = score
            self._victims[i] = victims
        else:
            self._scores[i] = -1.0
            self._logistic[i] = 0.0
            self._freed[i] = 0.0
            self._victims.pop(i, None)

    def _cache_lookup(self, i: int) -> bool:
        """The cross-eval victim-memo fast path, hoisted out of
        _evaluate_node so the batched selector only gathers columns
        for true misses."""
        if not self._cacheable(i):
            return False
        row = self.table.live_allocs[i]
        hit = self.table.preempt_cache.get((id(row), self._cache_sig))
        if hit is None or hit[0] is not row:
            return False
        PREEMPT_STATS["cache_hits"] += 1
        _row, victims, score, logistic, freed = hit
        self._logistic[i] = logistic
        self._freed[i] = freed
        self._mp_groups[i] = frozenset()
        self._record(i, list(victims) if victims is not None else None,
                     score)
        return True

    def _memoize(self, i: int, victims: Optional[List[Allocation]],
                 score: float, logistic: float, freed,
                 cacheable: bool, has_mp: bool) -> None:
        """Cross-eval memo install, same contract as _evaluate_node's
        memo closure: only nodes nothing eval-specific touches, and
        only when no candidate carries max_parallel."""
        if not cacheable or has_mp:
            return
        cache = self.table.preempt_cache
        if len(cache) > CACHE_MAX:
            cache.clear()
            PREEMPT_STATS["cache_clears"] += 1
        row = self.table.live_allocs[i]
        cache[(id(row), self._cache_sig)] = (
            row, list(victims) if victims is not None else None,
            score, logistic,
            freed if freed is not None else np.zeros(4, np.float64))

    def _evaluate_pending(self, pending, used,
                          current: List[Allocation]) -> None:
        """Resolve every pending node's (victims, score) entry: memo
        hits first, then ONE batched columnar pass over the misses
        (per-node reference Preemptor when the round carries device/
        port asks, the kill switch is set, or a node's candidate set
        overflows the matrix cap)."""
        t0 = time.perf_counter()
        stopped_ids = {a.id for allocs in self.plan.node_update.values()
                       for a in allocs}
        stopped_ids |= {a.id for a in current}
        misses: List[int] = []
        for i in pending:
            i = int(i)
            if not self._cache_lookup(i):
                misses.append(i)
        PREEMPT_STATS["cache_misses"] += len(misses)
        if misses:
            if self._columnar:
                overflow = self._evaluate_columnar(misses, used, current,
                                                   stopped_ids)
            else:
                overflow = misses
            PREEMPT_STATS["fallback_nodes"] += len(overflow)
            for i in overflow:
                victims, score = self._evaluate_node(
                    i, used[i], current, stopped_ids)
                self._record(i, victims, score)
        n_scanned = len(pending)
        PREEMPT_STATS["nodes_scanned"] += n_scanned
        dt = time.perf_counter() - t0
        PREEMPT_STATS["select_s"] += dt
        if stages.enabled:
            n_victims = 0
            for i in pending:
                v = self._victims.get(int(i))
                if v:
                    n_victims += len(v)
            stages.add("preempt", dt, {"nodes_scanned": n_scanned,
                                       "victims": n_victims})

    def _evaluate_columnar(self, idxs: List[int], used,
                           current: List[Allocation],
                           stopped_ids: set) -> List[int]:
        """Victim selection for all of `idxs` at once: one
        struct-of-arrays gather over the nodes' candidate allocs (per-
        alloc facts through state/alloc_index's memoized extractors),
        then the whole reference pipeline — PRIORITY_DELTA filter,
        greedy closest-distance selection (all nodes step in lockstep:
        each round is one [nodes, candidates] distance matrix + argmin
        instead of a Python loop per node), the superset drop via
        stable two-key argsort + prefix cumulative sums, and the
        binpack + logistic scoring — as vectorized float64 numpy whose
        op order mirrors the Preemptor exactly (the 1k-seed parity
        suite pins bit-identical victims and scores). Returns the node
        indexes whose candidate sets overflow ROWS_MAX — those take
        the per-node reference path."""
        from ..state.alloc_index import alloc_max_parallel, alloc_usage_vec

        t = self.table
        plan = self.plan
        snap = self.snapshot
        ns, jid = self.job.namespace, self.job.id
        jp = self.job.priority

        # current preemption counts per group — static for this pass
        # (set_preemptions is called once per reference evaluation too)
        cur_counts: Dict[Tuple, int] = {}
        for a in current:
            k = (a.namespace, a.job_id, a.task_group)
            cur_counts[k] = cur_counts.get(k, 0) + 1

        P = len(idxs)
        all_usage = np.zeros((P, 3), np.float64)
        cand_allocs: List[List[Allocation]] = [[] for _ in range(P)]
        cand_cols: List[List[Tuple]] = [[] for _ in range(P)]
        cacheable = [False] * P
        has_mp = [False] * P
        overflow: List[int] = []
        over_p = [False] * P
        ids = t.ids
        alloc_of = plan.node_allocation
        for p, i in enumerate(idxs):
            node_id = ids[i]
            proposed = [a for a in snap.allocs_by_node(node_id)
                        if not a.terminal_status()
                        and a.id not in stopped_ids]
            proposed.extend(alloc_of.get(node_id, []))
            mp_groups = set()
            al = cand_allocs[p]
            cl = cand_cols[p]
            cpu_sum = mem_sum = disk_sum = 0.0
            for a in proposed:
                u = alloc_usage_vec(a)
                cpu_sum += u[0]
                mem_sum += u[1]
                disk_sum += u[2]
                # the placing job's own allocs count against capacity
                # but are never candidates (set_candidates' contract)
                if a.job_id == jid and a.namespace == ns:
                    continue
                mp = alloc_max_parallel(a)
                if mp > 0:
                    mp_groups.add((a.namespace, a.job_id, a.task_group))
                job = a.job
                if job is None or jp - job.priority < PRIORITY_DELTA:
                    continue
                al.append(a)
                cl.append((u[0], u[1], u[2], u[3], float(job.priority),
                           float(mp),
                           float(cur_counts.get(
                               (a.namespace, a.job_id, a.task_group), 0))))
            all_usage[p, 0] = cpu_sum
            all_usage[p, 1] = mem_sum
            all_usage[p, 2] = disk_sum
            self._mp_groups[i] = frozenset(mp_groups)
            cacheable[p] = self._cacheable(i)
            has_mp[p] = bool(mp_groups)
            if len(al) > ROWS_MAX:
                overflow.append(i)
                over_p[p] = True
        PREEMPT_STATS["candidate_rows"] += sum(len(c) for c in cand_cols)
        PREEMPT_STATS["columnar_nodes"] += P - len(overflow)

        idx_arr = np.asarray(idxs, np.int64)
        # same dtype walk as the reference res_fits check (float32 row
        # + float32 ask against float32 capacity + 1e-6)
        res_fits = np.all(used[idx_arr][:, :3]
                          + np.asarray(self.ask_vec[:3])
                          <= t.capacity[idx_arr][:, :3] + 1e-6, axis=1)
        ask3 = np.asarray(self.ask_vec[:3], np.float64)
        # capacity holds res - reserved exactly (int math at table
        # build; float32 is exact below 2^24, true for MHz/MB scales)
        cap3 = t.capacity[idx_arr][:, :3].astype(np.float64)
        remaining0 = cap3 - all_usage

        rows: List[int] = []           # p-indexes entering the matrix
        for p, i in enumerate(idxs):
            if over_p[p]:
                continue
            if res_fits[p] or not cand_cols[p]:
                # fits on cpu/mem/disk (victims would be []), or no
                # eligible candidates: the reference returns
                # memo(None, 0.0) either way
                self._memoize(i, None, 0.0, 0.0, None,
                              cacheable[p], has_mp[p])
                self._record(i, None, 0.0)
            else:
                rows.append(p)
        if not rows:
            return overflow

        rows_arr = np.asarray(rows, np.int64)
        counts = np.asarray([len(cand_cols[p]) for p in rows], np.int64)
        C = int(counts.max())
        M = len(rows)
        flat = [v for p in rows for v in cand_cols[p]]
        fa = np.asarray(flat, np.float64)               # [total, 7]
        m_idx = np.repeat(np.arange(M), counts)
        offs = np.concatenate(([0], np.cumsum(counts)[:-1]))
        c_idx = np.arange(len(flat)) - np.repeat(offs, counts)

        # ONE dense scatter for every column; the per-dim matrices are
        # views (slicing numpy per dim would triple the call overhead
        # the matrix exists to amortize)
        dense7 = np.zeros((M, C, 7), np.float64)
        dense7[m_idx, c_idx] = fa
        validM = np.zeros((M, C), bool)
        validM[m_idx, c_idx] = True
        c3 = dense7[:, :, 0:3]          # cpu, mem, disk
        c4 = dense7[:, :, 0:4]          # + mbits (the freed vector)
        cprio = dense7[:, :, 4].copy()
        cprio[~validM] = np.inf
        cmp_ = dense7[:, :, 5]
        cnp = dense7[:, :, 6]
        # scoreForTaskGroup's crowding penalty is static per pass (the
        # reference reads set_preemptions' counts, never its own picks)
        penalty = np.where((cmp_ > 0) & (cnp >= cmp_),
                          (cnp + 1.0 - cmp_) * MAX_PARALLEL_PENALTY, 0.0)

        # -- greedy closest-distance selection, all nodes in lockstep --
        needed = np.tile(ask3, (M, 1))
        avail = remaining0[rows_arr].copy()
        selected = np.zeros((M, C), bool)
        order = np.full((M, C), C + 1, np.int64)
        okM = np.zeros(M, bool)
        alive = np.arange(M)
        step = 0
        while alive.size:
            sub_valid = validM[alive] & ~selected[alive]
            has = sub_valid.any(axis=1)
            if not has.all():
                alive = alive[has]      # exhausted, ask unmet: no fit
                if not alive.size:
                    break
                sub_valid = validM[alive] & ~selected[alive]
            # band = the lowest priority still unselected; the
            # reference consumes each ascending group to exhaustion
            prio_m = np.where(sub_valid, cprio[alive], np.inf)
            band = prio_m.min(axis=1)
            in_band = prio_m == band[:, None]
            # basic_resource_distance with ask = the running `needed`
            # (sum order mirrors the scalar: mem² + cpu², then disk²)
            nd3 = needed[alive][:, None, :]             # [k, 1, 3]
            pos = nd3 > 0.0
            t3 = np.where(pos, (nd3 - c3[alive]) / np.where(pos, nd3, 1.0),
                          0.0)
            t3 = t3 * t3
            dist = np.sqrt(t3[:, :, 1] + t3[:, :, 0] + t3[:, :, 2]) \
                + penalty[alive]
            dist = np.where(in_band, dist, np.inf)
            # argmin keeps the first minimum — the scalar loop's strict
            # `dist < best_dist` tie-break over proposed order
            pick = dist.argmin(axis=1)
            selected[alive, pick] = True
            order[alive, pick] = step
            pv3 = c3[alive, pick]                       # [k, 3]
            avail[alive] += pv3
            needed[alive] -= pv3
            met = (avail[alive] >= ask3).all(axis=1)
            okM[alive[met]] = True
            alive = alive[~met]
            step += 1

        # -- superset drop + scoring for the feasible nodes ------------
        F = np.nonzero(okM)[0]
        fail = np.nonzero(~okM)[0]
        for m in fail:
            p = rows[int(m)]
            i = idxs[p]
            self._memoize(i, None, 0.0, 0.0, None, cacheable[p],
                          has_mp[p])
            self._record(i, None, 0.0)
        if not F.size:
            return overflow

        # filterSuperset sorts by distance-to-ask DESC, stable over the
        # selection order (Python's stable sorted + reverse=True):
        # stable-argsort by selection order first, then stable-argsort
        # the gathered negated distances
        cF = c3[F]
        posF = cF > 0.0
        f3 = np.where(posF, (cF - ask3) / np.where(posF, cF, 1.0), 0.0)
        f3 = f3 * f3
        dfull = np.sqrt(f3[:, :, 1] + f3[:, :, 0] + f3[:, :, 2])
        selF = selected[F]
        ordF = np.where(selF, order[F], np.iinfo(np.int64).max)
        k1 = np.argsort(ordF, axis=1, kind="stable")
        negd1 = np.take_along_axis(np.where(selF, -dfull, np.inf), k1,
                                   axis=1)
        k2 = np.argsort(negd1, axis=1, kind="stable")
        perm = np.take_along_axis(k1, k2, axis=1)
        sel_s = np.take_along_axis(selF, perm, axis=1)

        # prefix cumulative sums ARE the reference's sequential
        # available.add walk (int-valued floats: exact either way)
        sorted4 = np.where(sel_s[:, :, None],
                           np.take_along_axis(c4[F], perm[:, :, None],
                                              axis=1), 0.0)
        cum4 = np.cumsum(sorted4, axis=1)
        availF = remaining0[rows_arr][F]
        met_pref = ((availF[:, None, :] + cum4[:, :, 0:3]
                     >= ask3).all(axis=2) & sel_s)
        nvict = selF.sum(axis=1)
        any_met = met_pref.any(axis=1)
        keep = np.where(any_met, met_pref.argmax(axis=1) + 1, nvict)

        fr = np.arange(F.size)
        freed4 = cum4[fr, keep - 1]

        # ScoreFitBinPack over the post-eviction utilization + the ask
        all3 = all_usage[rows_arr][F]
        capF = cap3[rows_arr][F]
        util_cpu = all3[:, 0] - freed4[:, 0] + ask3[0]
        util_mem = all3[:, 1] - freed4[:, 1] + ask3[1]
        node_cpu = capF[:, 0]
        node_mem = capF[:, 1]
        free_cpu = np.where(node_cpu != 0.0,
                            1.0 - util_cpu / np.where(node_cpu != 0.0,
                                                      node_cpu, 1.0), 0.0)
        free_mem = np.where(node_mem != 0.0,
                            1.0 - util_mem / np.where(node_mem != 0.0,
                                                      node_mem, 1.0), 0.0)
        total = np.power(10.0, free_cpu) + np.power(10.0, free_mem)
        binpack = np.minimum(18.0, np.maximum(0.0, 20.0 - total)) / 18.0

        # netPriority + the logistic preemption score over the KEPT set
        pr_s = np.where(sel_s,
                        np.take_along_axis(cprio[F], perm, axis=1), 0.0)
        kept = (np.arange(C)[None, :] < keep[:, None]) & sel_s
        mx = np.max(np.where(kept, pr_s, 0.0), axis=1)
        tot = np.sum(np.where(kept, pr_s, 0.0), axis=1)
        netp = np.where(mx != 0.0,
                        mx + tot / np.where(mx != 0.0, mx, 1.0), 0.0)
        pscore = 1.0 / (1.0 + np.exp(0.0048 * (netp - 2048.0)))
        score = (binpack + pscore) / 2.0

        perm_l = perm.tolist()
        keep_l = keep.tolist()
        for f, m in enumerate(F.tolist()):
            p = rows[m]
            i = idxs[p]
            al = cand_allocs[p]
            victims = [al[c] for c in perm_l[f][:keep_l[f]]]
            lg = float(pscore[f])
            fr4 = freed4[f]
            self._logistic[i] = lg
            self._freed[i] = fr4
            self._memoize(i, victims, float(score[f]), lg, fr4,
                          cacheable[p], has_mp[p])
            self._record(i, victims, float(score[f]))
        return overflow

    # -- entry ---------------------------------------------------------
    def find_placement(self, used) -> Optional[Tuple[int, List[Allocation],
                                                     float]]:
        """Best (node_idx, victims, score) for one failed instance, or
        None. `used` is the current proposed usage [N, D]."""
        current = self._preempted_now()
        self._invalidate_dirty(current)

        fits = np.all(used + np.asarray(self.ask_vec)[None, :]
                      <= self.table.capacity + 1e-6, axis=1)
        candidates = self.mask & ~fits
        pending = np.nonzero(candidates & ~self._known)[0]
        if len(pending):
            self._evaluate_pending(pending, used, current)
        masked = np.where(candidates & self._known, self._scores, -1.0)
        best_i = int(np.argmax(masked))
        if masked[best_i] < 0:
            return None
        return best_i, self._victims[best_i], float(masked[best_i])

    def columns(self, used, extra_candidates=None
                ) -> Tuple["np.ndarray", "np.ndarray"]:
        """Kernel competition columns (rank.go:415-448): for every
        masked node that doesn't fit but CAN fit after evictions,
        (logistic preemption score, freed resources). `used` rows for
        those nodes should be reduced by `freed` before the kernel so
        fit and binpack reflect the post-eviction node."""
        current = self._preempted_now()
        self._invalidate_dirty(current)
        fits = np.all(used + np.asarray(self.ask_vec)[None, :]
                      <= self.table.capacity + 1e-6, axis=1)
        candidates = self.mask & ~fits
        if extra_candidates is not None:
            # nodes failing only on devices/reserved ports (the
            # PreemptForDevice / PreemptForNetwork variants)
            candidates |= self.mask & extra_candidates
        pending = np.nonzero(candidates & ~self._known)[0]
        if len(pending):
            self._evaluate_pending(pending, used, current)
        ok = candidates & self._known & (self._scores >= 0)
        d = used.shape[1]
        pre_score = np.where(ok, self._logistic, 0.0).astype(np.float32)
        freed = np.where(ok[:, None], self._freed[:, :d],
                         0.0).astype(np.float32)
        return pre_score, freed

    def victims_for(self, idx: int):
        return self._victims.get(idx)


def find_preemption_placement(snapshot, table, mask, used, ask_vec, job,
                              plan) -> Optional[Tuple[int, List[Allocation], float]]:
    """One-shot wrapper over PreemptionRound (kept for callers that
    only need a single placement)."""
    return PreemptionRound(snapshot, table, mask, ask_vec, job,
                           plan).find_placement(used)
