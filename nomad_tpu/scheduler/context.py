"""Per-evaluation context: the plan under construction plus eligibility
bookkeeping carried into blocked evals.

Reference semantics: scheduler/context.go (EvalContext:76,
EvalEligibility:190). ProposedAllocs overlays live in
ops/tables.ProposedIndex.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..models import Evaluation, Job, Plan


class EvalEligibility:
    """Tracks class eligibility for blocked evals (context.go:190-356).
    With full-matrix feasibility we don't memoize per class at eval time
    (the masks are vectorized), but the blocked-evals subsystem still
    needs per-class eligibility and the escaped flag."""

    def __init__(self):
        self.job_escaped = False
        self.tg_escaped: Dict[str, bool] = {}
        self.class_eligibility: Dict[str, bool] = {}

    def has_escaped(self) -> bool:
        return self.job_escaped or any(self.tg_escaped.values())

    def set_job(self, job: Job) -> None:
        self.job_escaped = _constraints_escaped(job.constraints)
        for tg in job.task_groups:
            esc = _constraints_escaped(tg.constraints)
            for t in tg.tasks:
                esc = esc or _constraints_escaped(t.constraints)
            self.tg_escaped[tg.name] = esc

    def set_class_eligibility(self, computed_class: str, eligible: bool) -> None:
        self.class_eligibility[computed_class] = eligible


def _constraints_escaped(constraints) -> bool:
    """A constraint "escapes" class memoization when it references
    node-unique properties (structs.go EscapedConstraints)."""
    for c in constraints:
        for target in (c.ltarget, c.rtarget):
            if "${node.unique." in target or "${unique." in target:
                return True
    return False


class EvalContext:
    def __init__(self, snapshot, evaluation: Evaluation,
                 plan: Optional[Plan] = None):
        self.snapshot = snapshot
        self.eval = evaluation
        self.plan = plan or Plan(eval_id=evaluation.id)
        self.eligibility = EvalEligibility()
