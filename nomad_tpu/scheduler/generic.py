"""GenericScheduler: service and batch evaluation processing.

Reference semantics: scheduler/generic_sched.go — Process:125 (retry
loop, 5 service / 2 batch attempts), process:216, computeJobAllocs:332,
computePlacements:468, blocked-eval creation:193.

The placement inner loop differs by design: instead of one stack.Select
per missing alloc, placements are grouped per task group and dispatched
to the batched device kernel (PlacementEngine.select_batch) — the
north-star rewrite (SURVEY.md preamble).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from ..models import (
    AllocatedResources, AllocatedSharedResources, Allocation, AllocMetric,
    Evaluation, Job, Plan,
    ALLOC_CLIENT_FAILED, ALLOC_CLIENT_PENDING, ALLOC_DESIRED_RUN,
    EVAL_STATUS_COMPLETE, EVAL_STATUS_FAILED,
    TRIGGER_MAX_PLANS,
)
from ..models.alloc import RescheduleEvent, RescheduleTracker, AllocDeploymentStatus
from ..ops import ProposedIndex
from ..utils import stages
from ..utils.ids import generate_uuid
from .context import EvalContext
from .reconcile import AllocReconciler
from .reconcile_columnar import ColumnarAllocReconciler, columnar_enabled
from .stack import PlacementEngine, SelectOptions, tasks_updated_cached
from .util import (adjust_queued_allocations, tainted_nodes,
                   tainted_nodes_columnar, tasks_updated,
                   update_non_terminal_allocs_to_lost,
                   update_non_terminal_allocs_to_lost_columnar)

MAX_SERVICE_ATTEMPTS = 5
MAX_BATCH_ATTEMPTS = 2

BLOCKED_EVAL_MAX_PLAN_DESC = "created due to placement conflicts"
BLOCKED_EVAL_FAILED_PLACEMENTS = "created to place remaining allocations"
ALLOC_IN_PLACE = "alloc updating in-place"


class SetStatusError(Exception):
    def __init__(self, eval_status: str, msg: str):
        super().__init__(msg)
        self.eval_status = eval_status


class GenericScheduler:
    def __init__(self, state, planner, batch: bool):
        self.state = state
        self.planner = planner
        self.batch = batch

        self.eval: Optional[Evaluation] = None
        self.job: Optional[Job] = None
        self.plan: Optional[Plan] = None
        self.plan_result = None
        self.ctx: Optional[EvalContext] = None
        self.engine: Optional[PlacementEngine] = None
        self.deployment = None

        self.blocked: Optional[Evaluation] = None
        # True while this eval reconciles columnar: gates the
        # tasks_updated memo so engine-off (env hatch OR
        # ServerConfig.reconcile_columnar=False) measures the raw
        # reference diff cost, not the memoized one
        self._columnar_active = False
        self.failed_tg_allocs: Dict[str, AllocMetric] = {}
        self.queued_allocs: Dict[str, int] = {}
        self.followup_evals: List[Evaluation] = []
        # set by the batched worker: routes kernel dispatches through
        # the multi-eval gateway (one select_many per lane barrier)
        self.kernel_dispatch = None
        # set by concurrent workers: (lane, lanes) hash-slice
        # decorrelation for big batch selects (SelectKernel.decorrelate)
        self.kernel_decorrelate = None

    # -- entry ---------------------------------------------------------
    def process(self, evaluation: Evaluation) -> None:
        self.eval = evaluation
        limit = MAX_BATCH_ATTEMPTS if self.batch else MAX_SERVICE_ATTEMPTS

        # retryMax + progressMade (scheduler/util.go:277-310): a round
        # that committed ANYTHING resets the attempt budget — under
        # optimistic concurrency a storm of plan conflicts burns rounds
        # while still converging, and only zero-progress rounds may
        # exhaust the limit
        progress = [False]
        attempts = 0
        while True:
            progress[0] = False
            try:
                done = self._process_once(progress)
            except SetStatusError as e:
                self._set_status(e.eval_status, str(e))
                return
            if done:
                self._set_status(EVAL_STATUS_COMPLETE, "")
                return
            if progress[0]:
                attempts = 0
                continue
            attempts += 1
            if attempts >= limit:
                break
        # retries exhausted on placement conflicts: block so the remaining
        # work is retried when capacity frees (generic_sched.go:150-160)
        if self.blocked is None and self.ctx is not None:
            blocked = self.eval.create_blocked_eval(
                dict(self.ctx.eligibility.class_eligibility),
                self.ctx.eligibility.has_escaped(), "")
            blocked.triggered_by = TRIGGER_MAX_PLANS
            blocked.status_description = BLOCKED_EVAL_MAX_PLAN_DESC
            self.planner.create_eval(blocked)
            self.blocked = blocked
        self._set_status(
            EVAL_STATUS_FAILED,
            f"maximum attempts reached ({limit})")

    # -- one attempt ---------------------------------------------------
    def _process_once(self, progress) -> bool:
        ev = self.eval
        snapshot = self.state
        self.job = snapshot.job_by_id(ev.namespace, ev.job_id)

        self.queued_allocs = {tg.name: 0
                              for tg in (self.job.task_groups if self.job else [])}
        self.failed_tg_allocs = {}
        self.followup_evals = []

        self.plan = ev.make_plan(self.job)
        self.blocked = None
        self.ctx = EvalContext(snapshot, ev, self.plan)
        self.engine = PlacementEngine(snapshot)
        if self.kernel_dispatch is not None:
            self.engine.dispatch = self.kernel_dispatch
        if self.kernel_decorrelate is not None:
            self.engine.kernel.decorrelate = self.kernel_decorrelate
        if self.job is not None:
            self.engine.set_job(self.job)
            self.ctx.eligibility.set_job(self.job)

        self.deployment = None
        if self.job is not None:
            self.deployment = snapshot.latest_deployment_by_job(
                ev.namespace, ev.job_id)

        # compute the changes
        self._compute_job_allocs()

        # if the plan is a no-op, we're done
        if self.plan.is_no_op() and not self.followup_evals \
                and not self.failed_tg_allocs:
            return True

        # create follow-up evals for delayed reschedules
        for fev in self.followup_evals:
            self.planner.create_eval(fev)

        # if there were failures, create/adjust a blocked eval
        if self.failed_tg_allocs and self.blocked is None:
            self.blocked = self.eval.create_blocked_eval(
                dict(self.ctx.eligibility.class_eligibility),
                self.ctx.eligibility.has_escaped(), "")
            self.blocked.status_description = BLOCKED_EVAL_FAILED_PLACEMENTS
            self.planner.create_eval(self.blocked)

        if self.plan.is_no_op():
            return True

        # submit the plan
        result = self.planner.submit_plan(self.plan)
        self.plan_result = result
        adjust_queued_allocations(result, self.queued_allocs)

        if result is None:
            return True
        full, expected, actual = result.full_commit(self.plan)
        if not full:
            # partial commit: refresh state and retry
            if result.refresh_index:
                self.state = self.planner.refreshed_state(
                    result.refresh_index) if hasattr(
                        self.planner, "refreshed_state") else self.state
            progress[0] = actual > 0
            return False
        return True

    # -- reconcile + place --------------------------------------------
    def _compute_job_allocs(self) -> None:
        ev = self.eval
        t0 = time.perf_counter() if stages.enabled else 0.0

        # columnar reconcile engine: the state store's per-job alloc
        # index turns the O(allocs) host phase into mask ops
        # (reconcile_columnar.py); NOMAD_TPU_COLUMNAR_RECONCILE=0 or a
        # detached snapshot falls back to the reference reconciler
        cols = None
        if columnar_enabled():
            getter = getattr(self.state, "job_alloc_columns", None)
            if getter is not None:
                cols = getter(ev.namespace, ev.job_id)
        self._columnar_active = cols is not None

        if cols is not None:
            tainted = tainted_nodes_columnar(self.state, cols)
            update_non_terminal_allocs_to_lost_columnar(
                self.plan, tainted, cols)
        else:
            allocs = self.state.allocs_by_job(ev.namespace, ev.job_id)
            tainted = tainted_nodes(self.state, allocs)
            update_non_terminal_allocs_to_lost(self.plan, tainted,
                                               allocs)

        job = self.job
        if job is None or job.stopped():
            job = job if job is not None else Job(
                id=ev.job_id, namespace=ev.namespace, stop=True,
                task_groups=[])
        if cols is not None:
            reconciler = ColumnarAllocReconciler(
                self._alloc_update_fn, self.batch, ev.job_id, job,
                self.deployment, cols, tainted, ev.id,
                spec_change_fn=self._spec_change_fn)
        else:
            reconciler = AllocReconciler(
                self._alloc_update_fn, self.batch, ev.job_id, job,
                self.deployment, allocs, tainted, ev.id)
        results = reconciler.compute()

        if self.eval.annotate_plan:
            from ..models.plan import PlanAnnotations
            self.plan.annotations = PlanAnnotations(
                desired_tg_updates=results.desired_tg_updates)

        # Add the deployment changes to the plan
        self.plan.deployment = results.deployment
        self.plan.deployment_updates = results.deployment_updates

        # Followup evals (delayed reschedules)
        for evals in results.desired_followup_evals.values():
            self.followup_evals.extend(evals)

        # Update the stored deployment
        if results.deployment is not None:
            self.deployment = results.deployment

        # Handle stops
        for stop in results.stop:
            self.plan.append_stopped_alloc(
                stop.alloc, stop.status_description, stop.client_status,
                stop.followup_eval_id)

        # Handle attribute updates (followup eval ids on allocs)
        for alloc in results.attribute_updates.values():
            self.plan.append_alloc(alloc)

        # Handle in-place updates
        for alloc in results.inplace_update:
            self.plan.append_alloc(alloc)

        # Queued allocations = requested placements per tg, derived
        # from the reconciler's per-tg counts in ONE pass: fresh places
        # + canaries + migrations land in results.place, destructive
        # updates in results.destructive_update, and the old code
        # re-walked both 10k-entry lists after the reconciler had
        # already bucketed them
        for tg_name, du in results.desired_tg_updates.items():
            n = du.place + du.canary + du.migrate + du.destructive_update
            if n:
                self.queued_allocs[tg_name] = \
                    self.queued_allocs.get(tg_name, 0) + n

        if stages.enabled:
            # attrs ride onto the flight recorder's reconcile span (a
            # slow reconcile means something different on the columnar
            # engine vs the reference fallback)
            stages.add("reconcile", time.perf_counter() - t0,
                       attrs={"columnar": self._columnar_active})

        # Compute placements (destructive first to discount resources)
        self._compute_placements(results.destructive_update, results.place)

    def _spec_change_fn(self, old_job: Job, tg_name: str) -> bool:
        """Destructive-update verdict for the columnar reconciler: one
        memoized deep diff per (old version, new version, tg)."""
        return tasks_updated_cached(self.job, old_job, tg_name)

    # genericAllocUpdateFn (util.go:926)
    def _alloc_update_fn(self, existing: Allocation, new_job: Job, new_tg):
        if existing.job is not None and \
                existing.job.job_modify_index == new_job.job_modify_index:
            return True, False, None
        if existing.job is None:
            return False, True, None
        # memoized with the engine on (one diff per version pair);
        # engine-off — env hatch or reconcile_columnar=False — keeps
        # the raw diff so comparisons measure the true reference cost
        updated = (tasks_updated_cached(new_job, existing.job,
                                        new_tg.name)
                   if self._columnar_active
                   else tasks_updated(new_job, existing.job,
                                      new_tg.name))
        if updated:
            return False, True, None
        if existing.terminal_status():
            return True, False, None
        node = self.state.node_by_id(existing.node_id)
        if node is None:
            return False, True, None

        # Host-side single-node feasibility + fit check: the in-place path
        # touches exactly one node, so a device dispatch per candidate
        # alloc would be pure overhead (genericAllocUpdateFn util.go:926
        # runs the stack on a one-node set for the same reason).
        if not self._node_feasible_for(node, new_tg):
            return False, True, None
        ask = PlacementEngine.group_ask(new_tg)
        cap = node.comparable_resources()
        cap.subtract(node.comparable_reserved_resources())
        used = [0.0, 0.0, 0.0]
        stopped = {a.id for allocs in self.plan.node_update.values()
                   for a in allocs} | {existing.id}
        for a in self.state.allocs_by_node(node.id):
            if a.terminal_status() or a.id in stopped:
                continue
            c = a.comparable_resources()
            if c is not None:
                used[0] += c.cpu_shares
                used[1] += c.memory_mb
                used[2] += c.disk_mb
        for a in self.plan.node_allocation.get(node.id, []):
            c = a.comparable_resources()
            if c is not None:
                used[0] += c.cpu_shares
                used[1] += c.memory_mb
                used[2] += c.disk_mb
        if (used[0] + ask[0] > cap.cpu_shares
                or used[1] + ask[1] > cap.memory_mb
                or used[2] + ask[2] > cap.disk_mb):
            return False, True, None

        # build task resources, restoring network/device offers from the
        # existing allocation (in-place updates keep their ports)
        from ..models.resources import (AllocatedCpuResources,
                                        AllocatedMemoryResources,
                                        AllocatedTaskResources)
        task_resources = {}
        for task in new_tg.tasks:
            tr = AllocatedTaskResources(
                cpu=AllocatedCpuResources(task.resources.cpu),
                memory=AllocatedMemoryResources(task.resources.memory_mb))
            if existing.allocated_resources is not None:
                old = existing.allocated_resources.tasks.get(task.name)
                if old is not None:
                    tr.networks = old.networks
                    tr.devices = old.devices
            task_resources[task.name] = tr
        option = type("_Opt", (), {})()
        option.task_resources = task_resources

        new_alloc = existing.copy_skip_job()
        new_alloc.eval_id = self.eval.id
        new_alloc.job = None
        new_alloc.allocated_resources = AllocatedResources(
            tasks=option.task_resources,
            shared=AllocatedSharedResources(
                disk_mb=new_tg.ephemeral_disk.size_mb
                if new_tg.ephemeral_disk else 0,
                networks=(existing.allocated_resources.shared.networks
                          if existing.allocated_resources else []),
            ))
        new_alloc.metrics = existing.metrics.copy() if existing.metrics \
            else AllocMetric()
        return False, False, new_alloc

    def _node_feasible_for(self, node, tg) -> bool:
        """Static feasibility of one node for a task group (host-side,
        no device dispatch)."""
        from ..ops.tables import NodeTable
        t = NodeTable([node])
        engine = PlacementEngine.__new__(PlacementEngine)
        engine.snapshot = self.state
        engine.config = self.state.scheduler_config()
        engine.job = self.job
        engine.table = t
        engine.by_dc = {node.datacenter: 1}
        engine._base_mask = t.ready.copy()
        engine._mask_cache = {}
        engine._dc_key = None       # private table: no cross-eval cache
        engine._net_cache = {}
        engine._dev_cache = {}
        engine._feas_tokens = {}
        engine._feas_push_s = 0.0
        mask, _counts = engine.feasibility(tg)
        return bool(mask[0])

    # computePlacements (generic_sched.go:468), batched per task group
    def _compute_placements(self, destructive: List, place: List) -> None:
        if self.job is None:
            return
        n = self.engine.set_nodes(self.job.datacenters)
        self._preemption_rounds = {}   # tg name -> PreemptionRound

        deployment_id = ""
        if self.deployment is not None and self.deployment.active():
            deployment_id = self.deployment.id

        now = time.time()

        empty_options = SelectOptions()
        for results in (destructive, place):
            # group placements by (tg, penalty/preferred signature)
            groups: Dict[Tuple, List] = {}
            order: List[Tuple] = []
            for missing in results:
                tg = missing.task_group if not hasattr(missing, "place_task_group") \
                    else missing.place_task_group
                if tg is None:
                    continue
                if missing.previous_alloc is None:
                    # fresh placement: no penalty/preferred signature —
                    # skip per-instance option construction (a 10k-count
                    # job walks this loop 10k times)
                    options = empty_options
                    sig = (tg.name, None, None)
                else:
                    options = self._get_select_options(missing)
                    sig = (tg.name, options.penalty_node_ids,
                           tuple(nd.id for nd in options.preferred_nodes))
                if sig not in groups:
                    groups[sig] = []
                    order.append(sig)
                groups[sig].append((missing, options))

            for sig in order:
                batch = groups[sig]
                tg_name = sig[0]
                tg = self.job.lookup_task_group(tg_name)
                if tg is None:
                    continue
                if tg.name in self.failed_tg_allocs:
                    self.failed_tg_allocs[tg.name].coalesced_failures += len(batch)
                    continue

                # fresh batches (sig carries no penalty/preferred data ⟺
                # every item has previous_alloc None) have no stops to
                # stage and take the bulk append below
                fresh = sig[1] is None and sig[2] is None

                # stage stops for destructive updates first (frees resources)
                if not fresh:
                    for missing, _opts in batch:
                        stop_prev, stop_desc = missing.stop_previous()
                        if stop_prev and missing.previous_alloc is not None:
                            self.plan.append_stopped_alloc(
                                missing.previous_alloc, stop_desc, "", "")

                proposed = ProposedIndex(
                    self.engine.table, self.job,
                    self.state.allocs_by_job(self.job.namespace, self.job.id),
                    self.plan)
                options_list = self.engine.select_batch(
                    tg, len(batch), proposed, batch[0][1],
                    preemption_round=self._preemption_round_for(tg))

                if fresh and not batch[0][1].preferred_nodes:
                    # bulk-append the successful fresh placements in one
                    # tight loop (a 10k-count batch spent ~0.3 s in the
                    # general per-item body below — round-5 profile);
                    # leftovers (no fit, preemption winners, canaries)
                    # fall through to the general loop
                    leftover = self._append_fresh_bulk(
                        batch, options_list, tg, deployment_id)
                    if not leftover:
                        continue
                    pairs = leftover
                else:
                    pairs = list(zip(batch, options_list))

                for (missing, _opts), (option, metrics) in pairs:
                    # preferred-node miss falls back to the full node set
                    if option is None and batch[0][1].preferred_nodes:
                        fallback = self.engine.select_batch(
                            tg, 1, ProposedIndex(
                                self.engine.table, self.job,
                                self.state.allocs_by_job(
                                    self.job.namespace, self.job.id),
                                self.plan),
                            SelectOptions(
                                penalty_node_ids=batch[0][1].penalty_node_ids))
                        option, metrics = fallback[0] if fallback else (None, metrics)
                    # no fit anywhere: try preemption before failing
                    # (BinPackIterator evict path, rank.go:415-448)
                    if option is None:
                        option = self._try_preemption(tg, metrics)
                    if option is not None:
                        self._append_placement(missing, tg, option,
                                               deployment_id, now)
                        continue
                    if tg.name in self.failed_tg_allocs:
                        # coalesce later failures of the same group
                        self.failed_tg_allocs[tg.name].coalesced_failures += 1
                    else:
                        # private copy: `metrics` may be the batch's
                        # shared flyweight, and coalesced_failures
                        # mutates on later failures
                        self.failed_tg_allocs[tg.name] = metrics.copy()
                    # back out the staged stop: a failed placement must not
                    # leave its previous alloc stopping with no replacement
                    stop_prev, _ = missing.stop_previous()
                    if stop_prev and missing.previous_alloc is not None:
                        self.plan.remove_update(missing.previous_alloc)

        # record class eligibility for the blocked eval — only over nodes
        # in the iteration set (ready & in-DC): a down node's class must
        # stay UNKNOWN so BlockedEvals wakes the eval when it recovers
        # (the resident table holds all nodes; feasible.go's iterator
        # never saw non-ready ones)
        if self.failed_tg_allocs and self.engine.table is not None:
            base = self.engine._base_mask
            for tg_name in self.failed_tg_allocs:
                tg = self.job.lookup_task_group(tg_name)
                if tg is None:
                    continue
                mask, _counts = self.engine.feasibility(tg)
                for i, node in enumerate(self.engine.table.nodes):
                    if node.computed_class and bool(base[i]):
                        prev = self.ctx.eligibility.class_eligibility.get(
                            node.computed_class, False)
                        self.ctx.eligibility.set_class_eligibility(
                            node.computed_class, prev or bool(mask[i]))

    def _preemption_round_for(self, tg):
        """Per-(eval, task group) PreemptionRound when preemption is
        enabled for this scheduler type; None otherwise."""
        from .preemption import PreemptionRound, preemption_enabled
        if not preemption_enabled(self.state.scheduler_config(),
                                  "batch" if self.batch else "service"):
            return None
        round_ = self._preemption_rounds.get(tg.name)
        if round_ is None or round_.plan is not self.plan:
            mask, _counts = self.engine.feasibility(tg)
            round_ = PreemptionRound(
                self.state, self.engine.table, mask,
                self.engine.group_ask(tg), self.job, self.plan, tg=tg)
            self._preemption_rounds[tg.name] = round_
        return round_

    def _try_preemption(self, tg, metrics):
        """When the kernel finds no fit, look for a node where evicting
        lower-priority allocs (priority delta >= 10) makes room. The
        PreemptionRound is cached per task group for the whole eval so
        repeated failures share per-node victim computations."""
        from ..ops.tables import ProposedIndex as PI
        from .stack import RankedNode
        round_ = self._preemption_round_for(tg)
        if round_ is None:
            return None
        proposed = PI(self.engine.table, self.job,
                      self.state.allocs_by_job(self.job.namespace, self.job.id),
                      self.plan)
        found = round_.find_placement(proposed.used())
        if found is None:
            return None
        idx, victims, score = found
        node = self.engine.table.nodes[idx]
        # victims free their ports too: rebuild this node's net index
        # after staging the preemptions
        for v in victims:
            self.plan.append_preempted_alloc(v, "")
        self.engine._net_cache.pop(node.id, None)
        task_resources, shared, ok = self.engine._assign_resources(
            node, tg, self.plan)
        if not ok:
            for v in victims:
                lst = self.plan.node_preemptions.get(v.node_id, [])
                self.plan.node_preemptions[v.node_id] = \
                    [a for a in lst if a.id != v.id]
            return None
        return RankedNode(node=node, final_score=score,
                          task_resources=task_resources,
                          alloc_resources=shared, metrics=metrics,
                          preempted_allocs=victims)

    def _append_fresh_bulk(self, batch, options_list, tg,
                           deployment_id: str):
        """Append fresh placements (no previous alloc) to the plan via a
        prototype-copy loop: one Allocation template per batch, per-item
        work limited to id/name/node/resources. Safe because the shared
        default fields (desired_transition, task_states,
        preempted_allocations) are replaced, never mutated, downstream.
        Returns the (item, option) pairs needing the general path:
        failures, preemption winners, canaries."""
        from os import urandom

        proto = Allocation(
            namespace=self.job.namespace, eval_id=self.eval.id,
            job_id=self.job.id, task_group=tg.name,
            deployment_id=deployment_id,
            desired_status=ALLOC_DESIRED_RUN,
            client_status=ALLOC_CLIENT_PENDING)
        base = proto.__dict__
        disk_mb = tg.ephemeral_disk.size_mb if tg.ephemeral_disk else 0
        res_fly: Dict[Tuple[int, int], AllocatedResources] = {}
        node_alloc = self.plan.node_allocation
        deployment_active = (self.deployment is not None
                             and self.deployment.active())
        leftover = []
        for item, (option, metrics) in zip(batch, options_list):
            missing = item[0]
            if option is None or option.preempted_allocs or \
                    (missing.canary and deployment_active):
                leftover.append((item, (option, metrics)))
                continue
            tr = option.task_resources
            ar = option.alloc_resources
            key = (id(tr), id(ar))
            resources = res_fly.get(key)
            if resources is None:
                resources = AllocatedResources(
                    tasks=tr, shared=ar or AllocatedSharedResources(
                        disk_mb=disk_mb))
                res_fly[key] = resources
            a = object.__new__(Allocation)
            d = a.__dict__
            d.update(base)
            h = urandom(16).hex()
            d["id"] = f"{h[:8]}-{h[8:12]}-4{h[13:16]}-{h[16:20]}-{h[20:]}"
            node = option.node
            d["name"] = missing.name
            d["node_id"] = node.id
            d["node_name"] = node.name
            d["allocated_resources"] = resources
            d["metrics"] = option.metrics
            lst = node_alloc.get(node.id)
            if lst is None:
                node_alloc[node.id] = [a]
            else:
                lst.append(a)
        return leftover

    @staticmethod
    def _get_select_options(missing) -> SelectOptions:
        prev = missing.previous_alloc
        penalty = set()
        if prev is not None:
            if prev.client_status == ALLOC_CLIENT_FAILED:
                penalty.add(prev.node_id)
            if prev.reschedule_tracker is not None:
                for ev in prev.reschedule_tracker.events:
                    if ev.prev_node_id:
                        penalty.add(ev.prev_node_id)
        return SelectOptions(penalty_node_ids=frozenset(penalty))

    def _append_placement(self, missing, tg, option, deployment_id: str,
                          now: float) -> None:
        # flyweight-aware: winners of one batch share task_resources
        # when no ports/devices are at stake (stack.py select_batch), so
        # the wrapping AllocatedResources can be shared too — these are
        # read-only downstream (in-place updates build fresh objects)
        cached = getattr(self, "_res_fly", None)
        if cached is not None and cached[0] is option.task_resources \
                and cached[1] is option.alloc_resources:
            resources = cached[2]
        else:
            resources = AllocatedResources(
                tasks=option.task_resources,
                shared=option.alloc_resources or AllocatedSharedResources(
                    disk_mb=tg.ephemeral_disk.size_mb
                    if tg.ephemeral_disk else 0))
            self._res_fly = (option.task_resources,
                             option.alloc_resources, resources)
        alloc = Allocation(
            id=generate_uuid(),
            namespace=self.job.namespace,
            eval_id=self.eval.id,
            name=missing.name,
            job_id=self.job.id,
            task_group=tg.name,
            metrics=option.metrics,
            node_id=option.node.id,
            node_name=option.node.name,
            deployment_id=deployment_id,
            allocated_resources=resources,
            desired_status=ALLOC_DESIRED_RUN,
            client_status=ALLOC_CLIENT_PENDING,
        )
        prev = missing.previous_alloc
        if prev is not None:
            alloc.previous_allocation = prev.id
            if missing.reschedule:
                self._update_reschedule_tracker(alloc, prev, now)
        if missing.canary and self.deployment is not None:
            alloc.deployment_status = AllocDeploymentStatus(canary=True)
        if option.preempted_allocs:
            from .preemption import link_preemptions
            link_preemptions(self.plan, alloc, option.preempted_allocs)
        self.plan.append_alloc(alloc)

    @staticmethod
    def _update_reschedule_tracker(alloc: Allocation, prev: Allocation,
                                   now: float) -> None:
        events: List[RescheduleEvent] = []
        if prev.reschedule_tracker is not None:
            events.extend(prev.reschedule_tracker.events)
        events.append(RescheduleEvent(
            reschedule_time=now, prev_alloc_id=prev.id,
            prev_node_id=prev.node_id,
            delay_s=prev._next_delay(prev.reschedule_policy())
            if prev.reschedule_policy() else 0.0))
        alloc.reschedule_tracker = RescheduleTracker(events=events)

    # -- status --------------------------------------------------------
    def _set_status(self, status: str, desc: str) -> None:
        new_eval = self.eval.copy()
        new_eval.status = status
        new_eval.status_description = desc
        if self.blocked is not None:
            new_eval.blocked_eval = self.blocked.id
        if self.failed_tg_allocs:
            new_eval.failed_tg_allocs = dict(self.failed_tg_allocs)
        if self.queued_allocs is not None:
            new_eval.queued_allocations = dict(self.queued_allocs)
        if self.deployment is not None and self.deployment.active():
            new_eval.deployment_id = self.deployment.id
        self.planner.update_eval(new_eval)
