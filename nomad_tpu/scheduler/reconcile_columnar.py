"""Columnar reconcile engine: the AllocReconciler's per-alloc host
loops recast as numpy mask ops over the state store's per-job alloc
index (state/alloc_index.py JobAllocColumns).

The reference reconciler pays O(allocs) Python per eval — status
predicates, name parsing, job-version checks, and (the whale) one deep
`tasks_updated` structural diff per alloc during a deployment wave.
This subclass overrides the set-algebra hooks the base class exposes
(reconcile.py `_matrix`/`_filter_*`/`_name_index`/`_compute_updates`/
`_deployment_health`/`_had_running`) with vectorized versions:

  - partition predicates (terminal, migrate-flagged, tainted-lost,
    same-version ignore, old-terminal, per-tg bucketing) evaluate as
    boolean masks over the columns;
  - `tasks_updated` verdicts are computed ONCE per distinct
    (old job snapshot, task group) via `spec_change_fn` (the memoized
    stack.tasks_updated_cached) and broadcast over rows;
  - per-alloc Python survives only for the rows the masks flag:
    reschedule-eligibility of FAILED allocs, batch `ran_successfully`,
    in-place update candidates (node feasibility + alloc construction),
    canaries, and the deployment state machine.

Result sets stay plain AllocSet dicts (bulk-materialized at C speed),
so the intricate group math in the base class is SHARED — columnar and
reference run the same control flow over identically-shaped inputs,
which is what the randomized parity suite (tests/
test_reconcile_columnar.py) pins down.

`NOMAD_TPU_COLUMNAR_RECONCILE=0` is the runtime escape hatch: the
generic scheduler falls back to the reference reconciler (and the raw,
un-memoized `tasks_updated`).
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional

import numpy as np

from ..state.alloc_index import (CLIENT_FAILED_CODE, JobAllocColumns)
from .reconcile import AllocReconciler
from .reconcile_util import (AllocNameIndex, AllocSet,
                             DelayedRescheduleInfo,
                             update_by_reschedulable)


def columnar_enabled() -> bool:
    return os.environ.get("NOMAD_TPU_COLUMNAR_RECONCILE", "1").lower() \
        not in ("0", "false", "no")


_EMPTY_ROWS = np.zeros(0, dtype=np.intp)


class ColumnarAllocReconciler(AllocReconciler):
    def __init__(self, alloc_update_fn, batch: bool, job_id: str, job,
                 deployment, cols: JobAllocColumns, tainted_nodes,
                 eval_id: str, now: Optional[float] = None,
                 spec_change_fn: Optional[Callable] = None):
        super().__init__(alloc_update_fn, batch, job_id, job,
                         deployment, cols.allocs, tainted_nodes,
                         eval_id, now=now)
        self.cols = cols
        # spec_change_fn(old_job, tg_name) -> bool: the vectorizable
        # destructive-update verdict (generic.py wires the memoized
        # tasks_updated). None = unknown update fn: _compute_updates
        # falls back to the reference per-alloc loop.
        self.spec_change_fn = spec_change_fn
        # materialized-dict -> row-array stash so chained filters skip
        # the id->row reconversion; entries are only trusted while the
        # dict object is unmutated (length check)
        self._stash: Dict[int, tuple] = {}
        c = cols
        n = c.n
        self._terminal = (c.desired[:n] > 0) | (c.client[:n] >= 2)
        # per-node tainted categories, resolved once per eval
        n_nodes = len(c.node_ids)
        tainted_col = np.zeros(n_nodes, dtype=bool)
        lost_col = np.zeros(n_nodes, dtype=bool)
        for nid, node in tainted_nodes.items():
            code = c.node_of.get(nid)
            if code is None:
                continue
            tainted_col[code] = True
            if node is None or node.terminal_status():
                lost_col[code] = True
        self._node_tainted = tainted_col
        self._node_lost = lost_col

    # -- row/dict plumbing --------------------------------------------
    def _mat(self, rows: np.ndarray) -> AllocSet:
        ids = self.cols.ids
        allocs = self.cols.allocs
        d = {ids[r]: allocs[r] for r in rows.tolist()}
        self._stash[id(d)] = (d, rows)
        return d

    def _rows_for(self, s: AllocSet) -> np.ndarray:
        ent = self._stash.get(id(s))
        if ent is not None and ent[0] is s and len(ent[1]) == len(s):
            return ent[1]
        if not s:
            return _EMPTY_ROWS
        rof = self.cols.row_of
        return np.fromiter((rof[k] for k in s), dtype=np.intp,
                           count=len(s))

    # -- hook overrides ------------------------------------------------
    def _matrix(self) -> Dict[str, AllocSet]:
        c = self.cols
        tg_code = c.tg_code[:c.n]
        m: Dict[str, AllocSet] = {}
        for code, name in enumerate(c.tg_names):
            rows = np.nonzero(tg_code == code)[0]
            if len(rows):
                m[name] = self._mat(rows)
        if self.job is not None:
            for tg in self.job.task_groups:
                m.setdefault(tg.name, {})
        return m

    def _filter_tainted(self, s: AllocSet):
        rows = self._rows_for(s)
        if not len(rows):
            return {}, {}, {}
        c = self.cols
        term = self._terminal[rows]
        mig = ~term & c.migrate[rows]
        nc = c.node_code[rows]
        lost = (~term & ~mig & self._node_tainted[nc]
                & self._node_lost[nc])
        unt = ~mig & ~lost
        return (self._mat(rows[unt]), self._mat(rows[mig]),
                self._mat(rows[lost]))

    def _filter_terminal(self, s: AllocSet) -> AllocSet:
        rows = self._rows_for(s)
        if not len(rows):
            return {}
        return self._mat(rows[~self._terminal[rows]])

    def _filter_old_terminal_allocs(self, all_set: AllocSet):
        if not self.batch:
            return all_set, 0
        rows = self._rows_for(all_set)
        if not len(rows):
            return all_set, 0
        c = self.cols
        older = c.has_job[rows] & (
            (c.job_version[rows] < self.job.version)
            | (c.job_create[rows] < self.job.create_index))
        ign = older & self._terminal[rows]
        n = int(ign.sum())
        if not n:
            return all_set, 0
        return self._mat(rows[~ign]), n

    def _filter_rescheduleable(self, s: AllocSet):
        rows = self._rows_for(s)
        if not len(rows):
            return {}, {}, []
        c = self.cols
        term = self._terminal[rows]
        keep = ~(c.has_next[rows] & term)
        rows = rows[keep]
        if not len(rows):
            return {}, {}, []
        de = c.desired[rows]
        cl = c.client[rows]
        stop_evict = de > 0
        untainted_m = np.zeros(len(rows), dtype=bool)
        if self.batch:
            # stopped/evicted batch allocs: ran_successfully decides,
            # and it reads task_states — per-alloc, flagged rows only
            for i in np.nonzero(stop_evict)[0].tolist():
                if c.allocs[rows[i]].ran_successfully():
                    untainted_m[i] = True
            untainted_m |= ~stop_evict & (cl != CLIENT_FAILED_CODE)
            proceed = ~stop_evict & (cl == CLIENT_FAILED_CODE)
        else:
            proceed = ~(stop_evict | (cl == 2) | (cl == 4))
        # active-deployment member without a reschedule flag: never
        # rescheduled by this eval (update_by_reschedulable's gate)
        dep = self.deployment
        if dep is not None and dep.active():
            depcode = c.dep_of.get(dep.id, -2)
            blocked = (proceed & (c.dep_code[rows] == depcode)
                       & ~c.resched_flag[rows])
            untainted_m |= blocked
            proceed &= ~blocked
        # only FAILED rows can be reschedule-eligible (delay math needs
        # policy + tracker + task states); the rest reduce to the
        # force-reschedule flag. The per-alloc verdicts are folded back
        # into the masks BEFORE materializing so dict insertion order
        # stays row order — the reference's `place[:allowed]` slice
        # makes set iteration order semantic, so it must match exactly.
        need_py = proceed & (cl == CLIENT_FAILED_CODE)
        simple = proceed & ~need_py
        force = c.force_resched[rows]
        now_m = simple & force
        untainted_m |= simple & ~force
        reschedule_later: List[DelayedRescheduleInfo] = []
        for i in np.nonzero(need_py)[0].tolist():
            a = c.allocs[rows[i]]
            now_ok, later_ok, t = update_by_reschedulable(
                a, self.now, self.eval_id, self.deployment)
            if not now_ok:
                untainted_m[i] = True
                if later_ok:
                    reschedule_later.append(
                        DelayedRescheduleInfo(a.id, a, t))
            else:
                now_m[i] = True
        return (self._mat(rows[untainted_m]), self._mat(rows[now_m]),
                reschedule_later)

    def _name_index(self, group: str, count: int, untainted: AllocSet,
                    migrate: AllocSet,
                    reschedule_now: AllocSet) -> AllocNameIndex:
        ni = AllocNameIndex(self.job_id, group, count, {})
        rows = self._rows_for(untainted)
        if len(rows):
            vals = self.cols.name_idx[rows]
            ni.b = set(np.unique(vals[vals >= 0]).tolist())
        for small in (migrate, reschedule_now):
            for a in small.values():
                idx = a.index()
                if idx >= 0:
                    ni.b.add(idx)
        return ni

    def _had_running(self, all_set: AllocSet) -> bool:
        rows = self._rows_for(all_set)
        if not len(rows):
            return False
        c = self.cols
        return bool(np.any(
            c.has_job[rows]
            & (c.job_version[rows] == self.job.version)
            & (c.job_create[rows] == self.job.create_index)))

    def _deployment_health(self, untainted: AllocSet,
                           deployment_id: str):
        c = self.cols
        code = c.dep_of.get(deployment_id, -2)
        rows = self._rows_for(untainted)
        part = rows[c.dep_code[rows] == code] if len(rows) else rows
        if not len(part):
            return False, 0
        h = c.healthy[part]
        if np.any(h == -1):
            return True, 0
        return False, int((h != 1).sum())

    def _compute_stop(self, tg, name_index, untainted, migrate, lost,
                      canaries, canary_state, followup_evals):
        # steady-state fast path: nothing lost, nothing migrating, no
        # canaries, and the group is not over count -> the reference
        # body provably returns an empty stop set without side effects
        if not lost and not migrate and not canaries \
                and len(untainted) <= tg.count:
            return {}
        return super()._compute_stop(tg, name_index, untainted, migrate,
                                     lost, canaries, canary_state,
                                     followup_evals)

    def _compute_updates(self, tg, untainted: AllocSet):
        if self.spec_change_fn is None:
            # unknown alloc_update_fn semantics: reference loop
            return super()._compute_updates(tg, untainted)
        c = self.cols
        rows = self._rows_for(untainted)
        if not len(rows):
            return {}, {}, {}
        # mirrors genericAllocUpdateFn's decision ladder (util.go:926)
        # column-wise: (1) same job_modify_index -> ignore; (2) no job
        # snapshot -> destructive; (3) spec changed -> destructive,
        # ONE verdict per distinct old-job snapshot; (4) terminal ->
        # ignore; remaining rows are in-place candidates and drop to
        # the real fn (single-node feasibility + alloc construction)
        hj = c.has_job[rows]
        same = hj & (c.job_mod[rows] == self.job.job_modify_index)
        nojob = ~hj
        rest = ~same & ~nojob
        changed = np.zeros(len(rows), dtype=bool)
        if rest.any():
            from .stack import note_tasks_updated_broadcast
            jc = c.job_code[rows]
            for code in np.unique(jc[rest]).tolist():
                members = rest & (jc == code)
                if self.spec_change_fn(c.job_objs[code], tg.name):
                    changed |= members
                note_tasks_updated_broadcast(int(members.sum()))
        dest_m = nojob | (rest & changed)
        rem = rest & ~changed
        ign2 = rem & self._terminal[rows]
        cand = rem & ~ign2
        ignore = self._mat(rows[same | ign2])
        destructive = self._mat(rows[dest_m])
        inplace: AllocSet = {}
        for r in rows[cand].tolist():
            a = c.allocs[r]
            ignore_change, destructive_change, updated = \
                self.alloc_update_fn(a, self.job, tg)
            if ignore_change:
                ignore[a.id] = a
            elif destructive_change:
                destructive[a.id] = a
            else:
                inplace[a.id] = a
                if updated is not None:
                    self.result.inplace_update.append(updated)
        return ignore, inplace, destructive
