"""Compiled feasibility engine over interned attribute columns
(ISSUE 17 tentpole).

A task group's combined constraint tree compiles ONCE per static key
(the content-addressed key stack.py already builds) into a predicate
program: one op per driver check, per constraint, and one for the
host-volume set. Each op evaluates per UNIQUE value in the attribute's
intern table (state/node_attr_index.py) and broadcasts to bool[N] by
one np.take over the code column — O(distinct) Python, O(N) numpy,
zero per-node attribute walks. Programs and their result masks cache
by (static key, node epoch): a steady-state eval returns the cached
check list untouched, and a node UPDATE re-evaluates one row per
check through the index's mask journal (copy-on-write — older tables
may still hold the previous arrays) instead of rebuilding bool[N].

Bit-parity with the scalar reference is by construction: every unique
value/pair verdict calls ops/targets.constraint_verdict — the same
scalar twin a reference row evaluates — so compiled masks equal
ops/targets.constraint_mask exactly (pinned by the 1k-seed suite in
tests/test_feasible_columnar.py).

Fallbacks (always to the existing scalar path, never an error):
engine disabled (`NOMAD_TPU_COLUMNAR_FEAS=0` or
ServerConfig.feas_columnar=false), detached snapshots, a snapshot
older than the synced columns, table/index row mismatch, or an
overflowed intern table (per-op fallback).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..models.job import (
    CONSTRAINT_DISTINCT_HOSTS, CONSTRAINT_DISTINCT_PROPERTY,
    CONSTRAINT_IS_NOT_SET, CONSTRAINT_IS_SET, CONSTRAINT_REGEX,
    CONSTRAINT_SEMVER, CONSTRAINT_SET_CONTAINS,
    CONSTRAINT_SET_CONTAINS_ALL, CONSTRAINT_SET_CONTAINS_ANY,
    CONSTRAINT_VERSION,
)
from ..ops.targets import (
    constraint_mask, constraint_verdict, driver_ok, host_volume_ok,
    host_volume_value, node_target_value,
)
from ..state import node_attr_index as nai

ENV = "NOMAD_TPU_COLUMNAR_FEAS"
# residue kill switch (ISSUE 20): gates the sparse residue transport
# (token survives CSI/quota/preferred mutations via a per-eval device
# scatter), the flagged-row device check, and the vectorized spread/
# distinct input builds in ops/spread.py
ENV_RESIDUE = "NOMAD_TPU_FEAS_RESIDUE"

_CFG = {"enabled": True, "mask_cache_max": 256, "residue": True}

STATS: Dict[str, int] = {
    "mask_hits": 0,       # cached checks returned untouched
    "mask_patches": 0,    # journal replay: rows re-evaluated in place
    "mask_builds": 0,     # full bool[N] builds from code columns
    "recompiles": 0,      # predicate programs compiled
    "fallbacks": 0,       # compiled path declined, scalar path ran
    "rows_patched": 0,
    # residue transport (ISSUE 20)
    "token_survivals": 0,     # token kept through residue mutations
    "token_invalidations": 0, # residue too wide / switch off: dense path
    "residue_rows": 0,        # mask rows carried as per-eval scatter
    "device_flagged_rows": 0, # rows the flagged-row device check walked
    "device_checks": 0,       # flagged-row device masks built
}

# predicate programs by static key (shared across jobs with identical
# constraint sets, like the engine cache) — FIFO bounded
_PROGRAMS: Dict[Tuple, List[Tuple]] = {}
_PROGRAMS_MAX = 512

# operands whose row verdict reads BOTH resolved values
_PAIR_OPS = ("=", "==", "is", "!=", "not", "<", "<=", ">", ">=")
# operands that compare the lvalue against the RAW rtarget string but
# still require the rtarget to resolve (reference semantics)
_RLUT_OPS = (CONSTRAINT_VERSION, CONSTRAINT_SEMVER, CONSTRAINT_REGEX,
             CONSTRAINT_SET_CONTAINS, CONSTRAINT_SET_CONTAINS_ALL,
             CONSTRAINT_SET_CONTAINS_ANY)


def configure(enabled: Optional[bool] = None,
              intern_max_values: Optional[int] = None,
              mask_cache_max: Optional[int] = None,
              residue: Optional[bool] = None) -> None:
    """Server boot wiring for the ServerConfig.feas_* knobs."""
    if enabled is not None:
        _CFG["enabled"] = bool(enabled)
    if intern_max_values is not None:
        nai.INTERN_MAX_VALUES = int(intern_max_values)
    if mask_cache_max is not None:
        _CFG["mask_cache_max"] = int(mask_cache_max)
    if residue is not None:
        _CFG["residue"] = bool(residue)


def enabled() -> bool:
    env = os.environ.get(ENV)
    if env is not None:
        return env not in ("0", "off", "no", "false")
    return _CFG["enabled"]


def residue_enabled() -> bool:
    env = os.environ.get(ENV_RESIDUE)
    if env is not None:
        return env not in ("0", "off", "no", "false")
    return _CFG["residue"]


def stats() -> Dict[str, int]:
    return dict(STATS)


def hit_rate() -> float:
    """Steady-state effectiveness: journal patches count as hits —
    they do O(changes) work, not O(N)."""
    h = STATS["mask_hits"] + STATS["mask_patches"]
    return h / max(h + STATS["mask_builds"], 1)


def reset_stats() -> None:
    for k in STATS:
        STATS[k] = 0


# -- program compilation ----------------------------------------------

def _program_for(key: Tuple, tg, cons: List) -> List[Tuple]:
    prog = _PROGRAMS.get(key)
    if prog is not None:
        return prog
    prog = []
    for task in tg.tasks:
        if task.driver:
            prog.append(("driver", task.driver,
                         f"missing drivers \"{task.driver}\""))
    for c in cons:
        if c.operand in (CONSTRAINT_DISTINCT_HOSTS,
                         CONSTRAINT_DISTINCT_PROPERTY):
            continue
        prog.append(("cons", c.ltarget, c.rtarget, c.operand, str(c)))
    if tg.volumes:
        vols = tuple(
            (req.source, getattr(req, "read_only", False) is False)
            for req in tg.volumes.values()
            if getattr(req, "type", "host") == "host")
        prog.append(("vol", vols, "missing compatible host volumes"))
    while len(_PROGRAMS) >= _PROGRAMS_MAX:
        _PROGRAMS.pop(next(iter(_PROGRAMS)))
    _PROGRAMS[key] = prog
    STATS["recompiles"] += 1
    return prog


# -- target resolution over the index ---------------------------------

_COLUMN_TARGETS = ("${node.unique.id}", "${node.datacenter}",
                   "${node.unique.name}", "${node.class}")


def _resolve(idx, target: str):
    """('const', value) | ('col', AttrColumn) | ('none',) — the column
    analog of TargetColumns.resolve's three outcomes."""
    if not target.startswith("${"):
        return ("const", target)
    if target in _COLUMN_TARGETS or target.startswith("${attr.") \
            or target.startswith("${meta."):
        return ("col", idx.column(target))
    return ("none",)


def _lut_for(col, op_key: Tuple, verdict_fn) -> np.ndarray:
    """Verdict LUT over one column's intern table + a trailing missing
    slot (codes are -1 for missing, and -1 indexes the LAST element).
    Append-only intern tables mean the LUT only ever EXTENDS."""
    lut = col.luts.get(op_key)
    want = len(col.values) + 1
    if lut is None or len(lut) != want:
        prior = 0 if lut is None else len(lut) - 1
        out = np.empty(want, dtype=bool)
        if prior:
            out[:prior] = lut[:prior]
        for c in range(prior, want - 1):
            out[c] = verdict_fn(col.values[c], True)
        out[-1] = verdict_fn(None, False)
        col.luts[op_key] = out
        lut = out
    return lut


def _found_mask(spec, n: int) -> np.ndarray:
    kind = spec[0]
    if kind == "const":
        return np.ones(n, dtype=bool)
    if kind == "col":
        return spec[1].codes[:n] != -1
    return np.zeros(n, dtype=bool)


def _value_at(spec, code: int):
    """(value, found) for one decoded code of a resolve spec."""
    if spec[0] == "const":
        return spec[1], True
    if spec[0] == "none" or code < 0:
        return None, False
    return spec[1].values[code], True


def _cons_mask(idx, table, ltarget: str, rtarget: str,
               operand: str) -> np.ndarray:
    """One constraint as bool[n] in INDEX row space."""
    n = idx.n
    lspec = _resolve(idx, ltarget)
    rspec = _resolve(idx, rtarget) if rtarget else ("none",)
    if (lspec[0] == "col" and lspec[1].overflow) or \
            (rspec[0] == "col" and rspec[1].overflow):
        # intern table overflowed: reference path for this op, mapped
        # back to index rows via the inverse permutation by the caller
        return None

    if operand == CONSTRAINT_IS_SET:
        return _found_mask(lspec, n)
    if operand == CONSTRAINT_IS_NOT_SET:
        return ~_found_mask(lspec, n)

    if operand in _RLUT_OPS:
        # verdict depends on (lvalue, lfound) and the RAW rtarget only;
        # the resolved rvalue is unused but rfound still gates
        def vf(v, found):
            return constraint_verdict(operand, rtarget, v, found,
                                      None, True)
        if lspec[0] == "col":
            col = lspec[1]
            lut = _lut_for(col, (operand, rtarget), vf)
            base = lut[col.codes[:n]]
        elif lspec[0] == "const":
            base = np.full(n, vf(lspec[1], True), dtype=bool)
        else:
            base = np.full(n, vf(None, False), dtype=bool)
        return base & _found_mask(rspec, n)

    # pair operands (=, !=, <... and anything unknown -> all-False):
    # verdict per unique (lcode, rcode) pair, broadcast by np.take
    if lspec[0] != "col" and rspec[0] != "col":
        lv, lf = _value_at(lspec, -1)
        rv, rf = _value_at(rspec, -1)
        v = constraint_verdict(operand, rtarget, lv, lf, rv, rf)
        return np.full(n, v, dtype=bool)
    if lspec[0] == "col" and rspec[0] != "col":
        col = lspec[1]
        rv, rf = _value_at(rspec, -1)
        lut = _lut_for(col, (operand, rtarget, "l", rv, rf),
                       lambda v, found: constraint_verdict(
                           operand, rtarget, v, found, rv, rf))
        return lut[col.codes[:n]]
    if lspec[0] != "col" and rspec[0] == "col":
        col = rspec[1]
        lv, lf = _value_at(lspec, -1)
        lut = _lut_for(col, (operand, rtarget, "r", lv, lf),
                       lambda v, found: constraint_verdict(
                           operand, rtarget, lv, lf, v, found))
        return lut[col.codes[:n]]
    # both sides are columns: unique-pair path
    lcol, rcol = lspec[1], rspec[1]
    width = len(rcol.values) + 2
    pair = ((lcol.codes[:n].astype(np.int64) + 1) * width
            + (rcol.codes[:n].astype(np.int64) + 1))
    uniq, inverse = np.unique(pair, return_inverse=True)
    verdicts = np.empty(len(uniq), dtype=bool)
    for j, p in enumerate(uniq):
        lc = int(p) // width - 1
        rc = int(p) % width - 1
        lv, lf = _value_at(lspec, lc)
        rv, rf = _value_at(rspec, rc)
        verdicts[j] = constraint_verdict(operand, rtarget, lv, lf,
                                         rv, rf)
    return verdicts[inverse]


def _vol_mask(idx, vols: Tuple) -> np.ndarray:
    n = idx.n
    out = np.ones(n, dtype=bool)
    for source, ro_strict in vols:
        col = idx.column(("vol", source))
        if col.overflow:        # can't happen (2 values) — defensive
            return None
        lut = _lut_for(col, ("vol", ro_strict),
                       lambda v, found: host_volume_ok(
                           v if found else None, ro_strict))
        out &= lut[col.codes[:n]]
    return out


def _op_mask(idx, table, perm, op) -> np.ndarray:
    """One program op as bool[N] in TABLE row space."""
    kind = op[0]
    if kind == "driver":
        col = idx.column(("driver", op[1]))
        m = col.codes[:idx.n] != -1
    elif kind == "vol":
        m = _vol_mask(idx, op[1])
        if m is None:           # defensive: scalar twin over the table
            return np.fromiter(
                (all(host_volume_ok(host_volume_value(node, s), ro)
                     for s, ro in op[1]) for node in table.nodes),
                dtype=bool, count=table.n)
    else:
        m = _cons_mask(idx, table, op[1], op[2], op[3])
        if m is None:
            # intern table overflowed: the reference path already
            # works in table space
            return constraint_mask(table.cols, op[1], op[2], op[3])
    return m[perm]


def _op_row(node, op) -> bool:
    """One program op for ONE node — the journal-replay scalar twin."""
    kind = op[0]
    if kind == "driver":
        return driver_ok(node, op[1])
    if kind == "vol":
        return all(host_volume_ok(host_volume_value(node, source),
                                  ro_strict)
                   for source, ro_strict in op[1])
    _k, ltarget, rtarget, operand, _r = op
    lv, lf = node_target_value(node, ltarget)
    rv, rf = node_target_value(node, rtarget) if rtarget \
        else (None, False)
    return constraint_verdict(operand, rtarget, lv, lf, rv, rf)


# -- entry points ------------------------------------------------------

def static_checks(snapshot, table, tg, cons: List,
                  key: Tuple) -> Optional[List[Tuple[str, np.ndarray]]]:
    """The compiled twin of PlacementEngine._static_checks (drivers,
    constraints, host volumes — device asks stay on the host path and
    are appended by the caller). Returns the ordered (reason, bool[N])
    list in TABLE row space, or None to fall back scalar. The caller
    must copy before appending."""
    if not enabled():
        return None
    store = getattr(snapshot, "_store", None)
    if store is None:
        return None
    cache = getattr(store, "attr_index", None)
    if cache is None or not cache.enabled:
        return None
    if cache.needs_build():
        cache.build_install(snapshot)
    with cache.lock:
        idx = cache.synced(snapshot)
        if idx is None:
            STATS["fallbacks"] += 1
            return None
        entry = idx.mask_cache.get(key)
        if entry is not None and entry["epoch"] == idx.ids_epoch:
            if entry["version"] == idx.version:
                STATS["mask_hits"] += 1
                return entry["checks"]
            rows = idx.rows_since(entry["version"])
            if rows is not None:
                perm, inv = idx.perm_for(table.ids)
                if perm is not None:
                    checks = _patch(idx, entry, rows, inv)
                    entry["checks"] = checks
                    entry["version"] = idx.version
                    STATS["mask_patches"] += 1
                    STATS["rows_patched"] += len(rows)
                    return checks
        perm, _inv = idx.perm_for(table.ids)
        if perm is None:
            STATS["fallbacks"] += 1
            return None
        prog = _program_for(key, tg, cons)
        checks = [(op[-1], _op_mask(idx, table, perm, op))
                  for op in prog]
        STATS["mask_builds"] += 1
        while len(idx.mask_cache) >= _CFG["mask_cache_max"]:
            idx.mask_cache.pop(next(iter(idx.mask_cache)))
        idx.mask_cache[key] = {"epoch": idx.ids_epoch,
                               "version": idx.version,
                               "prog": prog, "checks": checks}
        return checks


def _patch(idx, entry: dict, rows: List[int],
           inv: np.ndarray) -> List[Tuple[str, np.ndarray]]:
    """Journal replay: re-evaluate the changed rows per check,
    copy-on-write (readers of older table versions may still hold the
    previous arrays mid-AND)."""
    t_rows = [int(inv[r]) for r in rows]
    out = []
    for (reason, mask), op in zip(entry["checks"], entry["prog"]):
        m = mask.copy()
        for r, tr in zip(rows, t_rows):
            m[tr] = _op_row(idx.nodes[r], op)
        out.append((reason, m))
    return out


# -- device residency (ISSUE 17 part 3) --------------------------------

def push_combined(mirror, feas_key: Tuple, mask: np.ndarray, snapshot,
                  static_key: Tuple) -> Optional[Tuple]:
    """Push one COMBINED feasibility mask beside the mirror's resident
    columns (ops/device_table.py FeasMaskStore). Row-patches through
    the index's mask journal when the node-id set is unchanged — a
    node update re-ships one bool row, not N. Returns the residency
    token select_batch hands to the kernel dispatch, or None."""
    if mirror is None or not enabled():
        return None
    store = getattr(snapshot, "_store", None)
    if store is None:
        return None
    cache = getattr(store, "attr_index", None)
    if cache is None:
        return None
    feas = getattr(mirror, "feas", None)
    if feas is None:
        return None
    with cache.lock:
        idx = cache._idx
        if idx is None or idx.version != snapshot.index("nodes"):
            return None
        ent = idx.mask_cache.get(static_key)
        if ent is None or ent["epoch"] != idx.ids_epoch \
                or ent["version"] != idx.version:
            return None
        prev = feas.peek(feas_key)
        rows = None
        if prev is not None and prev[0] == idx.ids_epoch \
                and prev[1] < idx.version:
            changed = idx.rows_since(prev[1])
            p = idx._perm    # set by the static_checks call this eval
            if changed is not None and p is not None \
                    and p[0] == idx.ids_epoch:
                rows = [int(p[2][r]) for r in changed]
        return feas.put(feas_key, mask, idx.ids_epoch, idx.version,
                        rows)


# -- flagged-row device inventory (ISSUE 20) ---------------------------

def device_rows_check(snapshot, table, asks) -> Optional[np.ndarray]:
    """The device capability mask as a flagged-row column: device
    inventory is a write-through synthetic column (("dev", "") in
    state/node_attr_index.py), so only rows whose nodes actually
    REPORT devices drop to the scalar group_satisfies walk — the rest
    are False by construction (a deviceless node can never satisfy a
    non-empty ask). Replaces the O(N)-per-table-rebuild walk in
    devices.static_device_mask with O(flagged). Returns None to fall
    back to the dense walk (engine/residue off, detached snapshot,
    unsynced index)."""
    if not asks or not enabled() or not residue_enabled():
        return None
    store = getattr(snapshot, "_store", None)
    if store is None:
        return None
    cache = getattr(store, "attr_index", None)
    if cache is None or not cache.enabled:
        return None
    if cache.needs_build():
        cache.build_install(snapshot)
    from .devices import node_device_ok
    with cache.lock:
        idx = cache.synced(snapshot)
        if idx is None:
            STATS["fallbacks"] += 1
            return None
        perm, _inv = idx.perm_for(table.ids)
        if perm is None:
            STATS["fallbacks"] += 1
            return None
        col = idx.column(("dev", ""))
        flagged = (col.codes[:idx.n] != -1)[perm]
    mask = np.zeros(table.n, dtype=bool)
    rows = np.flatnonzero(flagged)
    for r in rows:
        mask[r] = node_device_ok(table.nodes[int(r)], asks)
    STATS["device_flagged_rows"] += int(rows.size)
    STATS["device_checks"] += 1
    return mask
