"""SystemScheduler: one alloc per feasible node per task group.

Reference semantics: scheduler/system_sched.go (Process:54,
computeJobAllocs:183, computePlacements:268) and diffSystemAllocs
(util.go:70,201). The per-node diff is host-side; feasibility and fit
run as columnar masks over the whole node table at once.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..models import (
    AllocatedResources, AllocatedSharedResources, Allocation, AllocMetric,
    Evaluation, Job, Plan,
    ALLOC_CLIENT_LOST, ALLOC_CLIENT_PENDING, ALLOC_DESIRED_RUN,
    EVAL_STATUS_COMPLETE, EVAL_STATUS_FAILED,
)
from ..ops import ProposedIndex
from ..utils.ids import generate_uuid
from .context import EvalContext
from .reconcile import ALLOC_NOT_NEEDED, ALLOC_LOST
from .stack import PlacementEngine
from .util import tainted_nodes, tasks_updated, update_non_terminal_allocs_to_lost

MAX_SYSTEM_ATTEMPTS = 5

ALLOC_NODE_TAINTED = "alloc not needed as node is tainted"


class SetStatusError(Exception):
    def __init__(self, eval_status: str, msg: str):
        super().__init__(msg)
        self.eval_status = eval_status


class SystemScheduler:
    def __init__(self, state, planner):
        self.state = state
        self.planner = planner
        self.eval: Optional[Evaluation] = None
        self.job: Optional[Job] = None
        self.plan: Optional[Plan] = None
        self.failed_tg_allocs: Dict[str, AllocMetric] = {}
        self.queued_allocs: Dict[str, int] = {}

    def process(self, evaluation: Evaluation) -> None:
        self.eval = evaluation
        for _ in range(MAX_SYSTEM_ATTEMPTS):
            done, progress = self._process_once()
            if done:
                self._set_status(EVAL_STATUS_COMPLETE, "")
                return
            if not progress:
                break
        self._set_status(EVAL_STATUS_FAILED,
                         f"maximum attempts reached ({MAX_SYSTEM_ATTEMPTS})")

    def _process_once(self):
        ev = self.eval
        self.job = self.state.job_by_id(ev.namespace, ev.job_id)
        self.failed_tg_allocs = {}
        self.queued_allocs = {}
        self.plan = ev.make_plan(self.job)

        allocs = self.state.allocs_by_job(ev.namespace, ev.job_id)
        tainted = tainted_nodes(self.state, allocs)
        update_non_terminal_allocs_to_lost(self.plan, tainted, allocs)

        engine = PlacementEngine(self.state)
        if self.job is None or self.job.stopped():
            # stop everything
            for alloc in allocs:
                if not alloc.terminal_status():
                    self.plan.append_stopped_alloc(alloc, ALLOC_NOT_NEEDED)
            return self._finish()

        engine.set_job(self.job)
        n = engine.set_nodes(self.job.datacenters)
        table = engine.table
        live_by_node_tg = {}
        for alloc in allocs:
            if alloc.terminal_status():
                continue
            live_by_node_tg.setdefault((alloc.node_id, alloc.task_group),
                                       []).append(alloc)

        # stop allocs on nodes that are no longer ready / in the node set
        valid_nodes = engine.eligible_node_ids()
        for alloc in allocs:
            if alloc.terminal_status():
                continue
            if alloc.node_id not in valid_nodes:
                node = tainted.get(alloc.node_id, "absent")
                if node != "absent" and (node is None or
                                         node.terminal_status()):
                    self.plan.append_stopped_alloc(
                        alloc, ALLOC_LOST, ALLOC_CLIENT_LOST)
                else:
                    self.plan.append_stopped_alloc(alloc, ALLOC_NODE_TAINTED)

        # stop allocs whose task group was removed from the job
        tg_names = {tg.name for tg in self.job.task_groups}
        for alloc in allocs:
            if alloc.terminal_status() or alloc.node_id not in valid_nodes:
                continue
            if alloc.task_group not in tg_names:
                self.plan.append_stopped_alloc(alloc, ALLOC_NOT_NEEDED)

        # in-place vs destructive updates for existing allocs
        for alloc in allocs:
            if alloc.terminal_status() or alloc.node_id not in valid_nodes:
                continue
            tg = self.job.lookup_task_group(alloc.task_group)
            if tg is None:
                continue
            if alloc.job is not None and \
                    alloc.job.job_modify_index != self.job.job_modify_index:
                in_place = (
                    not tasks_updated(self.job, alloc.job, tg.name)
                    and bool(engine.feasibility(tg)[0][
                        table.id_to_idx[alloc.node_id]]))
                if in_place:
                    # same tasks under a new job version on a still-
                    # feasible node: the alloc keeps its id/node/
                    # resources and adopts the updated job
                    # (inplaceUpdate, util.go:633; feasibility
                    # re-checked like the generic _alloc_update_fn)
                    updated = alloc.copy_skip_job()
                    updated.job = None      # plan attaches plan.job
                    updated.eval_id = self.eval.id
                    self.plan.append_alloc(updated)
                else:
                    # destructive: stop; a replacement lands below
                    # only where the new version's mask allows
                    self.plan.append_stopped_alloc(
                        alloc, "alloc is being updated due to job update")
                    entry = live_by_node_tg.get(
                        (alloc.node_id, alloc.task_group))
                    if entry and alloc in entry:
                        entry.remove(alloc)

        # place each task group on every feasible node lacking an alloc
        for tg in self.job.task_groups:
            mask, filtered_counts = engine.feasibility(tg)
            missing_idx = [i for i, nid in enumerate(table.ids)
                           if mask[i] and not live_by_node_tg.get((nid, tg.name))]
            if not missing_idx:
                continue
            proposed = ProposedIndex(table, self.job, allocs, self.plan)
            used = proposed.used()
            ask = engine.group_ask(tg)
            fits = np.all(used + ask[None, :] <= table.capacity + 1e-6, axis=1)

            from .preemption import preemption_enabled
            preempt_ok = preemption_enabled(self.state.scheduler_config(),
                                            "system")
            placed = 0
            exhausted = 0
            for i in missing_idx:
                node = table.nodes[i]
                victims = None
                if not fits[i]:
                    if preempt_ok:
                        victims = self._find_victims(node, tg, engine, ask)
                    if not victims:
                        exhausted += 1
                        continue
                    for v in victims:
                        self.plan.append_preempted_alloc(v, "")
                    engine._net_cache.pop(node.id, None)
                task_resources, shared, ok = engine._assign_resources(
                    node, tg, self.plan)
                if not ok:
                    exhausted += 1
                    continue
                alloc = Allocation(
                    id=generate_uuid(),
                    namespace=self.job.namespace,
                    eval_id=ev.id,
                    name=f"{self.job.id}.{tg.name}[0]",
                    job_id=self.job.id,
                    task_group=tg.name,
                    node_id=node.id,
                    node_name=node.name,
                    allocated_resources=AllocatedResources(
                        tasks=task_resources,
                        shared=shared or AllocatedSharedResources(
                            disk_mb=tg.ephemeral_disk.size_mb
                            if tg.ephemeral_disk else 0)),
                    desired_status=ALLOC_DESIRED_RUN,
                    client_status=ALLOC_CLIENT_PENDING,
                    metrics=AllocMetric(nodes_evaluated=n,
                                        nodes_available=dict(engine.by_dc)),
                )
                if victims:
                    from .preemption import link_preemptions
                    link_preemptions(self.plan, alloc, victims)
                self.plan.append_alloc(alloc)
                placed += 1
            if exhausted:
                m = AllocMetric()
                m.nodes_evaluated = n
                m.nodes_filtered = int(n - mask.sum())
                m.constraint_filtered = dict(filtered_counts)
                m.nodes_exhausted = exhausted
                m.nodes_available = dict(engine.by_dc)
                self.failed_tg_allocs[tg.name] = m
                self.queued_allocs[tg.name] = exhausted

        return self._finish()

    def _find_victims(self, node, tg, engine, ask):
        """Preemption candidates on one node for a system placement."""
        from ..models import ComparableResources
        from .preemption import Preemptor
        stopped = {a.id for allocs in self.plan.node_update.values()
                   for a in allocs}
        stopped |= {a.id for allocs in self.plan.node_preemptions.values()
                    for a in allocs}
        proposed = [a for a in self.state.allocs_by_node(node.id)
                    if not a.terminal_status() and a.id not in stopped]
        proposed.extend(self.plan.node_allocation.get(node.id, []))
        p = Preemptor(self.job.priority, self.job.namespace, self.job.id)
        p.set_node(node)
        p.set_candidates(proposed)
        current = [a for allocs in self.plan.node_preemptions.values()
                   for a in allocs]
        p.set_preemptions(current)
        return p.preempt_for_task_group(ComparableResources(
            cpu_shares=float(ask[0]), memory_mb=float(ask[1]),
            disk_mb=float(ask[2])))

    def _finish(self):
        if self.plan.is_no_op():
            return True, False
        result = self.planner.submit_plan(self.plan)
        if result is None:
            return True, False
        full, expected, actual = result.full_commit(self.plan)
        if not full:
            return False, actual > 0
        return True, False

    def _set_status(self, status: str, desc: str) -> None:
        new_eval = self.eval.copy()
        new_eval.status = status
        new_eval.status_description = desc
        if self.failed_tg_allocs:
            new_eval.failed_tg_allocs = dict(self.failed_tg_allocs)
        new_eval.queued_allocations = dict(self.queued_allocs)
        self.planner.update_eval(new_eval)
