"""Allocation set algebra and name indexing for the reconciler.

Reference semantics: scheduler/reconcile_util.go — allocSet/allocMatrix
:97-208, filterByTainted:211, filterByRescheduleable:251,
allocNameIndex:413-575.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from ..models import (
    Allocation, Node,
    ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_FAILED, ALLOC_CLIENT_LOST,
    ALLOC_DESIRED_EVICT, ALLOC_DESIRED_STOP,
)

# reconciler window within which a delayed reschedule counts as "now"
RESCHEDULE_WINDOW_S = 5.0
# batching window for delayed-reschedule follow-up evals (reconcile.go)
BATCHED_FAILED_ALLOC_WINDOW_S = 5.0

AllocSet = Dict[str, Allocation]


def alloc_name(job_id: str, group: str, idx: int) -> str:
    return f"{job_id}.{group}[{idx}]"


def new_alloc_matrix(job, allocs: List[Allocation]) -> Dict[str, AllocSet]:
    m: Dict[str, AllocSet] = {}
    for a in allocs:
        m.setdefault(a.task_group, {})[a.id] = a
    if job is not None:
        for tg in job.task_groups:
            m.setdefault(tg.name, {})
    return m


def difference(a: AllocSet, *others: AllocSet) -> AllocSet:
    out = dict(a)
    for o in others:
        for k in o:
            out.pop(k, None)
    return out


def union(a: AllocSet, *others: AllocSet) -> AllocSet:
    out = dict(a)
    for o in others:
        out.update(o)
    return out


def from_keys(a: AllocSet, keys: List[str]) -> AllocSet:
    return {k: a[k] for k in keys if k in a}


def name_set(a: AllocSet) -> Set[str]:
    return {alloc.name for alloc in a.values()}


def name_order(a: AllocSet) -> List[Allocation]:
    """Allocs sorted by their name index (reconcile_util.go nameOrder)."""
    return sorted(a.values(), key=lambda x: x.index())


def filter_by_terminal(a: AllocSet) -> AllocSet:
    return {k: v for k, v in a.items() if not v.terminal_status()}


def filter_by_tainted(a: AllocSet, tainted: Dict[str, Optional[Node]]
                      ) -> Tuple[AllocSet, AllocSet, AllocSet]:
    """(untainted, migrate, lost) — reconcile_util.go:211."""
    untainted: AllocSet = {}
    migrate: AllocSet = {}
    lost: AllocSet = {}
    for alloc in a.values():
        if alloc.terminal_status():
            untainted[alloc.id] = alloc
            continue
        if alloc.desired_transition.should_migrate():
            migrate[alloc.id] = alloc
            continue
        if alloc.node_id not in tainted:
            untainted[alloc.id] = alloc
            continue
        node = tainted[alloc.node_id]
        if node is None or node.terminal_status():
            lost[alloc.id] = alloc
            continue
        untainted[alloc.id] = alloc
    return untainted, migrate, lost


def should_filter(alloc: Allocation, is_batch: bool) -> Tuple[bool, bool]:
    """(untainted, ignore) — reconcile_util.go shouldFilter:299."""
    if is_batch:
        if alloc.desired_status in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT):
            if alloc.ran_successfully():
                return True, False
            return False, True
        if alloc.client_status != ALLOC_CLIENT_FAILED:
            return True, False
        return False, False
    # service jobs
    if alloc.desired_status in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT):
        return False, True
    if alloc.client_status in (ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_LOST):
        return False, True
    return False, False


def update_by_reschedulable(alloc: Allocation, now: float, eval_id: str,
                            deployment) -> Tuple[bool, bool, float]:
    """(reschedule_now, reschedule_later, time) — reconcile_util.go:339."""
    if (deployment is not None and alloc.deployment_id == deployment.id
            and deployment.active()
            and not bool(alloc.desired_transition.reschedule)):
        return False, False, 0.0
    reschedule_now = alloc.desired_transition.should_force_reschedule()
    t, eligible = alloc.next_reschedule_time()
    if eligible and (alloc.follow_up_eval_id == eval_id
                     or t - now <= RESCHEDULE_WINDOW_S):
        return True, False, t
    if eligible and alloc.follow_up_eval_id == "":
        return reschedule_now, True, t
    return reschedule_now, False, t


@dataclasses.dataclass
class DelayedRescheduleInfo:
    alloc_id: str
    alloc: Allocation
    reschedule_time: float


def filter_by_rescheduleable(a: AllocSet, is_batch: bool, now: float,
                             eval_id: str, deployment
                             ) -> Tuple[AllocSet, AllocSet,
                                        List[DelayedRescheduleInfo]]:
    """(untainted, reschedule_now, reschedule_later) — :251."""
    untainted: AllocSet = {}
    reschedule_now: AllocSet = {}
    reschedule_later: List[DelayedRescheduleInfo] = []
    for alloc in a.values():
        if alloc.next_allocation != "" and alloc.terminal_status():
            continue
        is_untainted, ignore = should_filter(alloc, is_batch)
        if is_untainted:
            untainted[alloc.id] = alloc
        if is_untainted or ignore:
            continue
        now_ok, later_ok, t = update_by_reschedulable(alloc, now, eval_id,
                                                      deployment)
        if not now_ok:
            untainted[alloc.id] = alloc
            if later_ok:
                reschedule_later.append(
                    DelayedRescheduleInfo(alloc.id, alloc, t))
        else:
            reschedule_now[alloc.id] = alloc
    return untainted, reschedule_now, reschedule_later


def filter_by_deployment(a: AllocSet, deployment_id: str
                         ) -> Tuple[AllocSet, AllocSet]:
    match: AllocSet = {}
    nonmatch: AllocSet = {}
    for alloc in a.values():
        if alloc.deployment_id == deployment_id:
            match[alloc.id] = alloc
        else:
            nonmatch[alloc.id] = alloc
    return match, nonmatch


def delay_by_stop_after_client_disconnect(lost: AllocSet, now: float
                                          ) -> List[DelayedRescheduleInfo]:
    """Lost allocs whose group sets stop_after_client_disconnect get a
    delayed stop instead of an immediate one
    (reconcile_util.go delayByStopAfterClientDisconnect:391)."""
    later: List[DelayedRescheduleInfo] = []
    for a in lost.values():
        tg = a.job.lookup_task_group(a.task_group) if a.job else None
        if tg is None or tg.stop_after_client_disconnect_s is None:
            continue
        later.append(DelayedRescheduleInfo(
            a.id, a, now + tg.stop_after_client_disconnect_s))
    return later


class AllocNameIndex:
    """Bitmap-based alloc name chooser (reconcile_util.go:413-575)."""

    def __init__(self, job_id: str, task_group: str, count: int,
                 in_use: AllocSet):
        self.job_id = job_id
        self.task_group = task_group
        self.count = count
        self.b: Set[int] = set()
        for a in in_use.values():
            idx = a.index()
            if idx >= 0:
                self.b.add(idx)

    def highest(self, n: int) -> Set[str]:
        """Remove and return the highest n used names."""
        out: Set[str] = set()
        for idx in sorted(self.b, reverse=True):
            if len(out) >= n:
                break
            self.b.discard(idx)
            out.add(alloc_name(self.job_id, self.task_group, idx))
        return out

    def unset_index(self, idx: int) -> None:
        self.b.discard(idx)

    def next(self, n: int) -> List[str]:
        out: List[str] = []
        for idx in range(self.count):
            if len(out) == n:
                return out
            if idx not in self.b:
                out.append(alloc_name(self.job_id, self.task_group, idx))
                self.b.add(idx)
        # exhausted the free set; pick overlapping indexes
        i = 0
        while len(out) < n:
            out.append(alloc_name(self.job_id, self.task_group, i))
            self.b.add(i)
            i += 1
        return out

    def next_canaries(self, n: int, existing: AllocSet,
                      destructive: AllocSet) -> List[str]:
        next_names: List[str] = []
        existing_names = name_set(existing)
        # prefer indexes of destructive updates (they'll be replaced)
        dest_idx = sorted(a.index() for a in destructive.values()
                          if 0 <= a.index() < self.count)
        for idx in dest_idx:
            name = alloc_name(self.job_id, self.task_group, idx)
            if name not in existing_names and name not in next_names:
                next_names.append(name)
                self.b.add(idx)
                if len(next_names) == n:
                    return next_names
        for idx in range(self.count):
            if idx in self.b:
                continue
            name = alloc_name(self.job_id, self.task_group, idx)
            if name not in existing_names and name not in next_names:
                next_names.append(name)
                self.b.add(idx)
                if len(next_names) == n:
                    return next_names
        i = self.count
        while len(next_names) < n:
            next_names.append(alloc_name(self.job_id, self.task_group, i))
            i += 1
        return next_names
