"""In-process scheduler test harness.

Reference semantics: scheduler/testing.go — Harness:43 wraps a real
state store, implements Planner by applying plans directly, and records
Plans/Evals for assertions.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from ..models import Evaluation, Plan, PlanResult
from ..state import StateStore
from .scheduler import new_scheduler
from ..utils.locks import make_lock


class RejectPlan:
    """Planner that rejects everything (testing.go:18) — exercises the
    scheduler's retry path."""

    def __init__(self, harness: "Harness"):
        self.h = harness

    def submit_plan(self, plan: Plan) -> PlanResult:
        result = PlanResult(refresh_index=self.h.store.latest_index())
        return result

    def update_eval(self, evaluation: Evaluation) -> None:
        pass

    def create_eval(self, evaluation: Evaluation) -> None:
        pass

    def reblock_eval(self, evaluation: Evaluation) -> None:
        pass


class Harness:
    # recorded plans/evals are assertion material for tests, but a
    # long bench loop (bench/soak.py) drives hundreds of thousands of
    # evals through one harness — unbounded recording was one of the
    # round-5 soak's RSS leaks (each plan pins its placed allocs and
    # job). Tests never come close to this bound.
    MAX_HISTORY = 4096

    def __init__(self, store: Optional[StateStore] = None):
        self.store = store or StateStore()
        self.planner = None
        self.plans: List[Plan] = []
        self.evals: List[Evaluation] = []
        self.create_evals: List[Evaluation] = []
        self.reblock_evals: List[Evaluation] = []
        self._lock = make_lock()
        self._next_index = 1000

    def _trim(self, lst: List) -> None:
        if len(lst) > self.MAX_HISTORY:
            del lst[:len(lst) - self.MAX_HISTORY]

    def next_index(self) -> int:
        with self._lock:
            self._next_index += 1
            return self._next_index

    # -- Planner interface --------------------------------------------
    def submit_plan(self, plan: Plan) -> PlanResult:
        with self._lock:
            self.plans.append(plan)
            self._trim(self.plans)
        if self.planner is not None:
            return self.planner.submit_plan(plan)

        # apply the plan directly to the state store (testing.go:83)
        index = self.next_index()
        stopped = [a for allocs in plan.node_update.values() for a in allocs]
        placed = [a for allocs in plan.node_allocation.values() for a in allocs]
        preempted = [a for allocs in plan.node_preemptions.values()
                     for a in allocs]
        for a in placed:
            if a.job is None:
                a.job = plan.job
        self.store.upsert_plan_results(
            index,
            allocs_stopped=stopped,
            allocs_placed=placed,
            allocs_preempted=preempted,
            deployment=plan.deployment,
            deployment_updates=plan.deployment_updates,
        )
        return PlanResult(
            node_update=plan.node_update,
            node_allocation=plan.node_allocation,
            node_preemptions=plan.node_preemptions,
            deployment=plan.deployment,
            deployment_updates=plan.deployment_updates,
            alloc_index=index,
        )

    def update_eval(self, evaluation: Evaluation) -> None:
        with self._lock:
            self.evals.append(evaluation)
            self._trim(self.evals)

    def create_eval(self, evaluation: Evaluation) -> None:
        with self._lock:
            self.create_evals.append(evaluation)
            self._trim(self.create_evals)

    def reblock_eval(self, evaluation: Evaluation) -> None:
        with self._lock:
            self.reblock_evals.append(evaluation)
            self._trim(self.reblock_evals)

    # -- driving -------------------------------------------------------
    def process(self, scheduler_name: str, evaluation: Evaluation) -> None:
        snapshot = self.store.snapshot()
        sched = new_scheduler(scheduler_name, snapshot, self)
        sched.process(evaluation)

    def assert_eval_status(self, testcase, status: str) -> None:
        assert len(self.evals) > 0
        assert self.evals[-1].status == status
