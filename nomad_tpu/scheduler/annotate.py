"""Plan diff annotations (scheduler/annotate.go).

`nomad job plan` shows the job diff; this pass decorates it so a human
can read consequences off the plan: task-group update counts from the
scheduler's DesiredUpdates, count-change arrows, and per-task
forces-create/destroy/in-place/destructive annotations.
"""

from __future__ import annotations

from typing import Dict, List, Optional

# annotate.go:9-14
FORCES_CREATE = "forces create"
FORCES_DESTROY = "forces destroy"
FORCES_INPLACE = "forces in-place update"
FORCES_DESTRUCTIVE = "forces create/destroy update"

# annotate.go:17-25
UPDATE_TYPE_IGNORE = "ignore"
UPDATE_TYPE_CREATE = "create"
UPDATE_TYPE_DESTROY = "destroy"
UPDATE_TYPE_MIGRATE = "migrate"
UPDATE_TYPE_CANARY = "canary"
UPDATE_TYPE_INPLACE = "in-place update"
UPDATE_TYPE_DESTRUCTIVE = "create/destroy update"

# primitive task fields whose change does NOT force a destructive
# update (annotate.go:166-177 — KillTimeout only)
_NONDESTRUCTIVE_FIELDS = frozenset({"kill_timeout_s"})
# object changes applicable in place (annotate.go:180-193:
# LogConfig, Service, Constraint)
_INPLACE_OBJECTS = ("log_config", "services", "constraints")


def annotate(diff: Dict, annotations: Optional[Dict] = None) -> Dict:
    """Annotate a job_diff() dict in place (scheduler/annotate.go
    Annotate:38). `annotations` is {"DesiredTGUpdates": {group:
    DesiredUpdates-wire-dict}} from the scheduler's plan."""
    for tg in diff.get("TaskGroups") or []:
        _annotate_task_group(tg, annotations)
    return diff


def _annotate_task_group(tg: Dict,
                         annotations: Optional[Dict]) -> None:
    """annotateTaskGroup:54."""
    updates = ((annotations or {}).get("DesiredTGUpdates") or {}).get(
        tg.get("Name"))
    if updates:
        out = tg.setdefault("Updates", {})
        for src, label in (
                ("ignore", UPDATE_TYPE_IGNORE),
                ("place", UPDATE_TYPE_CREATE),
                ("migrate", UPDATE_TYPE_MIGRATE),
                ("stop", UPDATE_TYPE_DESTROY),
                ("canary", UPDATE_TYPE_CANARY),
                ("in_place_update", UPDATE_TYPE_INPLACE),
                ("destructive_update", UPDATE_TYPE_DESTRUCTIVE)):
            n = updates.get(src) or 0
            if n:
                out[label] = n
    _annotate_count_change(tg)
    for td in tg.get("Tasks") or []:
        _annotate_task(td, tg)


def _annotate_count_change(tg: Dict) -> None:
    """annotateCountChange:106."""
    count = next((f for f in tg.get("Fields") or []
                  if f.get("Name") == "count"), None)
    if count is None:
        return
    old = int(count.get("Old") or 0)
    new = int(count.get("New") or 0)
    if old < new:
        count.setdefault("Annotations", []).append(FORCES_CREATE)
    elif new < old:
        count.setdefault("Annotations", []).append(FORCES_DESTROY)


def _annotate_task(td: Dict, parent: Dict) -> None:
    """annotateTask:150."""
    if td.get("Type") == "None":
        return
    if parent.get("Type") in ("Added", "Deleted"):
        if td.get("Type") == "Added":
            td.setdefault("Annotations", []).append(FORCES_CREATE)
            return
        if td.get("Type") == "Deleted":
            td.setdefault("Annotations", []).append(FORCES_DESTROY)
            return
    destructive = any(
        f.get("Name") not in _NONDESTRUCTIVE_FIELDS
        for f in td.get("Fields") or [])
    if not destructive:
        destructive = any(
            not str(o.get("Name", "")).startswith(_INPLACE_OBJECTS)
            for o in td.get("Objects") or [])
    td.setdefault("Annotations", []).append(
        FORCES_DESTRUCTIVE if destructive else FORCES_INPLACE)
