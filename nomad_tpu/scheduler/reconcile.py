"""The allocation reconciler: declarative diff of desired (job) vs
actual (allocations), producing place/stop/inplace/destructive/migrate
decisions plus deployment lifecycle.

Reference semantics: scheduler/reconcile.go (Compute:184-254,
computeGroup:341, computeStop:753, computeUpdates:864,
handleDelayedReschedules:887). Host-side control flow by design —
SURVEY.md §7.2 step 4.
"""

from __future__ import annotations

import dataclasses
import time as _time
from typing import Callable, Dict, List, Optional

from ..models import (
    Allocation, AllocMetric, Deployment, Evaluation, Job, Node, TaskGroup,
    ALLOC_CLIENT_LOST,
    EVAL_STATUS_PENDING,
)
from ..models.deployment import (
    DeploymentState, DeploymentStatusUpdate,
    DEPLOYMENT_STATUS_CANCELLED, DEPLOYMENT_STATUS_FAILED,
    DEPLOYMENT_STATUS_PAUSED, DEPLOYMENT_STATUS_SUCCESSFUL,
    DESC_NEW_JOB_VERSION, DESC_RUNNING_AUTO_PROMOTION,
    DESC_RUNNING_NEEDS_PROMOTION, DESC_SUCCESSFUL,
)
from ..models.evaluation import TRIGGER_RETRY_FAILED_ALLOC
from ..models.plan import DesiredUpdates
from . import reconcile_util as ru
from .reconcile_util import (AllocNameIndex, AllocSet, DelayedRescheduleInfo,
                             difference, filter_by_deployment,
                             filter_by_rescheduleable, filter_by_tainted,
                             filter_by_terminal, from_keys, name_order,
                             name_set, new_alloc_matrix, union)

# status descriptions (scheduler/generic_sched.go:a few consts)
ALLOC_NOT_NEEDED = "alloc not needed due to job update"
ALLOC_RECONNECTED = "alloc reconnected"
ALLOC_MIGRATING = "alloc is being migrated"
ALLOC_UPDATING = "alloc is being updated due to job update"
ALLOC_LOST = "alloc is lost since its node is down"
ALLOC_UNNEEDED = "alloc is not needed since job count was reduced"
ALLOC_RESCHEDULED = "alloc was rescheduled because it failed"
RESCHEDULING_FOLLOWUP_EVAL_DESC = "created for delayed rescheduling"


@dataclasses.dataclass
class AllocStopResult:
    alloc: Allocation
    client_status: str = ""
    status_description: str = ""
    followup_eval_id: str = ""


@dataclasses.dataclass
class AllocPlaceResult:
    name: str = ""
    canary: bool = False
    task_group: Optional[TaskGroup] = None
    previous_alloc: Optional[Allocation] = None
    reschedule: bool = False
    downgrade_non_canary: bool = False
    min_job_version: int = 0

    def stop_previous(self):
        return False, ""


@dataclasses.dataclass
class AllocDestructiveResult:
    place_name: str = ""
    place_task_group: Optional[TaskGroup] = None
    stop_alloc: Optional[Allocation] = None
    stop_status_description: str = ""

    # placementResult interface parity
    @property
    def name(self):
        return self.place_name

    @property
    def task_group(self):
        return self.place_task_group

    @property
    def previous_alloc(self):
        return self.stop_alloc

    canary = False
    reschedule = False
    downgrade_non_canary = False
    min_job_version = 0

    def stop_previous(self):
        return True, self.stop_status_description


@dataclasses.dataclass
class ReconcileResults:
    """reconcile.go reconcileResults."""
    deployment: Optional[Deployment] = None
    deployment_updates: List[DeploymentStatusUpdate] = dataclasses.field(default_factory=list)
    place: List[AllocPlaceResult] = dataclasses.field(default_factory=list)
    destructive_update: List[AllocDestructiveResult] = dataclasses.field(default_factory=list)
    inplace_update: List[Allocation] = dataclasses.field(default_factory=list)
    stop: List[AllocStopResult] = dataclasses.field(default_factory=list)
    attribute_updates: Dict[str, Allocation] = dataclasses.field(default_factory=dict)
    desired_tg_updates: Dict[str, DesiredUpdates] = dataclasses.field(default_factory=dict)
    desired_followup_evals: Dict[str, List[Evaluation]] = dataclasses.field(default_factory=dict)


# allocUpdateFn(alloc, new_job, new_tg) -> (ignore, destructive, updated_alloc)
AllocUpdateFn = Callable


class AllocReconciler:
    def __init__(self, alloc_update_fn: AllocUpdateFn, batch: bool,
                 job_id: str, job: Job, deployment: Optional[Deployment],
                 existing_allocs: List[Allocation],
                 tainted_nodes: Dict[str, Optional[Node]],
                 eval_id: str, now: Optional[float] = None):
        self.alloc_update_fn = alloc_update_fn
        self.batch = batch
        self.job_id = job_id
        self.job = job
        self.deployment = deployment.copy() if deployment else None
        self.old_deployment: Optional[Deployment] = None
        self.existing_allocs = existing_allocs
        self.tainted_nodes = tainted_nodes
        self.eval_id = eval_id
        self.now = now if now is not None else _time.time()
        self.deployment_paused = False
        self.deployment_failed = False
        self.result = ReconcileResults()

    # -- set-algebra hooks ---------------------------------------------
    # The columnar engine (reconcile_columnar.ColumnarAllocReconciler)
    # overrides these with numpy-mask versions computed over the state
    # store's per-job alloc index; the base implementations are the
    # reference per-alloc path. Hooks return the SAME dict shapes so
    # the group math below stays shared between both engines.
    def _matrix(self) -> Dict[str, AllocSet]:
        return new_alloc_matrix(self.job, self.existing_allocs)

    def _filter_tainted(self, a: AllocSet):
        return filter_by_tainted(a, self.tainted_nodes)

    def _filter_terminal(self, a: AllocSet) -> AllocSet:
        return filter_by_terminal(a)

    def _filter_rescheduleable(self, a: AllocSet):
        return filter_by_rescheduleable(a, self.batch, self.now,
                                        self.eval_id, self.deployment)

    def _name_index(self, group: str, count: int, untainted: AllocSet,
                    migrate: AllocSet,
                    reschedule_now: AllocSet) -> "AllocNameIndex":
        return AllocNameIndex(self.job_id, group, count,
                              union(untainted, migrate, reschedule_now))

    def _had_running(self, all_set: AllocSet) -> bool:
        return any(
            a.job is not None and a.job.version == self.job.version
            and a.job.create_index == self.job.create_index
            for a in all_set.values())

    def _deployment_health(self, untainted: AllocSet,
                           deployment_id: str):
        """(any_unhealthy, n_not_healthy) over the untainted allocs
        that belong to `deployment_id` (the rolling-limit discount,
        reconcile.go computeLimit)."""
        part_of, _ = filter_by_deployment(untainted, deployment_id)
        n = 0
        for alloc in part_of.values():
            ds = alloc.deployment_status
            if ds is not None and ds.is_unhealthy():
                return True, n
            if ds is None or not ds.is_healthy():
                n += 1
        return False, n

    # -- top level -----------------------------------------------------
    def compute(self) -> ReconcileResults:
        m = self._matrix()
        self._cancel_deployments()

        # a nil job behaves as stopped (structs.go Job.Stopped treats a
        # nil receiver as stopped; the GC path reconciles deleted jobs)
        if self.job is None or self.job.stopped():
            self._handle_stop(m)
            return self.result

        if self.deployment is not None:
            self.deployment_paused = self.deployment.status == DEPLOYMENT_STATUS_PAUSED
            self.deployment_failed = self.deployment.status == DEPLOYMENT_STATUS_FAILED

        complete = True
        for group, allocs in m.items():
            complete &= self._compute_group(group, allocs)

        if self.deployment is not None and complete:
            self.result.deployment_updates.append(DeploymentStatusUpdate(
                deployment_id=self.deployment.id,
                status=DEPLOYMENT_STATUS_SUCCESSFUL,
                status_description=DESC_SUCCESSFUL,
            ))

        d = self.result.deployment
        if d is not None and d.requires_promotion():
            d.status_description = (DESC_RUNNING_AUTO_PROMOTION
                                    if d.has_auto_promote()
                                    else DESC_RUNNING_NEEDS_PROMOTION)
        return self.result

    def _cancel_deployments(self) -> None:
        if self.job is None or self.job.stopped():
            if self.deployment is not None and self.deployment.active():
                self.result.deployment_updates.append(DeploymentStatusUpdate(
                    deployment_id=self.deployment.id,
                    status=DEPLOYMENT_STATUS_CANCELLED,
                    status_description="Cancelled because job is stopped",
                ))
            self.old_deployment = self.deployment
            self.deployment = None
            return
        d = self.deployment
        if d is None:
            return
        if (d.job_create_index != self.job.create_index
                or d.job_version != self.job.version):
            if d.active():
                self.result.deployment_updates.append(DeploymentStatusUpdate(
                    deployment_id=d.id,
                    status=DEPLOYMENT_STATUS_CANCELLED,
                    status_description=DESC_NEW_JOB_VERSION,
                ))
            self.old_deployment = d
            self.deployment = None
        elif d.status == DEPLOYMENT_STATUS_SUCCESSFUL:
            self.old_deployment = d
            self.deployment = None

    def _handle_stop(self, m: Dict[str, AllocSet]) -> None:
        for group, allocs in m.items():
            allocs = self._filter_terminal(allocs)
            untainted, migrate, lost = self._filter_tainted(allocs)
            self._mark_stop(untainted, "", ALLOC_NOT_NEEDED)
            self._mark_stop(migrate, "", ALLOC_NOT_NEEDED)
            self._mark_stop(lost, ALLOC_CLIENT_LOST, ALLOC_LOST)
            du = DesiredUpdates(stop=len(allocs))
            self.result.desired_tg_updates[group] = du

    def _mark_stop(self, allocs: AllocSet, client_status: str,
                   desc: str, followup: Optional[Dict[str, str]] = None) -> None:
        for alloc in allocs.values():
            self.result.stop.append(AllocStopResult(
                alloc=alloc, client_status=client_status,
                status_description=desc,
                followup_eval_id=(followup or {}).get(alloc.id, "")))

    # -- per group -----------------------------------------------------
    def _compute_group(self, group: str, all_set: AllocSet) -> bool:
        desired = DesiredUpdates()
        self.result.desired_tg_updates[group] = desired
        tg = self.job.lookup_task_group(group)

        if tg is None:
            untainted, migrate, lost = self._filter_tainted(all_set)
            self._mark_stop(untainted, "", ALLOC_NOT_NEEDED)
            self._mark_stop(migrate, "", ALLOC_NOT_NEEDED)
            self._mark_stop(lost, ALLOC_CLIENT_LOST, ALLOC_LOST)
            desired.stop = len(untainted) + len(migrate) + len(lost)
            return True

        dstate: Optional[DeploymentState] = None
        existing_deployment = False
        if self.deployment is not None:
            dstate = self.deployment.task_groups.get(group)
            existing_deployment = dstate is not None
        if not existing_deployment:
            dstate = DeploymentState()
            if tg.update is not None:
                dstate.auto_revert = tg.update.auto_revert
                dstate.auto_promote = tg.update.auto_promote
                dstate.progress_deadline_s = tg.update.progress_deadline_s

        all_set, n_old_ignore = self._filter_old_terminal_allocs(all_set)
        desired.ignore += n_old_ignore

        canaries, all_set = self._handle_group_canaries(all_set, desired)

        untainted, migrate, lost = self._filter_tainted(all_set)
        untainted, reschedule_now, reschedule_later = \
            self._filter_rescheduleable(untainted)

        lost_later = ru.delay_by_stop_after_client_disconnect(lost, self.now)
        lost_later_evals = self._handle_delayed_lost(lost_later, all_set,
                                                     tg.name)
        self._handle_delayed_reschedules(reschedule_later, all_set, tg.name)

        name_index = self._name_index(group, tg.count, untainted,
                                      migrate, reschedule_now)

        canary_state = (dstate is not None and dstate.desired_canaries != 0
                        and not dstate.promoted)
        stop = self._compute_stop(tg, name_index, untainted, migrate, lost,
                                  canaries, canary_state, lost_later_evals)
        desired.stop += len(stop)
        untainted = difference(untainted, stop)

        ignore2, inplace, destructive = self._compute_updates(tg, untainted)
        desired.ignore += len(ignore2)
        desired.in_place_update += len(inplace)
        if not existing_deployment:
            dstate.desired_total += len(destructive) + len(inplace)

        if canary_state:
            untainted = difference(untainted, canaries)

        # an empty strategy (max_parallel == 0) behaves like no update
        # stanza: no deployment, no rolling limit (UpdateStrategy
        # .IsEmpty, structs.go:4644)
        strategy = tg.update if (tg.update is not None
                                 and not tg.update.is_empty()) else None
        canaries_promoted = dstate is not None and dstate.promoted
        require_canary = (len(destructive) != 0 and strategy is not None
                          and len(canaries) < strategy.canary
                          and not canaries_promoted)
        if require_canary:
            dstate.desired_canaries = strategy.canary
        if require_canary and not self.deployment_paused and not self.deployment_failed:
            number = strategy.canary - len(canaries)
            desired.canary += number
            for name in name_index.next_canaries(number, canaries, destructive):
                self.result.place.append(AllocPlaceResult(
                    name=name, canary=True, task_group=tg))

        canary_state = (dstate is not None and dstate.desired_canaries != 0
                        and not dstate.promoted)
        limit = self._compute_limit(tg, untainted, destructive, migrate,
                                    canary_state)

        # a delayed stop_after_client_disconnect alloc delays scheduling
        # for the whole group (reconcile.go:462-467)
        place: List[AllocPlaceResult] = []
        if len(lost_later) == 0:
            place = self._compute_placements(tg, name_index, untainted,
                                             migrate, reschedule_now,
                                             canary_state)
            if not existing_deployment:
                dstate.desired_total += len(place)

        deployment_place_ready = (not self.deployment_paused
                                  and not self.deployment_failed
                                  and not canary_state)
        if deployment_place_ready:
            desired.place += len(place)
            self.result.place.extend(place)
            self._mark_stop(reschedule_now, "", ALLOC_RESCHEDULED)
            desired.stop += len(reschedule_now)
            limit -= min(len(place), limit)
        else:
            if len(lost) != 0:
                allowed = min(len(lost), len(place))
                desired.place += allowed
                self.result.place.extend(place[:allowed])
            if len(reschedule_now) != 0:
                for p in place:
                    prev = p.previous_alloc
                    if p.reschedule and not (
                            self.deployment_failed and prev is not None
                            and self.deployment is not None
                            and self.deployment.id == prev.deployment_id):
                        self.result.place.append(p)
                        desired.place += 1
                        self.result.stop.append(AllocStopResult(
                            alloc=prev, status_description=ALLOC_RESCHEDULED))
                        desired.stop += 1

        if deployment_place_ready:
            n = min(len(destructive), limit)
            desired.destructive_update += n
            desired.ignore += len(destructive) - n
            for alloc in name_order(destructive)[:n]:
                self.result.destructive_update.append(AllocDestructiveResult(
                    place_name=alloc.name, place_task_group=tg,
                    stop_alloc=alloc,
                    stop_status_description=ALLOC_UPDATING))
        else:
            desired.ignore += len(destructive)

        desired.migrate += len(migrate)
        for alloc in name_order(migrate):
            is_canary = (alloc.deployment_status is not None
                         and alloc.deployment_status.canary)
            self.result.stop.append(AllocStopResult(
                alloc=alloc, status_description=ALLOC_MIGRATING))
            self.result.place.append(AllocPlaceResult(
                name=alloc.name, canary=is_canary, task_group=tg,
                previous_alloc=alloc,
                downgrade_non_canary=canary_state and not is_canary,
                min_job_version=alloc.job.version if alloc.job else 0))

        # Create a deployment if the spec is updating or first run
        updating_spec = len(destructive) != 0 or len(self.result.inplace_update) != 0
        had_running = self._had_running(all_set)
        if (not existing_deployment and strategy is not None
                and dstate.desired_total != 0
                and (not had_running or updating_spec)):
            if self.deployment is None:
                self.deployment = Deployment.from_job(self.job)
                self.result.deployment = self.deployment
            self.deployment.task_groups[group] = dstate

        deployment_complete = (
            len(destructive) + len(inplace) + len(place) + len(migrate)
            + len(reschedule_now) + len(reschedule_later) == 0
            and not require_canary)
        if deployment_complete and self.deployment is not None:
            ds = self.deployment.task_groups.get(group)
            if ds is not None:
                if (ds.healthy_allocs < max(ds.desired_total, ds.desired_canaries)
                        or (ds.desired_canaries > 0 and not ds.promoted)):
                    deployment_complete = False
        return deployment_complete

    # -- helpers -------------------------------------------------------
    def _filter_old_terminal_allocs(self, all_set: AllocSet):
        """(filtered_set, n_ignored) — only the count is consumed."""
        if not self.batch:
            return all_set, 0
        filtered = dict(all_set)
        n = 0
        for aid, alloc in list(filtered.items()):
            older = (alloc.job is not None
                     and (alloc.job.version < self.job.version
                          or alloc.job.create_index < self.job.create_index))
            if older and alloc.terminal_status():
                del filtered[aid]
                n += 1
        return filtered, n

    def _handle_group_canaries(self, all_set: AllocSet,
                               desired: DesiredUpdates):
        stop_ids: List[str] = []
        if self.old_deployment is not None:
            for ds in self.old_deployment.task_groups.values():
                if not ds.promoted:
                    stop_ids.extend(ds.placed_canaries)
        if (self.deployment is not None
                and self.deployment.status == DEPLOYMENT_STATUS_FAILED):
            for ds in self.deployment.task_groups.values():
                if not ds.promoted:
                    stop_ids.extend(ds.placed_canaries)
        stop_set = from_keys(all_set, stop_ids)
        self._mark_stop(stop_set, "", ALLOC_NOT_NEEDED)
        desired.stop += len(stop_set)
        all_set = difference(all_set, stop_set)

        canaries: AllocSet = {}
        if self.deployment is not None:
            canary_ids: List[str] = []
            for ds in self.deployment.task_groups.values():
                canary_ids.extend(ds.placed_canaries)
            canaries = from_keys(all_set, canary_ids)
            untainted, migrate, lost = self._filter_tainted(canaries)
            self._mark_stop(migrate, "", ALLOC_MIGRATING)
            self._mark_stop(lost, ALLOC_CLIENT_LOST, ALLOC_LOST)
            canaries = untainted
            all_set = difference(all_set, migrate, lost)
        return canaries, all_set

    def _compute_limit(self, tg: TaskGroup, untainted: AllocSet,
                       destructive: AllocSet, migrate: AllocSet,
                       canary_state: bool) -> int:
        if (tg.update is None or tg.update.is_empty()
                or len(destructive) + len(migrate) == 0):
            return tg.count
        if self.deployment_paused or self.deployment_failed:
            return 0
        if canary_state:
            return 0
        limit = tg.update.max_parallel
        if self.deployment is not None:
            any_unhealthy, n_not_healthy = self._deployment_health(
                untainted, self.deployment.id)
            if any_unhealthy:
                return 0
            limit -= n_not_healthy
        return max(limit, 0)

    def _compute_placements(self, tg: TaskGroup, name_index: AllocNameIndex,
                            untainted: AllocSet, migrate: AllocSet,
                            reschedule: AllocSet,
                            canary_state: bool) -> List[AllocPlaceResult]:
        place: List[AllocPlaceResult] = []
        for alloc in reschedule.values():
            is_canary = (alloc.deployment_status is not None
                         and alloc.deployment_status.canary)
            place.append(AllocPlaceResult(
                name=alloc.name, task_group=tg, previous_alloc=alloc,
                reschedule=True, canary=is_canary,
                downgrade_non_canary=canary_state and not is_canary,
                min_job_version=alloc.job.version if alloc.job else 0))
        existing = len(untainted) + len(migrate) + len(reschedule)
        if existing < tg.count:
            for name in name_index.next(tg.count - existing):
                place.append(AllocPlaceResult(
                    name=name, task_group=tg,
                    downgrade_non_canary=canary_state))
        return place

    def _compute_stop(self, tg: TaskGroup, name_index: AllocNameIndex,
                      untainted: AllocSet, migrate: AllocSet, lost: AllocSet,
                      canaries: AllocSet, canary_state: bool,
                      followup_evals: Dict[str, str]) -> AllocSet:
        stop: AllocSet = dict(lost)
        self._mark_stop(lost, ALLOC_CLIENT_LOST, ALLOC_LOST, followup_evals)

        if canary_state:
            untainted = difference(untainted, canaries)

        remove = len(untainted) + len(migrate) - tg.count
        if remove <= 0:
            return stop

        untainted = self._filter_terminal(untainted)

        if not canary_state and len(canaries) != 0:
            canary_names = name_set(canaries)
            for aid, alloc in list(difference(untainted, canaries).items()):
                if alloc.name in canary_names:
                    stop[aid] = alloc
                    self.result.stop.append(AllocStopResult(
                        alloc=alloc, status_description=ALLOC_NOT_NEEDED))
                    untainted.pop(aid, None)
                    remove -= 1
                    if remove == 0:
                        return stop

        if len(migrate) != 0:
            m_names = AllocNameIndex(self.job_id, tg.name, tg.count, migrate)
            remove_names = m_names.highest(remove)
            for aid, alloc in list(migrate.items()):
                if alloc.name not in remove_names:
                    continue
                self.result.stop.append(AllocStopResult(
                    alloc=alloc, status_description=ALLOC_NOT_NEEDED))
                del migrate[aid]
                stop[aid] = alloc
                name_index.unset_index(alloc.index())
                remove -= 1
                if remove == 0:
                    return stop

        remove_names = name_index.highest(remove)
        for aid, alloc in list(untainted.items()):
            if alloc.name in remove_names:
                stop[aid] = alloc
                self.result.stop.append(AllocStopResult(
                    alloc=alloc, status_description=ALLOC_NOT_NEEDED))
                del untainted[aid]
                remove -= 1
                if remove == 0:
                    return stop

        for aid, alloc in list(untainted.items()):
            stop[aid] = alloc
            self.result.stop.append(AllocStopResult(
                alloc=alloc, status_description=ALLOC_NOT_NEEDED))
            del untainted[aid]
            remove -= 1
            if remove == 0:
                return stop
        return stop

    def _compute_updates(self, tg: TaskGroup, untainted: AllocSet):
        ignore: AllocSet = {}
        inplace: AllocSet = {}
        destructive: AllocSet = {}
        for alloc in untainted.values():
            ignore_change, destructive_change, updated = self.alloc_update_fn(
                alloc, self.job, tg)
            if ignore_change:
                ignore[alloc.id] = alloc
            elif destructive_change:
                destructive[alloc.id] = alloc
            else:
                inplace[alloc.id] = alloc
                if updated is not None:
                    self.result.inplace_update.append(updated)
        return ignore, inplace, destructive

    def _handle_delayed_reschedules(self, later: List[DelayedRescheduleInfo],
                                    all_set: AllocSet,
                                    tg_name: str) -> Dict[str, str]:
        mapping = self._handle_delayed_lost(later, all_set, tg_name)
        for alloc_id, eval_id in mapping.items():
            existing = all_set.get(alloc_id)
            if existing is None:
                continue
            updated = existing.copy()
            updated.follow_up_eval_id = eval_id
            self.result.attribute_updates[alloc_id] = updated
        return mapping

    def _handle_delayed_lost(self, later: List[DelayedRescheduleInfo],
                             all_set: AllocSet,
                             tg_name: str) -> Dict[str, str]:
        if not later:
            return {}
        later = sorted(later, key=lambda i: i.reschedule_time)
        evals: List[Evaluation] = []
        next_time = later[0].reschedule_time
        mapping: Dict[str, str] = {}
        ev = Evaluation(
            namespace=self.job.namespace, priority=self.job.priority,
            type=self.job.type, triggered_by=TRIGGER_RETRY_FAILED_ALLOC,
            job_id=self.job.id, job_modify_index=self.job.modify_index,
            status=EVAL_STATUS_PENDING,
            status_description=RESCHEDULING_FOLLOWUP_EVAL_DESC,
            wait_until=next_time)
        evals.append(ev)
        for info in later:
            if info.reschedule_time - next_time < ru.BATCHED_FAILED_ALLOC_WINDOW_S:
                mapping[info.alloc_id] = ev.id
            else:
                next_time = info.reschedule_time
                ev = Evaluation(
                    namespace=self.job.namespace, priority=self.job.priority,
                    type=self.job.type,
                    triggered_by=TRIGGER_RETRY_FAILED_ALLOC,
                    job_id=self.job.id,
                    job_modify_index=self.job.modify_index,
                    status=EVAL_STATUS_PENDING, wait_until=next_time)
                evals.append(ev)
                mapping[info.alloc_id] = ev.id
        self.result.desired_followup_evals.setdefault(tg_name, []).extend(evals)
        return mapping
