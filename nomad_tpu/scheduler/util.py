"""Scheduler utilities (reference: scheduler/util.go).

tasksUpdated:351, taintedNodes:312, readyNodesInDCs:233,
updateNonTerminalAllocsToLost:898, adjustQueuedAllocations:869.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..models import (
    Allocation, Job, Node, PlanResult, TaskGroup,
    ALLOC_CLIENT_LOST, ALLOC_DESIRED_EVICT, ALLOC_DESIRED_STOP,
    NODE_STATUS_DOWN,
)
from ..utils.codec import to_wire


def tainted_nodes(snapshot, allocs: List[Allocation]) -> Dict[str, Optional[Node]]:
    """Map of nodes that are tainted for the allocs (util.go:312):
    down/draining/ineligible nodes, or missing (GC'd -> None)."""
    out: Dict[str, Optional[Node]] = {}
    for alloc in allocs:
        if alloc.node_id in out:
            continue
        node = snapshot.node_by_id(alloc.node_id)
        if node is None:
            out[alloc.node_id] = None
            continue
        if node.drain or node.status == NODE_STATUS_DOWN:
            out[alloc.node_id] = node
    return out


def tainted_nodes_columnar(snapshot, cols) -> Dict[str, Optional[Node]]:
    """tainted_nodes over the columnar alloc index: one node lookup per
    DISTINCT node instead of one per alloc (a 10k-alloc job on 1k
    nodes pays 1k lookups)."""
    out: Dict[str, Optional[Node]] = {}
    for code in np.unique(cols.node_code[:cols.n]).tolist():
        nid = cols.node_ids[code]
        node = snapshot.node_by_id(nid)
        if node is None:
            out[nid] = None
        elif node.drain or node.status == NODE_STATUS_DOWN:
            out[nid] = node
    return out


def update_non_terminal_allocs_to_lost_columnar(plan, tainted, cols) -> None:
    """update_non_terminal_allocs_to_lost as a mask: qualifying rows
    (down/GC'd node, desired stop/evict, client running/pending) are
    flagged vectorized and only those touch Python."""
    if not tainted:
        return
    down_codes = np.zeros(len(cols.node_ids), dtype=bool)
    any_down = False
    for nid, node in tainted.items():
        if node is not None and node.status != NODE_STATUS_DOWN:
            continue
        code = cols.node_of.get(nid)
        if code is not None:
            down_codes[code] = True
            any_down = True
    if not any_down:
        return
    n = cols.n
    # client codes 0/1 = pending/running (state/alloc_index.py)
    mask = (down_codes[cols.node_code[:n]] & (cols.desired[:n] > 0)
            & (cols.client[:n] <= 1) & (cols.client[:n] >= 0))
    for r in np.nonzero(mask)[0].tolist():
        plan.append_stopped_alloc(
            cols.allocs[r], "alloc is lost since its node is down",
            ALLOC_CLIENT_LOST)


def _networks_wire(networks) -> list:
    out = []
    for nw in networks:
        out.append({
            "mode": nw.mode, "mbits": nw.mbits,
            "reserved": sorted((p.label, p.value, p.to) for p in nw.reserved_ports),
            "dynamic": sorted((p.label, p.to) for p in nw.dynamic_ports),
        })
    return out


def tasks_updated(job_a: Job, job_b: Job, group: str) -> bool:
    """Whether the group requires a destructive update (util.go:351)."""
    a = job_a.lookup_task_group(group)
    b = job_b.lookup_task_group(group)
    if a is None or b is None:
        return True
    if len(a.tasks) != len(b.tasks):
        return True
    if to_wire(a.ephemeral_disk) != to_wire(b.ephemeral_disk):
        return True
    if _networks_wire(a.networks) != _networks_wire(b.networks):
        return True
    # affinities/spreads at job+tg+task level
    aff_a = [x.key() for x in
             list(job_a.affinities) + list(a.affinities)
             + [af for t in a.tasks for af in t.affinities]]
    aff_b = [x.key() for x in
             list(job_b.affinities) + list(b.affinities)
             + [af for t in b.tasks for af in t.affinities]]
    if aff_a != aff_b:
        return True
    spread_a = [to_wire(s) for s in list(job_a.spreads) + list(a.spreads)]
    spread_b = [to_wire(s) for s in list(job_b.spreads) + list(b.spreads)]
    if spread_a != spread_b:
        return True
    for at in a.tasks:
        bt = b.lookup_task(at.name)
        if bt is None:
            return True
        if at.driver != bt.driver or at.user != bt.user:
            return True
        if at.config != bt.config or at.env != bt.env:
            return True
        if to_wire(at.artifacts) != to_wire(bt.artifacts):
            return True
        if to_wire(at.vault) != to_wire(bt.vault):
            return True
        if to_wire(at.templates) != to_wire(bt.templates):
            return True
        meta_a = {**job_a.meta, **a.meta, **at.meta}
        meta_b = {**job_b.meta, **b.meta, **bt.meta}
        if meta_a != meta_b:
            return True
        if _networks_wire(at.resources.networks) != _networks_wire(bt.resources.networks):
            return True
        if (at.resources.cpu != bt.resources.cpu
                or at.resources.memory_mb != bt.resources.memory_mb):
            return True
        if to_wire(at.resources.devices) != to_wire(bt.resources.devices):
            return True
    return False


def update_non_terminal_allocs_to_lost(plan, tainted: Dict[str, Optional[Node]],
                                       allocs: List[Allocation]) -> None:
    """On down nodes, mark non-terminal allocs lost (util.go:898)."""
    for alloc in allocs:
        node = tainted.get(alloc.node_id, "absent")
        if node == "absent":
            continue
        if node is not None and node.status != NODE_STATUS_DOWN:
            continue
        if alloc.desired_status in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT) and \
                alloc.client_status in ("running", "pending"):
            plan.append_stopped_alloc(alloc, "alloc is lost since its node is down",
                                      ALLOC_CLIENT_LOST)


def adjust_queued_allocations(result: Optional[PlanResult],
                              queued: Dict[str, int]) -> None:
    """Subtract actually-placed allocs from the queued counts (util.go:869)."""
    if result is None:
        return
    for allocs in result.node_allocation.values():
        for alloc in allocs:
            if alloc.create_index != result.alloc_index:
                continue
            if alloc.task_group in queued:
                queued[alloc.task_group] -= 1
                if queued[alloc.task_group] <= 0:
                    del queued[alloc.task_group]
