"""Scheduler interfaces and factory registry.

Reference semantics: scheduler/scheduler.go:23-131 — BuiltinSchedulers
factory map, Scheduler/State/Planner interfaces, SchedulerVersion gate.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Protocol

from ..models import Allocation, Evaluation, Node, Plan, PlanResult

SCHEDULER_VERSION = 1


class SchedulerState(Protocol):
    """Immutable snapshot view the scheduler reads (scheduler.go State)."""

    def nodes(self) -> List[Node]: ...
    def node_by_id(self, node_id: str) -> Optional[Node]: ...
    def job_by_id(self, namespace: str, job_id: str): ...
    def allocs_by_job(self, namespace: str, job_id: str) -> List[Allocation]: ...
    def allocs_by_node(self, node_id: str) -> List[Allocation]: ...
    def latest_deployment_by_job(self, namespace: str, job_id: str): ...
    def scheduler_config(self): ...


class Planner(Protocol):
    """How the scheduler effects change (scheduler.go Planner)."""

    def submit_plan(self, plan: Plan): ...
    def update_eval(self, evaluation: Evaluation) -> None: ...
    def create_eval(self, evaluation: Evaluation) -> None: ...
    def reblock_eval(self, evaluation: Evaluation) -> None: ...


class Scheduler(Protocol):
    def process(self, evaluation: Evaluation) -> None: ...


SchedulerFactory = Callable[[SchedulerState, Planner], Scheduler]


def _service(state, planner):
    from .generic import GenericScheduler
    return GenericScheduler(state, planner, batch=False)


def _batch(state, planner):
    from .generic import GenericScheduler
    return GenericScheduler(state, planner, batch=True)


def _system(state, planner):
    from .system import SystemScheduler
    return SystemScheduler(state, planner)


BUILTIN_SCHEDULERS: Dict[str, SchedulerFactory] = {
    "service": _service,
    "batch": _batch,
    "system": _system,
    # the device-batched pipeline IS the default execution backend; the
    # explicit name is kept for the reference's registration parity
    # (BASELINE.json north star: a `tpu-batch` Factory entry)
    "tpu-batch": _batch,
}


def new_scheduler(name: str, state: SchedulerState, planner: Planner) -> Scheduler:
    factory = BUILTIN_SCHEDULERS.get(name)
    if factory is None:
        raise ValueError(f"unknown scheduler '{name}'")
    return factory(state, planner)
