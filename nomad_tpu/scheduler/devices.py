"""Device scheduling: feasibility columns, slot accounting, affinity
scoring, and instance assignment.

Reference semantics:
  - DeviceChecker (scheduler/feasible.go:1138): a node is feasible when
    every requested device has a matching group with enough HEALTHY
    instances satisfying the request's constraints (a capability check,
    independent of current usage).
  - deviceAllocator.AssignDevice (scheduler/device.go:32): pick the
    highest-affinity matching group with enough FREE instances and
    reserve concrete instance IDs.
  - BinPack device scoring (scheduler/rank.go:456-461): the "devices"
    scorer fires whenever any ask carries affinities; its value is
    sum(matched weights of chosen groups) / sum(|weights| of all asks).

Columnar mapping: the capability mask is a static column cached with
the other feasibility checks; current usage collapses into one
"placement slots" column (min over asks of free-matching-instances //
ask.count) the kernel decrements per placement; the affinity score is a
per-node column fed to the kernel as an additional scorer. Concrete
instance IDs are assigned host-side for winners only, mirroring the
port-assignment split (SURVEY.md §7.3 item 1).
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..models import AllocatedDeviceResource, Node, RequestedDevice
from ..models.constraints import Constraint
from ..models.device_accounting import DeviceAccounter
from ..ops.targets import (_check_set_contains_all,
                           _check_set_contains_any, _regex)
from ..ops.versions import version_matches
from ..plugins.psstructs import compare_values

_DEV_TARGET = re.compile(r"^\$\{device\.(.+)\}$")


def combined_device_asks(tg) -> List[RequestedDevice]:
    """All device requests of a task group's tasks, in task order."""
    out: List[RequestedDevice] = []
    for t in tg.tasks:
        out.extend(t.resources.devices)
    return out


def resolve_device_target(target: str, group) -> Tuple[Optional[object], bool]:
    """${device.vendor|type|model|attr.<key>} -> value (device.go
    resolveDeviceTarget). Non-interpolated targets are literals."""
    m = _DEV_TARGET.match(target or "")
    if not m:
        return target, target != ""
    key = m.group(1)
    if key == "vendor":
        return group.vendor, True
    if key == "type":
        return group.type, True
    if key in ("model", "name"):
        return group.name, True
    if key.startswith("attr."):
        v = group.attributes.get(key[len("attr."):])
        return v, v is not None
    return None, False


def _compare(op: str, lval, rval) -> bool:
    """Device-constraint comparison over typed attributes with units
    (feasible.go:1297 checkAttributeConstraint + psstructs
    Attribute.Compare): "500 MiB" vs "1 GiB" converts both sides to
    base bytes; incomparable dimensions fail ordered operators."""
    if op == "is_set":
        return lval is not None
    if op == "is_not_set":
        return lval is None
    if op in ("!=", "not"):
        # nil != nil is false; nil != some is true (handled by caller
        # passing None through); both present -> typed inequality.
        if lval is None and rval is None:
            return False
        if (lval is None) != (rval is None):
            return True
        v, ok = compare_values(lval, rval)
        return ok and v != 0
    if lval is None or rval is None:
        return False
    if op in ("<", "<=", ">", ">=", "=", "==", "is"):
        v, ok = compare_values(lval, rval)
        if not ok:
            return False
        return {"is": v == 0, "==": v == 0, "=": v == 0,
                "<": v == -1, "<=": v != 1,
                ">": v == 1, ">=": v != -1}[op]
    if op == "version":
        return version_matches(str(lval), str(rval))
    if op == "semver":
        return version_matches(str(lval), str(rval), strict_semver=True)
    if op == "regexp":
        # cached compile; invalid user patterns mean "no match", not a
        # crashed eval (same contract as the node-constraint engine)
        pat = _regex(str(rval))
        return pat is not None and pat.search(str(lval)) is not None
    if op in ("set_contains", "set_contains_all"):
        return _check_set_contains_all(str(lval), str(rval))
    if op == "set_contains_any":
        return _check_set_contains_any(str(lval), str(rval))
    return False


def group_satisfies(group, req: RequestedDevice) -> bool:
    """Name match + constraint checks (feasible.go nodeDeviceMatches)."""
    if not group.matches_request(req):
        return False
    for c in req.constraints:
        lval, lok = resolve_device_target(c.ltarget, group)
        rval, rok = resolve_device_target(c.rtarget, group)
        if c.operand == "is_set":
            if not lok:
                return False
            continue
        if c.operand == "is_not_set":
            if lok:
                return False
            continue
        if c.operand in ("!=", "not"):
            if not _compare(c.operand, lval if lok else None,
                            rval if rok else None):
                return False
            continue
        if not lok or not rok:
            return False
        if not _compare(c.operand, lval, rval):
            return False
    return True


def group_affinity_score(group, req: RequestedDevice) -> Tuple[float, float]:
    """(choice score used to pick among groups, matched weights
    contributed to the node's 'devices' scorer) — device.go:74-96."""
    if not req.affinities:
        return 0.0, 0.0
    total = 0.0
    choice = 0.0
    matched = 0.0
    for a in req.affinities:
        total += abs(float(a.weight))
        lval, lok = resolve_device_target(a.ltarget, group)
        rval, rok = resolve_device_target(a.rtarget, group)
        if not lok or not rok:
            continue
        if _compare(a.operand, lval, rval):
            choice += float(a.weight)
            matched += float(a.weight)
    if total > 0:
        choice /= total
    return choice, matched


def total_affinity_weight(asks: List[RequestedDevice]) -> float:
    return sum(abs(float(a.weight))
               for req in asks for a in req.affinities)


def node_device_ok(node: Node, asks: List[RequestedDevice]) -> bool:
    """One node's DeviceChecker verdict: every ask has a satisfying
    group with enough healthy instances. The scalar row twin the
    flagged-row check (feasible_compiler.device_rows_check) evaluates
    over device-reporting rows only."""
    groups = node.node_resources.devices
    for req in asks:
        ok = False
        for g in groups:
            if not group_satisfies(g, req):
                continue
            healthy = sum(1 for inst in g.instances if inst.healthy)
            if healthy >= req.count:
                ok = True
                break
        if not ok:
            return False
    return True


def static_device_mask(nodes: List[Node],
                       asks: List[RequestedDevice]) -> np.ndarray:
    """DeviceChecker capability mask: every ask has a satisfying group
    with enough healthy instances (usage-independent, cacheable)."""
    return np.fromiter((node_device_ok(node, asks) for node in nodes),
                       dtype=bool, count=len(nodes))


def free_instance_counts(node: Node, allocs) -> Dict[Tuple, int]:
    """(vendor, type, name) -> free healthy instances given allocs."""
    acct = DeviceAccounter(node)
    acct.add_allocs(allocs)
    return {gid: len(acct.free_instances(gid))
            for gid in acct.devices}


def device_columns(nodes: List[Node], asks: List[RequestedDevice],
                   allocs_for_node) -> Tuple[np.ndarray, np.ndarray, bool]:
    """Per-eval device columns for the kernel:
      slots[N]  — placements of this task group the node can still hold
                  (min over asks of free-matching // count); nodes with
                  no device asks get +inf
      score[N]  — the 'devices' scorer value per node
      fires     — True when any ask has affinities (rank.go:457)
    `allocs_for_node(node_id)` yields the proposed allocs to account.
    """
    n = len(nodes)
    slots = np.full(n, np.inf, dtype=np.float32)
    score = np.zeros(n, dtype=np.float32)
    if not asks:
        return slots, score, False
    total_w = total_affinity_weight(asks)
    for i, node in enumerate(nodes):
        groups = node.node_resources.devices
        if not groups:
            slots[i] = 0.0
            continue
        free = free_instance_counts(node, allocs_for_node(node.id))
        node_slots = np.inf
        matched_sum = 0.0
        for req in asks:
            best: Optional[Tuple[float, float, int]] = None
            for g in groups:
                if not group_satisfies(g, req):
                    continue
                f = free.get(g.id_tuple(), 0)
                if f < req.count:
                    continue
                choice, matched = group_affinity_score(g, req)
                if best is None or choice > best[0]:
                    best = (choice, matched, f)
            if best is None:
                node_slots = 0.0
                break
            node_slots = min(node_slots, best[2] // max(req.count, 1))
            matched_sum += best[1]
        slots[i] = node_slots
        if total_w > 0 and node_slots > 0:
            score[i] = matched_sum / total_w
    return slots, score, total_w > 0


def assign_devices(node: Node, tg, allocs,
                   acct: Optional[DeviceAccounter] = None) -> Tuple[
        Optional[Dict[str, List[AllocatedDeviceResource]]], float]:
    """Concrete instance assignment for a winning node: per task, per
    request, pick the best-scoring matching group with enough free
    instances and reserve IDs (device.go AssignDevice + AddReserved).
    Pass a shared accounter so successive placements within one eval
    see each other's reservations (the plan only carries them after
    select_batch returns). Returns (task -> offers, matched-weights
    sum) or (None, 0)."""
    if acct is None:
        acct = DeviceAccounter(node)
        acct.add_allocs(allocs)
    out: Dict[str, List[AllocatedDeviceResource]] = {}
    matched_sum = 0.0
    for task in tg.tasks:
        offers: List[AllocatedDeviceResource] = []
        for req in task.resources.devices:
            best = None       # (choice_score, matched, group, free_ids)
            for g in node.node_resources.devices:
                if not group_satisfies(g, req):
                    continue
                free_ids = acct.free_instances(g.id_tuple())
                if len(free_ids) < req.count:
                    continue
                choice, matched = group_affinity_score(g, req)
                if best is None or choice > best[0]:
                    best = (choice, matched, g, free_ids)
            if best is None:
                return None, 0.0
            _choice, matched, g, free_ids = best
            offer = AllocatedDeviceResource(
                vendor=g.vendor, type=g.type, name=g.name,
                device_ids=list(free_ids[:req.count]))
            acct.add_reserved(offer)
            offers.append(offer)
            matched_sum += matched
        if offers:
            out[task.name] = offers
    return out, matched_sum
