"""Host-side schedulers.

The control flow (reconciliation, deployments, retries) mirrors the
reference's scheduler/ package; placement decisions are delegated to the
batched device kernels in nomad_tpu/ops.

Factory registry mirrors scheduler/scheduler.go:23-44; the TPU pipeline
is the default execution backend for every scheduler type (the
"tpu-batch" scheduler of BASELINE.json is the native mode here, not a
bolt-on).
"""

from .scheduler import (Scheduler, SchedulerState, Planner, new_scheduler,
                        BUILTIN_SCHEDULERS)
from .generic import GenericScheduler
from .system import SystemScheduler
from .harness import Harness
